"""Build integration for the native pipeline libraries.

The reference builds ~500k LoC of C++ into libmxnet.so via CMake
([U:CMakeLists.txt]); here the native surface is two small shared
libraries (RecordIO/JPEG pipeline, XLA-FFI custom-op demo) built from
``native/`` by ``make``.  ``python setup.py build_native`` compiles them
and stages sources + binaries into ``incubator_mxnet_tpu/_native/`` so a
wheel carries them; at runtime ``io/record_iter.py`` searches the
package-internal ``_native/`` first, then the repo-layout ``native/``
(building lazily when only sources are present).
"""
import os
import shutil
import subprocess

from setuptools import Command, setup
from setuptools.command.build import build as _build


class BuildNative(Command):
    description = "build native/*.so via make and stage into the package"
    user_options = []

    def initialize_options(self):
        pass

    def finalize_options(self):
        pass

    def run(self):
        root = os.path.dirname(os.path.abspath(__file__))
        native = os.path.join(root, "native")
        if not os.path.isdir(native):
            print("build_native: no native/ sources found; skipping")
            return
        dest = os.path.join(root, "incubator_mxnet_tpu", "_native")
        os.makedirs(dest, exist_ok=True)
        # drop any stale staged binaries (older build_native versions
        # copied .so files here; they would shadow newer sources)
        for f in os.listdir(dest):
            if f.endswith(".so"):
                os.remove(os.path.join(dest, f))
        # Stage SOURCES only — the wheel stays py3-none-any; the runtime
        # builds for the host lazily (and degrades to the pure-Python
        # pipeline when no toolchain is available, same as a failed make)
        for f in os.listdir(native):
            if f.endswith(".cpp") or f == "Makefile":
                shutil.copy2(os.path.join(native, f), os.path.join(dest, f))
        # best-effort compile so in-tree builds are ready immediately; a
        # missing toolchain/libjpeg must not fail the install
        r = subprocess.run(["make", "-C", native, "libmxtpu_io.so"],
                           check=False)
        if r.returncode != 0:
            print("build_native: make failed (no toolchain/libjpeg?) — "
                  "runtime will fall back to the pure-Python pipeline")
        subprocess.run(["make", "-C", native, "libmxtpu_custom_op.so"],
                       check=False)
        print(f"staged native sources into {dest}")


class Build(_build):
    # stage native artifacts in every standard build, so `pip install .` /
    # `pip wheel .` wheels actually contain _native/ (the package-data
    # globs in pyproject.toml)
    sub_commands = [("build_native", None)] + _build.sub_commands


setup(cmdclass={"build_native": BuildNative, "build": Build})
