"""TPU-backend correctness tier — runs against the REAL chip.

The reference's main device-backend oracle is rerunning the op suite under
the accelerator context and cross-comparing with CPU
([U:tests/python/gpu/test_operator_gpu.py] + check_consistency).  This
tier is the TPU analog.  It is intentionally OUTSIDE tests/ (whose
conftest pins everything to a virtual CPU mesh):

    MXNET_TEST_CTX=tpu python -m pytest tpu_tests/ -q

Skipped wholesale unless MXNET_TEST_CTX=tpu AND an accelerator is
actually visible — the tunneled chip is a shared, wedgable resource, so
opting in must be explicit.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_collection_modifyitems(config, items):
    if os.environ.get("MXNET_TEST_CTX") != "tpu":
        skip = pytest.mark.skip(reason="set MXNET_TEST_CTX=tpu to run the real-chip tier")
        for item in items:
            item.add_marker(skip)
        return
    import jax

    if not any(d.platform != "cpu" for d in jax.local_devices()):
        skip = pytest.mark.skip(reason="no accelerator device visible")
        for item in items:
            item.add_marker(skip)
