"""cpu-vs-tpu correctness for the core op surface + on-hardware Pallas
flash attention + AMP bf16 numerics + a small train-to-accuracy.

Parity: [U:tests/python/gpu/test_operator_gpu.py]'s rerun-under-ctx
pattern, with ``check_consistency`` (utils/test_utils.py) as the oracle —
jax-CPU is the reference backend, the tunneled TPU the device under test.

Tolerances: TPU fp32 matmuls run through the MXU with fp32 accumulate but
bf16-precision multiplies unless precision=HIGHEST; the package pins
highest by default, so most ops compare at tight tolerance.  Ops with
reductions get a slightly looser bound.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.utils.test_utils import check_consistency

RNG = np.random.RandomState(7)


def _r(*shape):
    return RNG.randn(*shape).astype(np.float32)


def _p(*shape):
    return np.abs(RNG.randn(*shape)).astype(np.float32) + 0.5


# ---------------------------------------------------------------------------
# ~30 core ops, forward + gradient, cpu-vs-tpu
# ---------------------------------------------------------------------------

ELEMWISE_CASES = [
    ("add", lambda a, b: a + b, [_r(4, 5), _r(4, 5)], None),
    ("sub", lambda a, b: a - b, [_r(4, 5), _r(4, 5)], None),
    ("mul", lambda a, b: a * b, [_r(4, 5), _r(4, 5)], None),
    ("div", lambda a, b: a / b, [_r(4, 5), _p(4, 5)], None),
    ("exp", lambda a: mx.nd.exp(a), [_r(3, 4)], None),
    # TPU transcendental units round differently from the CPU libm path:
    # log/log_softmax observed at ~1.6e-4 rel — still fp32-faithful
    ("log", lambda a: mx.nd.log(a), [_p(3, 4)], "loose"),
    ("sqrt", lambda a: mx.nd.sqrt(a), [_p(3, 4)], None),
    ("square", lambda a: mx.nd.square(a), [_r(3, 4)], None),
    ("tanh", lambda a: mx.nd.tanh(a), [_r(3, 4)], None),
    ("sigmoid", lambda a: mx.nd.sigmoid(a), [_r(3, 4)], None),
    ("relu", lambda a: mx.nd.relu(a), [_r(3, 4)], None),
    ("leaky_relu", lambda a: mx.nd.LeakyReLU(a, act_type="leaky"), [_r(3, 4)], None),
    ("gelu", lambda a: mx.nd.LeakyReLU(a, act_type="gelu"), [_r(3, 4)], None),
    ("clip", lambda a: mx.nd.clip(a, -0.5, 0.5), [_r(3, 4)], None),
    ("maximum", lambda a, b: mx.nd.maximum(a, b), [_r(3, 4), _r(3, 4)], None),
    ("where", lambda c, a, b: mx.nd.where(c > 0, a, b), [_r(3, 4), _r(3, 4), _r(3, 4)], None),
    ("sum", lambda a: mx.nd.sum(a, axis=1), [_r(4, 6)], None),
    ("mean", lambda a: mx.nd.mean(a, axis=0), [_r(4, 6)], None),
    ("max", lambda a: mx.nd.max(a, axis=1), [_r(4, 6)], None),
    ("argmax-fwd", lambda a: mx.nd.argmax(a, axis=1), [_r(4, 6)], "nograd"),
    ("transpose", lambda a: mx.nd.transpose(a, axes=(1, 0, 2)), [_r(2, 3, 4)], None),
    ("reshape", lambda a: a.reshape((6, 4)), [_r(2, 3, 4)], None),
    ("concat", lambda a, b: mx.nd.concat(a, b, dim=1), [_r(3, 2), _r(3, 5)], None),
    ("slice", lambda a: mx.nd.slice_axis(a, axis=1, begin=1, end=3), [_r(4, 5)], None),
    ("softmax", lambda a: mx.nd.softmax(a), [_r(4, 7)], None),
    ("log_softmax", lambda a: mx.nd.log_softmax(a), [_r(4, 7)], "loose"),
    ("dot", lambda a, b: mx.nd.dot(a, b), [_r(4, 6), _r(6, 5)], None),
    ("batch_dot", lambda a, b: mx.nd.batch_dot(a, b), [_r(2, 3, 4), _r(2, 4, 5)], None),
    ("broadcast_add", lambda a, b: mx.nd.broadcast_add(a, b), [_r(4, 5), _r(1, 5)], None),
    ("norm", lambda a: mx.nd.norm(a), [_r(4, 5)], None),
]


@pytest.mark.parametrize("name,fn,inputs,mode", ELEMWISE_CASES,
                         ids=[c[0] for c in ELEMWISE_CASES])
def test_core_op_cpu_vs_tpu(name, fn, inputs, mode):
    tol = 1e-3 if mode == "loose" else 2e-5
    check_consistency(fn, inputs, rtol=tol, atol=tol, grad=(mode != "nograd"))


def test_fully_connected_cpu_vs_tpu():
    w, b = _r(8, 12), _r(8)
    check_consistency(
        lambda x, w, b: mx.nd.FullyConnected(x, w, b, num_hidden=8),
        [_r(4, 12), w, b], rtol=1e-4, atol=1e-4)


def test_convolution_cpu_vs_tpu():
    check_consistency(
        lambda x, w, b: mx.nd.Convolution(x, w, b, kernel=(3, 3), num_filter=6, pad=(1, 1)),
        [_r(2, 3, 8, 8), _r(6, 3, 3, 3), _r(6)], rtol=1e-4, atol=1e-4)


def test_pooling_cpu_vs_tpu():
    check_consistency(
        lambda x: mx.nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max"),
        [_r(2, 3, 8, 8)], rtol=1e-5, atol=1e-5)
    check_consistency(
        lambda x: mx.nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg"),
        [_r(2, 3, 8, 8)], rtol=1e-5, atol=1e-5)


def test_batchnorm_layernorm_cpu_vs_tpu():
    c = 5
    check_consistency(
        lambda x, g, b, mm, mv: mx.nd.BatchNorm(x, g, b, mm, mv, fix_gamma=False),
        [_r(4, c, 3, 3), _p(c), _r(c), _r(c), _p(c)], rtol=1e-4, atol=1e-4)
    check_consistency(
        lambda x, g, b: mx.nd.LayerNorm(x, g, b),
        [_r(4, 8), _p(8), _r(8)], rtol=1e-4, atol=1e-4)


def test_embedding_take_cpu_vs_tpu():
    from incubator_mxnet_tpu import autograd

    idx = np.array([[1, 3], [0, 2]], dtype=np.float32)
    check_consistency(
        lambda w: mx.nd.Embedding(mx.nd.array(idx, dtype="int32", ctx=w.context), w,
                                  input_dim=5, output_dim=4),
        [_r(5, 4)], rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# Pallas flash attention ON HARDWARE (the only place the Mosaic kernel
# actually runs; tests/ exercises it in interpret mode only)
# ---------------------------------------------------------------------------


class TestFlashOnChip:
    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_fwd_matches_xla_on_tpu(self, causal, monkeypatch):
        import jax.numpy as jnp
        from incubator_mxnet_tpu.ops import attention as att

        q = jnp.asarray(_r(1, 2, 1024, 64)).astype(jnp.bfloat16)
        k = jnp.asarray(_r(1, 2, 1024, 64)).astype(jnp.bfloat16)
        v = jnp.asarray(_r(1, 2, 1024, 64)).astype(jnp.bfloat16)
        monkeypatch.setenv("MXNET_TPU_FLASH", "on")   # force the kernel
        out = att.flash_attention(q, k, v, causal=causal)
        monkeypatch.setenv("MXNET_TPU_FLASH", "off")  # force XLA reference
        ref = att.attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32),
            rtol=2e-2, atol=2e-2)

    def test_pallas_bwd_matches_xla_on_tpu(self, monkeypatch):
        import jax
        import jax.numpy as jnp
        from incubator_mxnet_tpu.ops import attention as att

        monkeypatch.setenv("MXNET_TPU_FLASH_BWD_MIN_SEQ", "512")
        monkeypatch.setenv("MXNET_TPU_FLASH_FWD_MIN_SEQ", "512")
        # thresholds are read at import; reload-free override via direct attr
        monkeypatch.setattr(att, "_PALLAS_BWD_MIN_SEQ", 512)
        monkeypatch.setattr(att, "_PALLAS_FWD_MIN_SEQ", 512)
        q = jnp.asarray(_r(1, 1, 512, 64)).astype(jnp.bfloat16)

        def loss_flash(x):
            monkeypatch.setenv("MXNET_TPU_FLASH", "on")
            return (att.flash_attention(x, x, x, causal=True) ** 2).sum().astype(jnp.float32)

        g_flash = jax.grad(loss_flash)(q)
        monkeypatch.setenv("MXNET_TPU_FLASH", "off")

        def loss_ref(x):
            return (att.attention_reference(x, x, x, causal=True) ** 2).sum().astype(jnp.float32)

        g_ref = jax.grad(loss_ref)(q)
        np.testing.assert_allclose(
            np.asarray(g_flash, dtype=np.float32), np.asarray(g_ref, dtype=np.float32),
            rtol=5e-2, atol=5e-2)


# ---------------------------------------------------------------------------
# AMP bf16 numerics on the chip
# ---------------------------------------------------------------------------


def test_amp_bf16_matmul_on_tpu():
    from incubator_mxnet_tpu import amp

    x, w = _r(8, 16), _r(4, 16)
    fp32 = mx.nd.FullyConnected(
        mx.nd.array(x, ctx=mx.tpu()), mx.nd.array(w, ctx=mx.tpu()), None,
        num_hidden=4, no_bias=True).asnumpy()
    amp.init("bfloat16")
    try:
        out = mx.nd.FullyConnected(
            mx.nd.array(x, ctx=mx.tpu()), mx.nd.array(w, ctx=mx.tpu()), None,
            num_hidden=4, no_bias=True)
        assert str(out.dtype) == "bfloat16"
        np.testing.assert_allclose(out.asnumpy().astype(np.float32), fp32,
                                   rtol=3e-2, atol=3e-2)
    finally:
        amp.disable()


# ---------------------------------------------------------------------------
# Small train-to-accuracy on the chip (fused SPMD step)
# ---------------------------------------------------------------------------


def test_train_mlp_on_tpu():
    import jax
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

    rng = np.random.RandomState(0)
    n, d = 256, 8
    centers = rng.randn(4, d) * 3
    yb = rng.randint(0, 4, n)
    xb = centers[yb] + rng.randn(n, d) * 0.5

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((2, d)))

    def loss_fn(out, label):
        logits = out._data if hasattr(out, "_data") else out[0]._data
        return NDArray(streaming_softmax_ce(logits, label._data))

    accel = [dev for dev in jax.local_devices() if dev.platform != "cpu"]
    mesh = make_mesh(devices=accel[:1])
    trainer = SPMDTrainer(net, loss_fn, "adam", {"learning_rate": 1e-2}, mesh=mesh)
    xs, ys = trainer.shard_batch(xb.astype(np.float32), yb.astype(np.int32))
    for _ in range(60):
        loss = trainer.step(xs, ys)
    final = float(np.asarray(loss._data))
    trainer.sync_to_block()
    pred = net(mx.nd.array(xb.astype(np.float32))).asnumpy().argmax(axis=1)
    acc = (pred == yb).mean()
    assert acc > 0.9, (acc, final)


# ---------------------------------------------------------------------------
# Round-3 op families on the chip
# ---------------------------------------------------------------------------


def test_quantized_fc_on_tpu():
    """int8 MXU matmul path executes on hardware within int8 tolerance."""
    x = _r(8, 32)
    w = _r(16, 32)
    ctx = mx.tpu()
    xq, xmn, xmx = mx.nd.quantize_v2(mx.nd.array(x, ctx=ctx))
    wq, wmn, wmx = mx.nd.quantize_v2(mx.nd.array(w, ctx=ctx))
    out = mx.nd.quantized_fully_connected(
        xq, wq, None, xmn, xmx, wmn, wmx, num_hidden=16, no_bias=True)
    ref = x @ w.T
    np.testing.assert_allclose(out.asnumpy(), ref, atol=np.abs(ref).max() * 0.05)


def test_control_flow_foreach_on_tpu():
    ctx = mx.tpu()
    data = mx.nd.array(_r(6, 4), ctx=ctx)
    init = mx.nd.array(np.zeros(4, np.float32), ctx=ctx)
    outs, final = mx.nd.contrib.foreach(lambda x, s: (s + x, s + x), data, init)
    np.testing.assert_allclose(final.asnumpy(), data.asnumpy().sum(axis=0),
                               rtol=1e-5, atol=1e-5)


def test_gather_positions_on_tpu():
    ctx = mx.tpu()
    seq = mx.nd.array(_r(2, 8, 4), ctx=ctx)
    pos_np = np.array([[1, 5], [0, 7]], np.int32)
    pos = mx.nd.array(pos_np, ctx=ctx)
    out = mx.nd.gather_positions(seq, pos)
    ref = np.take_along_axis(seq.asnumpy(), pos_np[..., None], axis=1)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)


def test_rtc_pallas_kernel_on_tpu():
    """mx.rtc kernels compile through Mosaic and run on the chip; values
    match the CPU interpret path."""
    import numpy as np

    import incubator_mxnet_tpu as mx

    mod = mx.rtc.PallasModule('''
def scale_add(x_ref, y_ref, o_ref):
    o_ref[...] = 2.0 * x_ref[...] + y_ref[...]
''')
    k = mod.get_kernel("scale_add", out_shapes=[(128, 256)])
    x = np.random.RandomState(0).rand(128, 256).astype(np.float32)
    y = np.random.RandomState(1).rand(128, 256).astype(np.float32)
    z = k.launch([mx.nd.array(x), mx.nd.array(y)])
    np.testing.assert_allclose(z.asnumpy(), 2 * x + y, rtol=1e-6)


# ---------------------------------------------------------------------------
# round-4 op families on the chip (same check_consistency oracle)
# ---------------------------------------------------------------------------


def test_linalg_family_cpu_vs_tpu():
    spd = np.einsum("ij,kj->ik", *(2 * [np.random.RandomState(0).randn(4, 4).astype(np.float32)])) + 4 * np.eye(4, dtype=np.float32)
    check_consistency(lambda a: mx.nd.linalg_potrf(a), [spd], rtol=1e-3, atol=1e-3, grad=False)
    check_consistency(lambda a: mx.nd.linalg_sumlogdiag(
        mx.nd.linalg_potrf(a)), [spd], rtol=1e-3, atol=1e-3, grad=False)
    tri = np.tril(np.random.RandomState(1).randn(4, 4)).astype(np.float32)
    np.fill_diagonal(tri, np.abs(np.diag(tri)) + 2)
    b = np.random.RandomState(2).randn(4, 3).astype(np.float32)
    check_consistency(lambda a, bb: mx.nd.linalg_trsm(a, bb), [tri, b],
                      rtol=1e-3, atol=1e-3)
    check_consistency(lambda a: mx.nd.linalg_extractdiag(a), [tri],
                      rtol=0, atol=0)


def test_ctc_loss_cpu_vs_tpu():
    logits = np.random.RandomState(3).randn(6, 2, 5).astype(np.float32)
    labels = np.array([[1, 2], [3, 0]], np.float32)
    check_consistency(lambda d: mx.nd.CTCLoss(d, mx.nd.array(labels)),
                      [logits], rtol=1e-3, atol=1e-3)


def test_spatial_family_cpu_vs_tpu():
    x = np.random.RandomState(4).rand(1, 2, 8, 8).astype(np.float32)
    rois = np.array([[0, 1, 1, 6, 6]], np.float32)
    check_consistency(
        lambda d: mx.nd.ROIPooling(d, mx.nd.array(rois), pooled_size=(2, 2),
                                   spatial_scale=1.0), [x],
        rtol=1e-3, atol=1e-4)
    check_consistency(
        lambda d: mx.nd._contrib_ROIAlign(d, mx.nd.array(rois),
                                          pooled_size=(2, 2),
                                          spatial_scale=1.0, sample_ratio=2),
        [x], rtol=1e-3, atol=1e-4)
    theta = np.array([[1, 0, 0.2, 0, 1, -0.1]], np.float32)
    check_consistency(
        lambda d, t: mx.nd.SpatialTransformer(d, t, target_shape=(8, 8)),
        [x, theta], rtol=1e-3, atol=1e-4)
    check_consistency(
        lambda d: mx.nd._contrib_AdaptiveAvgPooling2D(d, output_size=(3, 3)),
        [x], rtol=1e-3, atol=1e-4)


def test_new_optimizer_kernels_on_tpu():
    """nadam/ftml/adamax fused kernels on the chip vs the same kernels on
    CPU — the file's cpu-vs-tpu oracle, applied at the kernel level."""
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ops import optimizer_ops as K

    w = np.random.RandomState(5).randn(8, 4).astype(np.float32)
    g = np.random.RandomState(6).randn(8, 4).astype(np.float32)
    z = np.zeros_like(w)
    cpu = jax.local_devices(backend="cpu")[0]
    hyper = [jnp.float32(v) for v in (0.01, 0.0, 1.0, np.inf)]

    def run(kernel, arrays, extra):
        tpu_out = kernel(*[jnp.asarray(a) for a in arrays], *hyper, *extra)
        with jax.default_device(cpu):
            cpu_out = kernel(*[jnp.asarray(a) for a in arrays], *hyper, *extra)
        for t, c in zip(tpu_out, cpu_out):
            np.testing.assert_allclose(np.asarray(t), np.asarray(c),
                                       rtol=2e-3, atol=2e-4)

    run(K.nadam_update, [w, g, z, z, np.ones((), np.float32)],
        [jnp.float32(0.9), jnp.float32(0.999), jnp.float32(1e-8),
         jnp.float32(1), jnp.float32(0.004)])
    run(K.ftml_update, [w, g, z, z, z],
        [jnp.float32(0.6), jnp.float32(0.999), jnp.float32(1e-8),
         jnp.float32(1)])
    run(K.adamax_update, [w, g, z, z],
        [jnp.float32(0.9), jnp.float32(0.999)])


# ---------------------------------------------------------------------------
# round-5 op families on the chip (same check_consistency oracle)
# ---------------------------------------------------------------------------


def test_rnn_megaop_cpu_vs_tpu():
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size

    T, B, C, H = 5, 2, 3, 4
    rng = np.random.RandomState(7)
    x = rng.uniform(-1, 1, (T, B, C)).astype(np.float32)
    for mode, bidir in (("lstm", True), ("gru", False)):
        n = rnn_param_size(mode, C, H, 2, bidir)
        p = rng.uniform(-0.3, 0.3, (n,)).astype(np.float32)
        check_consistency(
            lambda d, pp, _m=mode, _b=bidir: mx.nd.RNN(
                d, pp, mode=_m, state_size=H, num_layers=2, bidirectional=_b),
            [x, p], rtol=1e-3, atol=1e-4)


def test_deformable_ops_cpu_vs_tpu():
    rng = np.random.RandomState(8)
    x = rng.randn(1, 4, 8, 8).astype(np.float32)
    w = rng.randn(6, 4, 3, 3).astype(np.float32)
    off = np.full((1, 18, 8, 8), 0.37, np.float32)
    check_consistency(
        lambda d, ww: mx.nd._contrib_DeformableConvolution(
            d, mx.nd.array(off), ww, kernel=(3, 3), pad=(1, 1), num_filter=6,
            no_bias=True), [x, w], rtol=1e-3, atol=1e-3)
    C = 2 * 2 * 2
    score = rng.randn(1, C, 8, 8).astype(np.float32)
    rois = np.array([[0, 1, 1, 11, 13]], np.float32)
    check_consistency(
        lambda d: mx.nd._contrib_DeformablePSROIPooling(
            d, mx.nd.array(rois), spatial_scale=0.5, output_dim=2,
            group_size=2, pooled_size=2, sample_per_part=2, no_trans=True),
        [score], rtol=1e-3, atol=1e-4)


def test_scalar_special_cpu_vs_tpu():
    x = np.random.RandomState(9).uniform(0.5, 4.0, (16,)).astype(np.float32)
    check_consistency(lambda d: mx.nd.digamma(d), [x], rtol=1e-3, atol=1e-4)
    check_consistency(lambda d: mx.nd.polygamma(d, n=1), [x],
                      rtol=1e-3, atol=1e-3, grad=False)


def test_pallas_fused_bn_on_tpu():
    """The fused BN epilogue COMPILED on the chip (interpret-mode tests
    cover CPU) vs the stock batch_norm op on the same device."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() == "cpu":
        pytest.skip("needs an accelerator backend")
    from incubator_mxnet_tpu.ops.pallas_bn import fused_bn_relu
    from incubator_mxnet_tpu.ops.nn import batch_norm

    rng = np.random.RandomState(10)
    x = jnp.asarray(rng.randn(2, 8, 14, 14).astype(np.float32))
    g = jnp.asarray(rng.rand(8).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(8).astype(np.float32))
    got, m, v = fused_bn_relu(x, g, b, relu=False, interpret=False)
    want, wm, wv = batch_norm(x, g, b, jnp.zeros(8), jnp.ones(8),
                              fix_gamma=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(m), np.asarray(wm), rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(v), np.asarray(wv), rtol=1e-4,
                               atol=1e-4)


def test_round5_tail_ops_cpu_vs_tpu():
    """Round-5 tail: Crop, legacy quantize, amp casts, element_0index trio
    — cpu-as-oracle rows for the chip tier."""
    rng = np.random.RandomState(11)
    img = rng.randn(2, 3, 8, 8).astype(np.float32)
    check_consistency(
        lambda d: mx.nd.Crop(d, h_w=(4, 4), offset=(1, 2)), [img])
    check_consistency(
        lambda d: mx.nd.Crop(d, mx.nd.zeros((2, 3, 5, 5)), center_crop=True),
        [img])

    x = rng.randn(3, 4).astype(np.float32)
    idx = np.array([0, 2, 3], np.float32)
    check_consistency(
        lambda d: mx.nd.choose_element_0index(d, mx.nd.array(idx)), [x])
    check_consistency(
        lambda d: mx.nd.fill_element_0index(
            d, mx.nd.array([9.0, 8.0, 7.0]), mx.nd.array(idx)), [x])

    check_consistency(lambda d: mx.nd.amp_cast(d, dtype="float16"), [x],
                      rtol=1e-3, atol=1e-3, grad=False)

    q = rng.rand(2, 8).astype(np.float32) * 2 - 1
    check_consistency(
        lambda d: mx.nd.quantize(d, mx.nd.array([-1.0]), mx.nd.array([1.0]),
                                 out_type="uint8")[0], [q], grad=False)


def test_onnx_breadth3_roundtrip_on_tpu():
    """The breadth-3 ONNX roundtrip executed with the TPU as the bind
    target (export/import themselves are host-side)."""
    import tempfile

    import incubator_mxnet_tpu.symbol as S
    from incubator_mxnet_tpu.contrib import onnx as onnx_mxnet

    S.symbol._reset_naming()
    data = S.var("data")
    x = S.clip(data, a_min=-0.8, a_max=0.8)
    x = S.expand_dims(S.sum(x, axis=1), axis=1)
    out_sym = S.log_softmax(S.tile(x, reps=(1, 4)), axis=-1)
    xv = np.random.RandomState(12).rand(3, 5).astype(np.float32) - 0.5

    exe = out_sym.simple_bind(data=xv.shape)
    exe.arg_dict["data"][:] = xv
    ref = exe.forward(is_train=False)[0].asnumpy()

    with tempfile.TemporaryDirectory() as td:
        f = td + "/b3.onnx"
        onnx_mxnet.export_model(out_sym, {}, input_shape=xv.shape,
                                onnx_file_path=f)
        sym2, arg2, aux2 = onnx_mxnet.import_model(f)
    exe2 = sym2.simple_bind(data=xv.shape)
    exe2.arg_dict["data"][:] = xv
    out = exe2.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
