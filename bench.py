#!/usr/bin/env python
"""Headline benchmark — BERT-base pretraining throughput (samples/sec).

One fused SPMD train step (forward + backward + Adam, donated buffers) via
``parallel.SPMDTrainer`` on the local mesh: config 3 of BASELINE.md.  Model
init runs on the CPU backend (one eager forward for deferred shapes; avoids
per-op RPCs through the axon tunnel), then parameters are device_put onto
the accelerator mesh and every step is a single jitted program.

Prints ONE JSON line:
  {"metric": "bert_base_samples_per_sec", "value": N, "unit":
   "samples/sec/chip", "vs_baseline": N}

vs_baseline divides by 100 samples/sec/device — recalled MXNet-era
GluonNLP BERT-base (seq 128, fp16) per-V100 pretraining throughput
(UNVERIFIED: reference mount was empty; see BASELINE.md provenance note).

``MXNET_TPU_BENCH=resnet50`` switches to BASELINE.md config 2 (ResNet-50
ImageNet-shape training, synthetic data, bf16 AMP, SGD+momentum);
vs_baseline there divides by 1400 img/s — recalled MXNet-era fp16 V100
throughput (same provenance caveat).
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_SAMPLES_PER_SEC = 100.0
BASELINE_RESNET50_IMG_PER_SEC = 1400.0


def _cpu_smoke_goodput(budget_s=120.0):
    """Bounded CPU-smoke goodput breakdown for outage rounds (ISSUE 20):
    run the scaling harness's child (2 virtual CPU devices, a handful of
    steps) in a subprocess with JAX_PLATFORMS=cpu and return its goodput
    snapshot.  A backend_unavailable round then still carries SOME
    evidence — proof the software stack trains and where its wall-clock
    goes — instead of a bare error string.  Never raises; returns None
    if even the CPU smoke can't run (that in itself is reported by the
    caller as smoke=None, i.e. the outage is not tunnel-only)."""
    import subprocess

    try:
        script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "benchmark", "opperf", "scaling.py")
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("MXNET_FAULT_SPEC", None)
        env.update(JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=2")
        r = subprocess.run(
            [sys.executable, script, "--child", "--devices", "2",
             "--config", "dp", "--mode", "weak", "--steps", "5",
             "--warmup", "2", "--per-device-batch", "8",
             "--global-batch", "16"],
            env=env, capture_output=True, text=True, timeout=budget_s)
        for line in r.stdout.splitlines():
            if line.startswith("SCALING_RESULT "):
                res = json.loads(line[len("SCALING_RESULT "):])
                snap = res.get("goodput") or {}
                return {"samples_per_sec": res.get("samples_per_sec"),
                        "goodput": snap.get("goodput"),
                        "wall_s": snap.get("wall_s"),
                        "top_overhead": snap.get("top_overhead")}
    except Exception as e:  # the smoke is best-effort evidence, never fatal
        print(f"bench: cpu smoke failed: {e}", file=sys.stderr)
    return None


def _emit_error(exc):
    """Structured one-line error JSON: a transient tunnel wedge must degrade
    to a parseable record, not an rc=1 traceback (the round-4 bench evidence
    died exactly that way — at backend init, through no fault of the
    workload).  Since ISSUE 20 the record carries a ``cpu_smoke`` goodput
    breakdown so an outage round still shows the stack trains on CPU and
    where its seconds went."""
    mode = os.environ.get("MXNET_TPU_BENCH") or "bert_base"
    print(json.dumps({
        "metric": mode, "value": None, "unit": None, "vs_baseline": None,
        "status": "backend_unavailable",
        "error": f"{type(exc).__name__}: {exc}"[:800],
        "cpu_smoke": _cpu_smoke_goodput(),
    }))


def _probe_backend(deadline_s):
    """Bounded wait-for-backend.  The probe runs in a CHILD process because a
    wedged axon tunnel can either raise at init or hang forever, and a failed
    init poisons jax's in-process backend cache; a subprocess bounds both and
    leaves this process's backend state untouched.  Polls with backoff up to
    ``deadline_s`` (default 10 min) before giving up."""
    import subprocess

    code = ("import jax, numpy as np; x = jax.numpy.ones((8, 8)); "
            "assert float(np.asarray(x.sum())) == 64.0; "
            "print('BACKEND_OK', jax.default_backend())")
    t0 = time.monotonic()
    delay, last = 5.0, "never probed"
    # per-attempt cap: a wedged tunnel hangs the child until this expires,
    # so a 180 s default burns most of the overall deadline on ONE attempt
    # (the round-18 run spent 748 s to report an unavailable backend);
    # tunable so CI can fail fast
    probe_s = float(os.environ.get("MXNET_TPU_BENCH_PROBE_TIMEOUT", "180"))
    while True:
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=probe_s)
            if r.returncode == 0 and "BACKEND_OK" in r.stdout:
                print(r.stdout.strip(), file=sys.stderr)
                return
            last = (r.stderr or r.stdout).strip()[-500:]
        except subprocess.TimeoutExpired:
            last = f"probe timed out after {probe_s:.0f}s (tunnel hang)"
        waited = time.monotonic() - t0
        if waited > deadline_s:
            raise RuntimeError(
                f"backend unavailable after {int(waited)}s; last: {last}")
        print(f"bench: backend not ready ({last.splitlines()[-1] if last else '?'}); "
              f"retrying in {delay:.0f}s", file=sys.stderr)
        time.sleep(delay)
        delay = min(delay * 1.7, 60.0)


def _fence(trainer, loss):
    """Concrete D2H of the last loss AND one updated parameter.  Under the
    tunneled axon backend block_until_ready can return before execution
    completes (measured 27x inflation), and the loss alone doesn't depend
    on the final optimizer update — fencing a param covers it."""
    import jax
    import numpy as np

    float(np.asarray(loss._data))
    p0 = jax.tree_util.tree_leaves(trainer._param_arrays)[0]
    np.asarray(p0.addressable_data(0))


def _bench_bert_folded(net, mlm_loss, mp, B, P, steps, warmup,
                       tok, seg, pos, labels, k=1):
    """bert_base through gluon.Trainer.fold_step (MXNET_STEP_FOLD=1): one
    donated compiled program per step on the default device — the folded
    twin of the SPMD headline, so the two paths are comparable round to
    round (docs/step_fold.md).  With k > 1 (MXNET_STEP_FOLD_K=K) the step
    is ``Trainer.fold_steps``: the batch is tiled to a [K, B, ...] window
    and one dispatch runs K logical steps in an in-program scan —
    samples/sec still counts LOGICAL steps, so the number is directly
    comparable to the K=1 and SPMD headlines."""
    import jax
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon

    dev = jax.devices()[0]

    def to_dev(nd):
        nd._data = jax.device_put(nd._data, dev)
        return nd

    # params/batch were staged on the CPU device for cheap eager init;
    # the fold runs where the chips are
    for p in net.collect_params().values():
        p._data._data = jax.device_put(p._data._data, dev)
        if p._data._grad is not None:
            p._data._grad._data = jax.device_put(p._data._grad._data, dev)
    nds = (tok, seg, pos, labels) if P else (tok, seg, labels)
    if k > 1:
        # [K, B, ...] stacked window — the io.DataPipeline.stage_window
        # layout; one tiled resident batch keeps H2D off the loop just
        # like the SPMD path's pre-staged shard
        batch = [to_dev(mx.nd.array(
            np.repeat(np.asarray(a._data)[None], k, axis=0),
            dtype=str(a._data.dtype))) for a in nds]
    else:
        batch = [to_dev(a) for a in nds]

    trainer = gluon.Trainer(
        net.collect_params(), "adam",
        {"learning_rate": 1e-4, "multi_precision": mp}, kvstore=None)
    if P:
        loss_fn = lambda t, s, pm, lb: mlm_loss(net(t, s, pm), lb)
    else:
        loss_fn = lambda t, s, lb: mlm_loss(net(t, s), lb)
    fold = (trainer.fold_steps(loss_fn, k=k, block=net) if k > 1
            else trainer.fold_step(loss_fn, block=net))
    variant = "step_fold" if k <= 1 else f"step_fold_k[{k}]"

    def fence(loss):
        float(np.asarray(loss._data).mean())
        p0 = next(iter(net.collect_params().values()))
        np.asarray(p0._data._data)

    for _ in range(max(1, warmup // max(1, k))):
        loss = fold(*batch)
    fence(loss)
    if not fold.folded:
        # do NOT time and emit a headline: it would be the EAGER path's
        # number wearing the step_fold variant tag (the opperf harness
        # exits 3 in this case; bench.py reports the error instead)
        print(json.dumps({
            "metric": "bert_base_samples_per_sec",
            "variant": variant,
            "error": f"fold fell back: {fold.fallback_reason}",
        }))
        return
    n_windows = max(1, steps // max(1, k))
    t0 = time.perf_counter()
    for _ in range(n_windows):
        loss = fold(*batch)
    fence(loss)
    dt = time.perf_counter() - t0
    # per LOGICAL step: a K-window is K steps of B samples
    samples_per_sec = B * n_windows * max(1, k) / dt
    out = {
        "metric": "bert_base_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/sec/chip",
        "variant": variant,
        "folded": bool(fold.folded),
        "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC, 3),
    }
    if k > 1:
        out["k"] = k
    print(json.dumps(out))


def bench_resnet50():
    """ResNet-50 training throughput, synthetic ImageNet-shape data (the
    ``--benchmark 1`` mode of the reference's train_imagenet fit loop)."""
    import jax

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

    backend = jax.default_backend()
    B = int(os.environ.get("MXNET_TPU_BENCH_BATCH", "256"))
    warmup, steps = (2, 60) if backend != "cpu" else (1, 2)
    steps = int(os.environ.get("MXNET_TPU_BENCH_STEPS", steps))

    from incubator_mxnet_tpu import amp
    if os.environ.get("MXNET_TPU_BENCH_AMP", "1") == "1":
        amp.init("bfloat16")

    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        mx.random.seed(0)
        net = resnet50_v1(classes=1000)
        net.initialize()
        rng = np.random.RandomState(0)
        img = mx.nd.array(rng.rand(B, 3, 224, 224).astype(np.float32))
        labels = mx.nd.array(rng.randint(0, 1000, (B,)), dtype="int32")
        # materialize deferred-init shapes with a tiny batch (param shapes
        # are batch-independent; a full-B eager CPU forward takes minutes)
        net(mx.nd.zeros((2, 3, 224, 224)))

    def ce_loss(out, label):
        from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce
        logits = out._data if hasattr(out, "_data") else out[0]._data
        return NDArray(streaming_softmax_ce(logits, label._data))  # [B]

    # bf16 canonical params + fp32 SGD-momentum masters: measured SLOWER
    # than fp32 params for ResNet (2423 vs 2455 img/s) — the mp master
    # round-trip costs more than the per-use weight cast it replaces at
    # conv-sized weights, and BN running stats lose precision.  Default
    # off; the knob remains for A/B.
    mp = (os.environ.get("MXNET_TPU_BENCH_BF16_PARAMS", "0") == "1"
          and os.environ.get("MXNET_TPU_BENCH_AMP", "1") == "1")
    if mp:
        net.cast("bfloat16")

    mesh = make_mesh()
    trainer = SPMDTrainer(net, ce_loss, "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4,
                           "multi_precision": mp},
                          mesh=mesh)

    # pre-stage the synthetic batch on the mesh (the reference's
    # --benchmark 1 discipline; per-step H2D belongs to the input
    # pipeline, measured separately)
    img, labels = trainer.shard_batch(img, labels)

    prof_dir = os.environ.get("MXNET_TPU_BENCH_PROFILE")
    if prof_dir:
        for _ in range(2):
            loss = trainer.step(img, labels)
        _fence(trainer, loss)
        with jax.profiler.trace(prof_dir):
            for _ in range(5):
                loss = trainer.step(img, labels)
            _fence(trainer, loss)

    dt = _run_spmd(trainer, img, labels, warmup, steps)
    _emit("resnet50_img_per_sec", B * steps / dt, "img/sec/chip",
          BASELINE_RESNET50_IMG_PER_SEC, mesh)


def _run_spmd(trainer, inputs, labels, warmup, steps):
    """Time `steps` optimizer steps.  MXNET_TPU_BENCH_BULK=k (default 1)
    dispatches k steps per device call via SPMDTrainer.step_bulk — the
    engine-bulking analog; use for dispatch-bound tiny models (MNIST)
    where the tunnel round trip, not the chip, is the bottleneck."""
    import time as _t

    bulk = int(os.environ.get("MXNET_TPU_BENCH_BULK", "1"))
    if bulk > 1:
        n = max(1, steps // bulk)          # dispatches; actual steps = n*bulk
        for _ in range(max(1, warmup // bulk)):
            loss = trainer.step_bulk(inputs, labels, bulk)
        _fence(trainer, loss)
        t0 = _t.perf_counter()
        for _ in range(n):
            loss = trainer.step_bulk(inputs, labels, bulk)
        _fence(trainer, loss)
        dt = _t.perf_counter() - t0
        # normalize so the caller's `B*steps/dt` reflects the true rate
        # even when bulk does not divide steps
        return dt * steps / (n * bulk)
    for _ in range(warmup):
        loss = trainer.step(inputs, labels)
    _fence(trainer, loss)
    t0 = _t.perf_counter()
    for _ in range(steps):
        loss = trainer.step(inputs, labels)
    _fence(trainer, loss)
    return _t.perf_counter() - t0


def _emit(metric, total_per_sec, unit, baseline, mesh):
    """Emit per-CHIP throughput: SPMD shards the global batch across the
    mesh, so total/dt must be divided by the chip count (as the resnet50
    and BERT benches always did)."""
    value = total_per_sec / mesh.devices.size
    print(json.dumps({"metric": metric, "value": round(value, 2), "unit": unit,
                      "vs_baseline": round(value / baseline, 3)}))


def bench_mnist(model="mlp"):
    """BASELINE config 1: MLP / LeNet on MNIST-shape data (the reference's
    train_mnist.py).  vs_baseline divides by 50k samples/s — recalled
    MXNet-era V100 MLP-MNIST throughput (UNVERIFIED, same provenance
    caveat as the other baselines)."""
    import jax

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

    backend = jax.default_backend()
    B = int(os.environ.get("MXNET_TPU_BENCH_BATCH", "1024"))
    warmup, steps = (3, 60) if backend != "cpu" else (1, 2)
    steps = int(os.environ.get("MXNET_TPU_BENCH_STEPS", steps))
    from incubator_mxnet_tpu import amp
    if os.environ.get("MXNET_TPU_BENCH_AMP", "1") == "1":
        amp.init("bfloat16")
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        mx.random.seed(0)
        net = nn.HybridSequential()
        if model == "mlp":
            net.add(nn.Dense(128, activation="relu"),
                    nn.Dense(64, activation="relu"), nn.Dense(10))
            img = mx.nd.array(np.random.RandomState(0).rand(B, 784).astype(np.float32))
            net.initialize()
            net(mx.nd.zeros((2, 784)))
        else:  # lenet
            net.add(nn.Conv2D(20, 5, activation="tanh"), nn.MaxPool2D(2, 2),
                    nn.Conv2D(50, 5, activation="tanh"), nn.MaxPool2D(2, 2),
                    nn.Flatten(), nn.Dense(500, activation="tanh"), nn.Dense(10))
            img = mx.nd.array(np.random.RandomState(0).rand(B, 1, 28, 28).astype(np.float32))
            net.initialize()
            net(mx.nd.zeros((2, 1, 28, 28)))
        labels = mx.nd.array(np.random.RandomState(0).randint(0, 10, (B,)), dtype="int32")

    def loss_fn(out, label):
        logits = out._data if hasattr(out, "_data") else out[0]._data
        return NDArray(streaming_softmax_ce(logits, label._data))

    trainer = SPMDTrainer(net, loss_fn, "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9}, mesh=make_mesh())
    img, labels = trainer.shard_batch(img, labels)
    dt = _run_spmd(trainer, img, labels, warmup, steps)
    _emit(f"mnist_{model}_samples_per_sec", B * steps / dt, "samples/sec/chip",
          50000.0, trainer.mesh)


def bench_transformer():
    """BASELINE config 4: Transformer-big WMT-shape training.  vs_baseline
    divides by 4500 tokens/s — recalled fp16 V100 transformer-big
    throughput (UNVERIFIED recall)."""
    import jax

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo.transformer import transformer_big
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

    backend = jax.default_backend()
    # S=256 default: the WMT bucketed pipeline's dominant bucket (the
    # round-3 S=64 config flattered tokens/s and starved the MXU —
    # VERDICT r3 item 3).  MXNET_TPU_BENCH_SEQ overrides for probes.
    B = int(os.environ.get("MXNET_TPU_BENCH_BATCH", "32"))
    S = int(os.environ.get("MXNET_TPU_BENCH_SEQ", "256"))
    vocab = 32768
    warmup, steps = (3, 120) if backend != "cpu" else (1, 2)
    steps = int(os.environ.get("MXNET_TPU_BENCH_STEPS", steps))
    from incubator_mxnet_tpu import amp
    if os.environ.get("MXNET_TPU_BENCH_AMP", "1") == "1":
        amp.init("bfloat16")
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        mx.random.seed(0)
        net = transformer_big(vocab_size=vocab, max_length=512, dropout=0.1)
        net.initialize()
        rng = np.random.RandomState(0)
        src = mx.nd.array(rng.randint(0, vocab, (B, S)), dtype="int32")
        tgt = mx.nd.array(rng.randint(0, vocab, (B, S)), dtype="int32")
        labels = mx.nd.array(rng.randint(0, vocab, (B, S)), dtype="int32")
        net(mx.nd.zeros((2, S), dtype="int32"), mx.nd.zeros((2, S), dtype="int32"))

    # same bf16-canonical-params + fp32-master discipline as the BERT bench
    mp = (os.environ.get("MXNET_TPU_BENCH_BF16_PARAMS", "1") == "1"
          and os.environ.get("MXNET_TPU_BENCH_AMP", "1") == "1")
    if mp:
        net.cast("bfloat16")

    def loss_fn(out, label):
        return NDArray(streaming_softmax_ce(out._data, label._data).mean(axis=-1))

    trainer = SPMDTrainer(net, loss_fn, "adam",
                          {"learning_rate": 1e-4, "multi_precision": mp},
                          mesh=make_mesh())
    src, tgt, labels = trainer.shard_batch(src, tgt, labels)
    dt = _run_spmd(trainer, (src, tgt), labels, warmup, steps)
    tok_per_sec = 2 * B * S * steps / dt  # src+tgt tokens, the WMT convention
    _emit("transformer_big_tokens_per_sec", tok_per_sec, "tokens/sec/chip",
          4500.0, trainer.mesh)


def bench_ssd():
    """BASELINE config 5: SSD-512 detection training (dynamic-shape stress;
    here fixed-shape by design).  vs_baseline divides by 60 img/s —
    recalled fp16 V100 SSD-512 throughput (UNVERIFIED recall)."""
    import jax
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo.ssd import ssd_512_resnet18
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.ops.detection import multibox_target
    from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

    backend = jax.default_backend()
    B = int(os.environ.get("MXNET_TPU_BENCH_BATCH", "32"))
    warmup, steps = (2, 60) if backend != "cpu" else (1, 1)
    steps = int(os.environ.get("MXNET_TPU_BENCH_STEPS", steps))
    from incubator_mxnet_tpu import amp
    if os.environ.get("MXNET_TPU_BENCH_AMP", "1") == "1":
        amp.init("bfloat16")
    backbone = os.environ.get("MXNET_TPU_BENCH_SSD_BACKBONE", "resnet18")
    if backbone not in ("resnet18", "vgg16"):
        raise ValueError(f"MXNET_TPU_BENCH_SSD_BACKBONE must be resnet18 or vgg16, got {backbone!r}")
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        mx.random.seed(0)
        if backbone == "vgg16":
            from incubator_mxnet_tpu.gluon.model_zoo.ssd import ssd_512_vgg16_atrous
            net = ssd_512_vgg16_atrous(num_classes=20)
        else:
            net = ssd_512_resnet18(num_classes=20)
        net.initialize()
        rng = np.random.RandomState(0)
        img = mx.nd.array(rng.rand(B, 3, 512, 512).astype(np.float32))
        lab = np.full((B, 4, 5), -1, np.float32)
        lab[:, 0] = [1, 0.2, 0.2, 0.7, 0.7]
        lab[:, 1] = [5, 0.5, 0.5, 0.9, 0.9]
        labels = mx.nd.array(lab)
        net(mx.nd.zeros((2, 3, 512, 512)))

    def ssd_loss(out, label):
        anchors, cls_preds, box_preds = out
        bt, bm, ct = multibox_target(anchors._data, label._data,
                                     jnp.swapaxes(cls_preds._data, 1, 2))
        ce = streaming_softmax_ce(cls_preds._data, ct).mean(axis=-1)
        l1 = (jnp.abs(box_preds._data - bt) * bm).mean(axis=-1)
        return NDArray(ce + l1)

    trainer = SPMDTrainer(net, ssd_loss, "sgd",
                          {"learning_rate": 0.01, "momentum": 0.9, "wd": 5e-4},
                          mesh=make_mesh())
    img, labels = trainer.shard_batch(img, labels)
    dt = _run_spmd(trainer, img, labels, warmup, steps)
    _emit(f"ssd512_{backbone}_img_per_sec" if backbone != "resnet18" else "ssd512_img_per_sec", B * steps / dt, "img/sec/chip", 60.0, trainer.mesh)


def bench_yolo3():
    """Extra (non-BASELINE) config: YOLOv3-darknet53 detection training at
    416², the canonical COCO setup.  vs_baseline divides by 55 img/s —
    recalled fp16 V100 YOLOv3 training throughput (UNVERIFIED recall)."""
    import jax

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo import yolo
    from incubator_mxnet_tpu import ndarray as nd
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

    backend = jax.default_backend()
    B = int(os.environ.get("MXNET_TPU_BENCH_BATCH", "16"))
    warmup, steps = (2, 20) if backend != "cpu" else (1, 1)
    steps = int(os.environ.get("MXNET_TPU_BENCH_STEPS", steps))
    from incubator_mxnet_tpu import amp
    if os.environ.get("MXNET_TPU_BENCH_AMP", "1") == "1":
        amp.init("bfloat16")
    C = 80
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        mx.random.seed(0)
        net = yolo.yolo3_darknet53(num_classes=C)
        net.initialize()
        rng = np.random.RandomState(0)
        img = mx.nd.array(rng.rand(B, 3, 416, 416).astype(np.float32))
        lab = np.full((B, 8, 5), -1, np.float32)
        lab[:, 0] = [1, 80, 80, 280, 280]
        lab[:, 1] = [7, 200, 120, 380, 360]
        labels = mx.nd.array(lab)
        net(mx.nd.zeros((2, 3, 416, 416)))

    def yolo_loss(out, label):
        preds, off, anc, st = out
        gt_ids = nd.slice_axis(label, axis=-1, begin=0, end=1)
        gt_boxes = nd.slice_axis(label, axis=-1, begin=1, end=5)
        targets = yolo.yolo3_targets(gt_boxes, gt_ids, off, anc, st, C)
        return yolo.yolo3_loss(preds, *targets, C, reduction="none")

    trainer = SPMDTrainer(net, yolo_loss, "sgd",
                          {"learning_rate": 1e-3, "momentum": 0.9, "wd": 5e-4},
                          mesh=make_mesh())
    img, labels = trainer.shard_batch(img, labels)
    dt = _run_spmd(trainer, img, labels, warmup, steps)
    _emit("yolo3_416_img_per_sec", B * steps / dt, "img/sec/chip", 55.0, trainer.mesh)


def main():
    mode = os.environ.get("MXNET_TPU_BENCH")
    if mode == "resnet50":
        return bench_resnet50()
    if mode == "yolo3":
        return bench_yolo3()
    if mode in ("mnist", "mlp"):
        return bench_mnist("mlp")
    if mode == "lenet":
        return bench_mnist("lenet")
    if mode == "transformer":
        return bench_transformer()
    if mode == "ssd":
        return bench_ssd()
    import jax

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo.bert import bert_base, BERTForPretrain
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

    backend = jax.default_backend()
    B = int(os.environ.get("MXNET_TPU_BENCH_BATCH", "64"))
    S, vocab = 128, 30522
    # MLM decodes only the masked positions (GluonNLP masked_positions /
    # MLPerf max_predictions_per_seq=20 at S=128) — the vocab projection
    # runs on P=20 tokens, not all 128; MXNET_TPU_BENCH_ALL_POSITIONS=1
    # restores the decode-everything variant for comparison.
    P = 0 if os.environ.get("MXNET_TPU_BENCH_ALL_POSITIONS") == "1" else 20
    # 180-step window: the fence's fixed D2H round-trip (~0.1-0.4 s through
    # the tunnel) is measurement cost, not workload; at 60 steps it shaved
    # ~2 ms/step off the steady-state rate (1407 -> 1474 samples/s at 180).
    warmup, steps = (3, 180) if backend != "cpu" else (1, 2)
    steps = int(os.environ.get("MXNET_TPU_BENCH_STEPS", steps))

    # BASELINE.md config 3 is mixed-precision: bf16 matmuls (MXU-native)
    # with fp32 softmax/norms/optimizer state, via the mx.amp op lists.
    from incubator_mxnet_tpu import amp
    if os.environ.get("MXNET_TPU_BENCH_AMP", "1") == "1":
        amp.init("bfloat16")

    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        mx.random.seed(0)
        bert = bert_base(vocab_size=vocab, max_length=512, dropout=0.1)
        net = BERTForPretrain(bert, vocab_size=vocab)
        net.initialize()
        rng = np.random.RandomState(0)
        tok = mx.nd.array(rng.randint(0, vocab, (B, S)), dtype="int32")
        seg = mx.nd.zeros((B, S), dtype="int32")
        if P:
            pos = mx.nd.array(
                np.sort(np.stack([rng.choice(S, P, replace=False) for _ in range(B)])),
                dtype="int32")
            labels = mx.nd.array(rng.randint(0, vocab, (B, P)), dtype="int32")
        else:
            pos = None
            labels = mx.nd.array(rng.randint(0, vocab, (B, S)), dtype="int32")
        # materialize deferred-init shapes with a tiny batch (cheap on the
        # eager CPU path; param shapes are batch-independent)
        net(mx.nd.zeros((2, S), dtype="int32"), mx.nd.zeros((2, S), dtype="int32"),
            mx.nd.zeros((2, P), dtype="int32") if P else None)

    # Store the canonical parameters in bf16 with fp32 Adam master weights
    # (MLPerf BERT discipline).  With fp32 params, every weight pays a
    # fp32-read + bf16-write AMP cast per step AND wgrad outputs convert
    # back to fp32; bf16 params + mp_adam_update cut ~10 bytes/param/step
    # of pure HBM traffic.  MXNET_TPU_BENCH_BF16_PARAMS=0 restores.
    mp = (os.environ.get("MXNET_TPU_BENCH_BF16_PARAMS", "1") == "1"
          and os.environ.get("MXNET_TPU_BENCH_AMP", "1") == "1")
    if mp:
        net.cast("bfloat16")

    def mlm_loss(out, label):
        # Streaming cross-entropy: no [B, S, V] fp32 log-prob tensor is
        # materialized (profiled: the log_softmax form cost ~3 ms/step in
        # HBM traffic at B=64 — docs/PERF_NOTES.md).
        from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce
        mlm_logits, _ = out
        return NDArray(streaming_softmax_ce(mlm_logits._data, label._data).mean(axis=-1))

    fold_k = int(os.environ.get("MXNET_STEP_FOLD_K", "0") or 0)
    if os.environ.get("MXNET_STEP_FOLD") == "1" or fold_k > 1:
        # ISSUE 15: route the headline through the FOLDED imperative step
        # (gluon.Trainer.fold_step — one donated compiled program per
        # step on a single device, docs/step_fold.md) so the TPU round
        # measures the fold against the SPMD path.  ISSUE 17: with
        # MXNET_STEP_FOLD_K=K>1 the step is the K-step fold_steps scan —
        # one dispatch per K logical steps on a [K, B, ...] tiled batch.
        return _bench_bert_folded(net, mlm_loss, mp, B, P, steps, warmup,
                                  tok, seg, pos, labels,
                                  k=max(1, fold_k))
    mesh = make_mesh()  # pure-dp over whatever local devices exist
    trainer = SPMDTrainer(net, mlm_loss, "adam",
                          {"learning_rate": 1e-4, "multi_precision": mp}, mesh=mesh)

    # Pre-stage the synthetic batch on the mesh (the reference's
    # --benchmark 1 mode reuses one device-resident batch the same way:
    # [U:example/image-classification/common/fit.py]); keeps per-step H2D
    # off the critical path, as a prefetching input pipeline would.
    if P:
        tok, seg, pos, labels = trainer.shard_batch(tok, seg, pos, labels)
        inputs = (tok, seg, pos)
    else:
        tok, seg, labels = trainer.shard_batch(tok, seg, labels)
        inputs = (tok, seg)

    for _ in range(warmup):
        loss = trainer.step(inputs, labels)
    _fence(trainer, loss)

    prof_dir = os.environ.get("MXNET_TPU_BENCH_PROFILE")
    if prof_dir:
        with jax.profiler.trace(prof_dir):
            for _ in range(5):
                loss = trainer.step(inputs, labels)
            _fence(trainer, loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step(inputs, labels)
    _fence(trainer, loss)
    dt = time.perf_counter() - t0

    n_chips = mesh.devices.size
    samples_per_sec = B * steps / dt / n_chips
    print(json.dumps({
        "metric": "bert_base_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    import signal

    watchdog = int(os.environ.get("MXNET_TPU_BENCH_TIMEOUT", "3000"))

    def _alarm(signum, frame):
        raise TimeoutError(f"bench exceeded {watchdog}s watchdog")

    try:
        signal.signal(signal.SIGALRM, _alarm)
        signal.alarm(watchdog)
        if os.environ.get("MXNET_TPU_BENCH_SKIP_PROBE") != "1":
            _probe_backend(float(os.environ.get("MXNET_TPU_BENCH_BACKEND_WAIT", "600")))
        main()
        signal.alarm(0)
    except Exception as e:
        import traceback
        traceback.print_exc(file=sys.stderr)
        _emit_error(e)
        sys.exit(0)
