#!/usr/bin/env python
"""Headline benchmark — BERT-base pretraining throughput (samples/sec).

One fused SPMD train step (forward + backward + Adam, donated buffers) via
``parallel.SPMDTrainer`` on the local mesh: config 3 of BASELINE.md.  Model
init runs on the CPU backend (one eager forward for deferred shapes; avoids
per-op RPCs through the axon tunnel), then parameters are device_put onto
the accelerator mesh and every step is a single jitted program.

Prints ONE JSON line:
  {"metric": "bert_base_samples_per_sec", "value": N, "unit":
   "samples/sec/chip", "vs_baseline": N}

vs_baseline divides by 100 samples/sec/device — recalled MXNet-era
GluonNLP BERT-base (seq 128, fp16) per-V100 pretraining throughput
(UNVERIFIED: reference mount was empty; see BASELINE.md provenance note).
"""
import json
import os
import time

import numpy as np

BASELINE_SAMPLES_PER_SEC = 100.0


def main():
    import jax

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo.bert import bert_base, BERTForPretrain
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

    backend = jax.default_backend()
    B, S, vocab = 64, 128, 30522
    warmup, steps = (2, 20) if backend != "cpu" else (1, 2)

    # BASELINE.md config 3 is mixed-precision: bf16 matmuls (MXU-native)
    # with fp32 softmax/norms/optimizer state, via the mx.amp op lists.
    from incubator_mxnet_tpu import amp
    if os.environ.get("MXNET_TPU_BENCH_AMP", "1") == "1":
        amp.init("bfloat16")

    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        mx.random.seed(0)
        bert = bert_base(vocab_size=vocab, max_length=512, dropout=0.1)
        net = BERTForPretrain(bert, vocab_size=vocab)
        net.initialize()
        rng = np.random.RandomState(0)
        tok = mx.nd.array(rng.randint(0, vocab, (B, S)), dtype="int32")
        seg = mx.nd.zeros((B, S), dtype="int32")
        labels = mx.nd.array(rng.randint(0, vocab, (B, S)), dtype="int32")
        net(tok, seg)  # materialize deferred-init shapes

    def mlm_loss(out, label):
        import jax.numpy as jnp
        mlm_logits, _ = out
        logp = jax.nn.log_softmax(mlm_logits._data.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(
            logp, label._data.astype(jnp.int32)[..., None], axis=-1
        )[..., 0]
        return NDArray(nll.mean(axis=-1))

    mesh = make_mesh()  # pure-dp over whatever local devices exist
    trainer = SPMDTrainer(net, mlm_loss, "adam", {"learning_rate": 1e-4}, mesh=mesh)

    for _ in range(warmup):
        loss = trainer.step((tok, seg), labels)
    jax.block_until_ready(trainer._param_arrays)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = trainer.step((tok, seg), labels)
    jax.block_until_ready(trainer._param_arrays)
    dt = time.perf_counter() - t0

    n_chips = mesh.devices.size
    samples_per_sec = B * steps / dt / n_chips
    print(json.dumps({
        "metric": "bert_base_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
