// Native data pipeline: RecordIO reader + JPEG decode/augment thread pool.
//
// Parity target: the reference's C++ ImageRecordIter pipeline
// ([U:src/io/iter_image_recordio_2.cc]): RecordIO chunk readers → OpenCV
// decode+augment worker pool → batcher → double-buffered prefetch.  Here:
// a reader thread parses the dmlc RecordIO framing, a pool of decode
// workers does libjpeg decode + resize/crop/mirror/normalize straight into
// per-batch float buffers (NCHW), and the Python side device_puts the
// filled buffer (host staging → TPU).  Sharded reading via
// part_index/num_parts matches the reference's distributed contract.
//
// C ABI (ctypes-consumed; no pybind11 in this environment):
//   MXTImageIterCreate / Next / Reset / Free, MXTRecordCount.
//
// RecordIO framing (dmlc-core recordio.h): [magic=0xced7230a][lrec][payload]
// with 4-byte alignment padding; lrec upper 3 bits = continuation flag,
// lower 29 = length.  Image payload = IRHeader{flag,label,id,id2} (24B) +
// flag*4 bytes of extra float labels + encoded image.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <setjmp.h>

namespace {

void WarnOnce(const char* what) {
  static std::atomic<int> warned{0};
  if (warned.fetch_add(1) == 0)
    std::fprintf(stderr, "[mxtpu_io] WARNING: %s (reported once)\n", what);
}

constexpr uint32_t kMagic = 0xced7230a;

struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};

// ---------------------------------------------------------------------------
// RecordIO parsing
// ---------------------------------------------------------------------------

struct Record {
  std::vector<uint8_t> payload;  // IRHeader + extra labels + image bytes
};

class RecordIOReader {
 public:
  explicit RecordIOReader(const std::string& path) : file_(nullptr) {
    file_ = std::fopen(path.c_str(), "rb");
  }
  ~RecordIOReader() {
    if (file_) std::fclose(file_);
  }
  bool ok() const { return file_ != nullptr; }

  void Seek(uint64_t offset) { std::fseek(file_, (long)offset, SEEK_SET); }
  uint64_t Tell() { return (uint64_t)std::ftell(file_); }

  // Read one logical record (reassembling continuation parts).
  bool Next(Record* out) {
    out->payload.clear();
    while (true) {
      uint32_t magic, lrec;
      if (std::fread(&magic, 4, 1, file_) != 1) return false;
      if (magic != kMagic) return false;  // corrupt or EOF padding
      if (std::fread(&lrec, 4, 1, file_) != 1) return false;
      uint32_t cflag = lrec >> 29u;
      uint32_t len = lrec & ((1u << 29u) - 1u);
      size_t off = out->payload.size();
      out->payload.resize(off + len);
      if (len && std::fread(out->payload.data() + off, 1, len, file_) != len)
        return false;
      size_t pad = (4 - (len % 4)) % 4;
      if (pad) std::fseek(file_, (long)pad, SEEK_CUR);
      // cflag: 0 = whole record, 1 = first part, 2 = middle, 3 = last
      if (cflag == 0 || cflag == 3) return true;
    }
  }

 private:
  std::FILE* file_;
};

// ---------------------------------------------------------------------------
// JPEG decode (libjpeg) with error-trap (corrupt images must not abort)
// ---------------------------------------------------------------------------

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void JpegErrorExit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// decode to RGB u8, returns false on failure
bool DecodeJpeg(const uint8_t* data, size_t len, std::vector<uint8_t>* out,
                int* out_h, int* out_w) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = JpegErrorExit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data), (unsigned long)len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  int w = (int)cinfo.output_width, h = (int)cinfo.output_height;
  out->resize((size_t)w * h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() + (size_t)cinfo.output_scanline * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *out_h = h;
  *out_w = w;
  return true;
}

// ---------------------------------------------------------------------------
// Augment: bilinear resize + crop + mirror + normalize → NCHW float32
// ---------------------------------------------------------------------------

void BilinearResize(const uint8_t* src, int sh, int sw, uint8_t* dst, int dh,
                    int dw) {
  const float ry = dh > 1 ? (float)(sh - 1) / (dh - 1) : 0.f;
  const float rx = dw > 1 ? (float)(sw - 1) / (dw - 1) : 0.f;
  for (int y = 0; y < dh; ++y) {
    float fy = y * ry;
    int y0 = (int)fy, y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = x * rx;
      int x0 = (int)fx, x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      float wx = fx - x0;
      for (int c = 0; c < 3; ++c) {
        float v00 = src[(y0 * sw + x0) * 3 + c];
        float v01 = src[(y0 * sw + x1) * 3 + c];
        float v10 = src[(y1 * sw + x0) * 3 + c];
        float v11 = src[(y1 * sw + x1) * 3 + c];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(y * dw + x) * 3 + c] = (uint8_t)(v + 0.5f);
      }
    }
  }
}

struct AugmentConfig {
  int h = 224, w = 224, c = 3;
  int rand_crop = 0;
  int rand_mirror = 0;
  int resize_shorter = 0;  // 0 = resize exactly to crop target
  float mean[3] = {0.f, 0.f, 0.f};
  float std_[3] = {1.f, 1.f, 1.f};
};

// Decode record → write NCHW float32 into out (h*w*c floats).
bool ProcessImage(const uint8_t* img, size_t len, const AugmentConfig& cfg,
                  std::mt19937* rng, float* out) {
  std::vector<uint8_t> rgb;
  int h = 0, w = 0;
  if (!DecodeJpeg(img, len, &rgb, &h, &w)) return false;

  std::vector<uint8_t> resized;
  const uint8_t* cur = rgb.data();
  int ch = h, cw = w;
  int target_h = cfg.h, target_w = cfg.w;
  int min_side = cfg.resize_shorter;
  if (min_side <= 0 && (h < target_h || w < target_w))
    min_side = target_h > target_w ? target_h : target_w;
  if (min_side > 0) {
    // resize shorter side to min_side, then crop
    float scale = (float)min_side / (h < w ? h : w);
    int nh = (int)(h * scale + 0.5f), nw = (int)(w * scale + 0.5f);
    if (nh < target_h) nh = target_h;
    if (nw < target_w) nw = target_w;
    resized.resize((size_t)nh * nw * 3);
    BilinearResize(cur, ch, cw, resized.data(), nh, nw);
    cur = resized.data();
    ch = nh;
    cw = nw;
  } else if (h != target_h || w != target_w) {
    if (h >= target_h && w >= target_w) {
      // big enough: crop directly below
    } else {
      resized.resize((size_t)target_h * target_w * 3);
      BilinearResize(cur, ch, cw, resized.data(), target_h, target_w);
      cur = resized.data();
      ch = target_h;
      cw = target_w;
    }
  }

  int y0 = (ch - target_h) / 2, x0 = (cw - target_w) / 2;
  if (cfg.rand_crop && rng) {
    y0 = ch > target_h ? (int)((*rng)() % (uint32_t)(ch - target_h + 1)) : 0;
    x0 = cw > target_w ? (int)((*rng)() % (uint32_t)(cw - target_w + 1)) : 0;
  }
  bool mirror = cfg.rand_mirror && rng && ((*rng)() & 1u);

  const size_t plane = (size_t)target_h * target_w;
  for (int y = 0; y < target_h; ++y) {
    for (int x = 0; x < target_w; ++x) {
      int sx = mirror ? (target_w - 1 - x) : x;
      const uint8_t* px = cur + ((size_t)(y0 + y) * cw + (x0 + sx)) * 3;
      for (int c = 0; c < 3; ++c) {
        out[c * plane + (size_t)y * target_w + x] =
            ((float)px[c] - cfg.mean[c]) / cfg.std_[c];
      }
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// Pipeline: reader thread → record queue → decode pool → batch
// ---------------------------------------------------------------------------

struct ImageIter {
  std::string rec_path;
  AugmentConfig cfg;
  int batch = 0;
  int num_threads = 4;
  int shuffle = 0;
  unsigned seed = 0;
  int part_index = 0, num_parts = 1;

  std::vector<uint64_t> offsets;  // record start offsets (this shard's)
  std::vector<size_t> order;      // iteration order over offsets
  size_t cursor = 0;              // next record to hand out
  size_t epoch = 0;               // advances augment RNG across epochs
  std::mt19937 epoch_rng;

  // scan all record offsets once, shard by part_index/num_parts
  bool Init() {
    RecordIOReader r(rec_path);
    if (!r.ok()) return false;
    std::vector<uint64_t> all;
    Record rec;
    uint64_t off = r.Tell();
    while (r.Next(&rec)) {
      all.push_back(off);
      off = r.Tell();
    }
    for (size_t i = 0; i < all.size(); ++i)
      if ((int)(i % (size_t)num_parts) == part_index) offsets.push_back(all[i]);
    order.resize(offsets.size());
    for (size_t i = 0; i < order.size(); ++i) order[i] = i;
    epoch_rng.seed(seed);
    Reset();
    return true;
  }

  void Reset() {
    cursor = 0;
    ++epoch;
    if (shuffle)
      std::shuffle(order.begin(), order.end(), epoch_rng);
  }

  // Fill one batch. Returns number of valid samples (0 = epoch end).
  int NextBatch(float* out_data, float* out_label) {
    size_t remaining = order.size() - cursor;
    if (remaining == 0) return 0;
    int n = (int)(remaining < (size_t)batch ? remaining : (size_t)batch);

    std::atomic<int> next_idx{0};
    std::atomic<int> n_ok{0};
    const size_t sample_floats = (size_t)cfg.h * cfg.w * cfg.c;
    size_t base = cursor;

    auto worker = [&](int tid) {
      RecordIOReader r(rec_path);  // per-thread handle: no seek contention
      std::mt19937 rng(seed + (unsigned)(base * 131 + tid) +
                       (unsigned)(epoch * 7919));  // fresh augs every epoch
      Record rec;
      while (true) {
        int i = next_idx.fetch_add(1);
        if (i >= n) break;
        float* slot = out_data + (size_t)i * sample_floats;
        r.Seek(offsets[order[base + i]]);
        if (!r.Next(&rec) || rec.payload.size() < sizeof(IRHeader)) {
          // corrupt/truncated record: never hand uninitialized memory to
          // the training batch
          std::memset(slot, 0, sample_floats * sizeof(float));
          out_label[i] = 0.f;
          WarnOnce("corrupt record");
          continue;
        }
        IRHeader hdr;
        std::memcpy(&hdr, rec.payload.data(), sizeof(hdr));
        size_t img_off = sizeof(IRHeader) + (size_t)hdr.flag * 4;
        // vector labels (flag > 0): header.label is 0; use the first
        // element like the Python fallback does
        float label = hdr.label;
        if (hdr.flag > 0 && rec.payload.size() >= sizeof(IRHeader) + 4)
          std::memcpy(&label, rec.payload.data() + sizeof(IRHeader), 4);
        if (rec.payload.size() <= img_off) {
          std::memset(slot, 0, sample_floats * sizeof(float));
          out_label[i] = label;
          WarnOnce("empty image payload");
          continue;
        }
        if (ProcessImage(rec.payload.data() + img_off,
                         rec.payload.size() - img_off, cfg, &rng, slot)) {
          out_label[i] = label;
          n_ok.fetch_add(1);
        } else {
          // decode failure (non-JPEG or corrupt): zero the slot, keep the
          // label so batch shape stays static for XLA, and warn loudly —
          // silent all-zero images are a training-killing failure mode
          std::memset(slot, 0, sample_floats * sizeof(float));
          out_label[i] = label;
          WarnOnce("JPEG decode failed (non-JPEG payload? repack with "
                   "tools/im2rec.py, which re-encodes to JPEG)");
        }
      }
    };

    int nt = num_threads < n ? num_threads : n;
    std::vector<std::thread> pool;
    pool.reserve(nt);
    for (int t = 0; t < nt; ++t) pool.emplace_back(worker, t);
    for (auto& th : pool) th.join();

    cursor += (size_t)n;
    return n;
  }
};

}  // namespace

extern "C" {

void* MXTImageIterCreate(const char* rec_path, int batch, int h, int w, int c,
                         int num_threads, int shuffle, unsigned seed,
                         int part_index, int num_parts, const float* mean_rgb,
                         const float* std_rgb, int rand_mirror, int rand_crop,
                         int resize_shorter) {
  if (c != 3) return nullptr;  // RGB-only pipeline; caller falls back
  auto* it = new ImageIter();
  it->rec_path = rec_path;
  it->batch = batch;
  it->cfg.h = h;
  it->cfg.w = w;
  it->cfg.c = c;
  it->cfg.rand_mirror = rand_mirror;
  it->cfg.rand_crop = rand_crop;
  it->cfg.resize_shorter = resize_shorter;
  for (int i = 0; i < 3; ++i) {
    it->cfg.mean[i] = mean_rgb ? mean_rgb[i] : 0.f;
    it->cfg.std_[i] = std_rgb ? std_rgb[i] : 1.f;
  }
  it->num_threads = num_threads > 0 ? num_threads : 4;
  it->shuffle = shuffle;
  it->seed = seed;
  it->part_index = part_index;
  it->num_parts = num_parts > 0 ? num_parts : 1;
  if (!it->Init()) {
    delete it;
    return nullptr;
  }
  return it;
}

long MXTImageIterNumSamples(void* handle) {
  return (long)static_cast<ImageIter*>(handle)->offsets.size();
}

int MXTImageIterNext(void* handle, float* out_data, float* out_label) {
  return static_cast<ImageIter*>(handle)->NextBatch(out_data, out_label);
}

void MXTImageIterReset(void* handle) {
  static_cast<ImageIter*>(handle)->Reset();
}

void MXTImageIterFree(void* handle) { delete static_cast<ImageIter*>(handle); }

}  // extern "C"
