// Example external operator library — the TPU-native analog of the
// reference's lib_api custom-op libraries ([U:include/mxnet/lib_api.h],
// [U:example/extensions/lib_custom_op/]).  Ops are XLA FFI handlers; the
// loader (incubator_mxnet_tpu.library.load) dlopens this .so, reads the
// manifest from mxtpu_op_list(), registers each handler with
// jax.ffi.register_ffi_target, and exposes the op through the normal
// registry so `mx.nd.<name>` reaches it.
//
// Contract v1 (documented in library.py): elementwise f32 ops —
// one f32 buffer in, one f32 buffer out, same shape.
//
// Build: make -C native libmxtpu_custom_op.so
//   (needs the XLA FFI headers bundled with jaxlib: make
//    XLA_FFI_INCLUDE=$(python -c 'import jax.ffi; print(jax.ffi.include_dir())'))

#include <cmath>
#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error SquareImpl(ffi::Buffer<ffi::F32> x,
                             ffi::ResultBuffer<ffi::F32> y) {
  const float* in = x.typed_data();
  float* out = y->typed_data();
  const size_t n = x.element_count();
  for (size_t i = 0; i < n; ++i) out[i] = in[i] * in[i];
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    mxtpu_square_handler, SquareImpl,
    ffi::Ffi::Bind().Arg<ffi::Buffer<ffi::F32>>().Ret<ffi::Buffer<ffi::F32>>());

static ffi::Error SoftSignImpl(ffi::Buffer<ffi::F32> x,
                               ffi::ResultBuffer<ffi::F32> y) {
  const float* in = x.typed_data();
  float* out = y->typed_data();
  const size_t n = x.element_count();
  for (size_t i = 0; i < n; ++i) out[i] = in[i] / (1.0f + std::fabs(in[i]));
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    mxtpu_softsign_handler, SoftSignImpl,
    ffi::Ffi::Bind().Arg<ffi::Buffer<ffi::F32>>().Ret<ffi::Buffer<ffi::F32>>());

extern "C" {
// Manifest: "opname=handler_symbol" pairs, ';'-separated.  The loader
// resolves each handler symbol via dlsym and registers it.
const char* mxtpu_op_list() {
  return "ext_square=mxtpu_square_handler;ext_softsign=mxtpu_softsign_handler";
}
}
