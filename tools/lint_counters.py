#!/usr/bin/env python
"""Counter-name lint (tools/ci.sh ``profiler`` tier).

``profiler.incr`` is strict at runtime — an undeclared name raises — but a
counter site on a cold path can hide a typo until production.  This lint
greps every ``*.py`` in the tree for ``incr`` / ``_incr`` call sites with
a string-literal name and checks each against the declared set: the
``_counters`` dict literal in ``incubator_mxnet_tpu/profiler.py`` (parsed
with ``ast`` — no jax import needed) plus any ``declare_counter("...")``
literals found in the tree.

It ALSO (ISSUE 7) diffs the declared set against the canonical counter
table in ``docs/observability.md`` ("Counter reference" section): a
counter added without a doc row — or documented after removal — fails the
profiler CI tier, so code and doc cannot drift.

Exit 0 = every literal declared AND the doc table in sync; 1 = violations
(listed on stderr).
"""
from __future__ import annotations

import ast
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIRS = ("incubator_mxnet_tpu", "tools", "benchmark", "tests", "example")
INCR_RE = re.compile(r"\b_?incr\(\s*[\"']([A-Za-z0-9_]+)[\"']")
DECLARE_RE = re.compile(r"\bdeclare_counter\(\s*[\"']([A-Za-z0-9_]+)[\"']")


def declared_counters():
    """Keys of the ``_counters = {...}`` literal in profiler.py."""
    path = os.path.join(ROOT, "incubator_mxnet_tpu", "profiler.py")
    with open(path) as f:
        tree = ast.parse(f.read(), path)
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "_counters"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            return {ast.literal_eval(k) for k in node.value.keys}
    raise SystemExit("lint_counters: no _counters dict literal in profiler.py")


DOC_PATH = os.path.join(ROOT, "docs", "observability.md")
DOC_ROW_RE = re.compile(r"^\|\s*`([A-Za-z0-9_]+)`")


def doc_counters():
    """Counter names from the ``## Counter reference`` table in
    docs/observability.md (first backticked cell of each row)."""
    names = set()
    in_section = False
    with open(DOC_PATH) as f:
        for line in f:
            if line.startswith("## "):
                in_section = line.strip().lower() == "## counter reference"
            elif in_section:
                m = DOC_ROW_RE.match(line)
                if m:
                    names.add(m.group(1))
    return names


def iter_py_files():
    for d in SCAN_DIRS:
        base = os.path.join(ROOT, d)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [x for x in dirnames
                           if x not in (".git", "__pycache__")]
            for fn in filenames:
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def main():
    declared = declared_counters()
    files = {p: open(p, errors="replace").read() for p in iter_py_files()}
    for text in files.values():  # pass 1: extensions opt in via declare
        declared |= set(DECLARE_RE.findall(text))
    violations = []
    for path, text in files.items():  # pass 2: check every incr literal
        for i, line in enumerate(text.splitlines(), 1):
            for name in INCR_RE.findall(line):
                if name not in declared:
                    violations.append((os.path.relpath(path, ROOT), i, name))
    if violations:
        for path, line, name in violations:
            print(f"{path}:{line}: undeclared profiler counter {name!r}",
                  file=sys.stderr)
        return 1
    # pass 3: the docs/observability.md counter table must mirror the
    # IN-TREE declared set exactly (declare_counter() extensions are
    # runtime opt-ins — tests register throwaways — and stay out of it)
    intree = declared_counters()
    documented = doc_counters()
    drift = 0
    for name in sorted(intree - documented):
        print(f"docs/observability.md: counter {name!r} declared in "
              "profiler._counters but missing from the Counter reference "
              "table", file=sys.stderr)
        drift += 1
    for name in sorted(documented - intree):
        print(f"docs/observability.md: counter {name!r} documented but not "
              "declared in profiler._counters (stale row?)", file=sys.stderr)
        drift += 1
    if drift:
        return 1
    print(f"lint_counters OK: {len(declared)} declared counters, all "
          f"incr() literals match, doc table in sync "
          f"({len(documented)} rows)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
