#!/usr/bin/env python
"""Counter-name lint (tools/ci.sh ``profiler`` tier).

``profiler.incr`` is strict at runtime — an undeclared name raises — but a
counter site on a cold path can hide a typo until production.  This lint
greps every ``*.py`` in the tree for ``incr`` / ``_incr`` call sites with
a string-literal name and checks each against the declared set: the
``_counters`` dict literal in ``incubator_mxnet_tpu/profiler.py`` (parsed
with ``ast`` — no jax import needed) plus any ``declare_counter("...")``
literals found in the tree.

Exit 0 = every literal declared; 1 = violations (listed on stderr).
"""
from __future__ import annotations

import ast
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_DIRS = ("incubator_mxnet_tpu", "tools", "benchmark", "tests", "example")
INCR_RE = re.compile(r"\b_?incr\(\s*[\"']([A-Za-z0-9_]+)[\"']")
DECLARE_RE = re.compile(r"\bdeclare_counter\(\s*[\"']([A-Za-z0-9_]+)[\"']")


def declared_counters():
    """Keys of the ``_counters = {...}`` literal in profiler.py."""
    path = os.path.join(ROOT, "incubator_mxnet_tpu", "profiler.py")
    with open(path) as f:
        tree = ast.parse(f.read(), path)
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "_counters"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            return {ast.literal_eval(k) for k in node.value.keys}
    raise SystemExit("lint_counters: no _counters dict literal in profiler.py")


def iter_py_files():
    for d in SCAN_DIRS:
        base = os.path.join(ROOT, d)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [x for x in dirnames
                           if x not in (".git", "__pycache__")]
            for fn in filenames:
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def main():
    declared = declared_counters()
    files = {p: open(p, errors="replace").read() for p in iter_py_files()}
    for text in files.values():  # pass 1: extensions opt in via declare
        declared |= set(DECLARE_RE.findall(text))
    violations = []
    for path, text in files.items():  # pass 2: check every incr literal
        for i, line in enumerate(text.splitlines(), 1):
            for name in INCR_RE.findall(line):
                if name not in declared:
                    violations.append((os.path.relpath(path, ROOT), i, name))
    if violations:
        for path, line, name in violations:
            print(f"{path}:{line}: undeclared profiler counter {name!r}",
                  file=sys.stderr)
        return 1
    print(f"lint_counters OK: {len(declared)} declared counters, "
          "all incr() literals match")
    return 0


if __name__ == "__main__":
    sys.exit(main())
