#!/usr/bin/env python
"""Diagnostic: lower the BERT SPMD train step to optimized HLO (CPU, no chip
time) and report convert/transpose/fusion counts + biggest fp32 tensors.
Used to verify AMP/layout perf changes actually land in the compiled graph.

Usage: python tools/inspect_step.py [--layers N] [--dump FILE]
"""
import argparse
import collections
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if "--tpu" in sys.argv:
    sys.argv.remove("--tpu")
else:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--dump", default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import amp
    from incubator_mxnet_tpu.gluon.model_zoo.bert import BERTModel, BERTForPretrain
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer
    from incubator_mxnet_tpu.random import get_key

    B, S = args.batch, 128
    amp.init("bfloat16")
    mx.random.seed(0)
    bert = BERTModel(vocab_size=args.vocab, units=768, hidden_size=3072,
                     num_layers=args.layers, num_heads=12, max_length=512,
                     dropout=0.1)
    net = BERTForPretrain(bert, vocab_size=args.vocab)
    net.initialize()
    rng = np.random.RandomState(0)
    tok = mx.nd.array(rng.randint(0, args.vocab, (B, S)), dtype="int32")
    seg = mx.nd.zeros((B, S), dtype="int32")
    labels = mx.nd.array(rng.randint(0, args.vocab, (B, S)), dtype="int32")
    net(mx.nd.zeros((2, S), dtype="int32"), mx.nd.zeros((2, S), dtype="int32"))

    def mlm_loss(out, label):
        from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce
        mlm_logits, _ = out
        return NDArray(streaming_softmax_ce(mlm_logits._data, label._data).mean(axis=-1))

    mesh = make_mesh()
    trainer = SPMDTrainer(net, mlm_loss, "adam", {"learning_rate": 1e-4}, mesh=mesh)
    arrays = trainer.shard_batch(tok, seg, labels)
    fn = trainer._build_step(arrays)
    lowered = fn.lower(
        get_key(), jnp.float32(1), jnp.float32(1e-4), jnp.float32(1.0 / B),
        trainer._param_arrays, trainer._opt_states, *arrays,
    )
    hlo = lowered.compile().as_text()
    if args.dump:
        with open(args.dump, "w") as f:
            f.write(hlo)

    counts = collections.Counter()
    big_converts = collections.Counter()
    big_transposes = collections.Counter()
    # HLO line shape:  %name = f32[8,128,768]{2,1,0} convert(%arg)
    pat = re.compile(r"= *([a-z0-9]+)\[([0-9,]*)\][^ ]* +([\w\-]+)\(")
    for line in hlo.splitlines():
        m = pat.search(line)
        if not m:
            continue
        dt, dims, op = m.groups()
        counts[op] += 1
        if op in ("convert", "transpose", "copy"):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            if n >= B * S * 256:  # big tensors only
                tgt = big_converts if op == "convert" else big_transposes
                tgt[f"{op} {dt}[{dims}]"] += 1

    print("== op histogram (top 25) ==")
    for op, c in counts.most_common(25):
        print(f"  {op:22s} {c}")
    print("== big converts ==")
    for k, c in big_converts.most_common(20):
        print(f"  {c:3d}x {k}")
    print("== big transposes/copies ==")
    for k, c in big_transposes.most_common(20):
        print(f"  {c:3d}x {k}")


if __name__ == "__main__":
    main()
