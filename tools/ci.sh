#!/usr/bin/env bash
# One-command CI entry (the [U:ci/build.py] + runtime_functions.sh analog).
#
# Runs the evidence tiers in order and prints a per-tier summary:
#   1. unit1     — CPU suite, operator/gluon half (8-device virtual mesh)
#   2. unit2     — CPU suite, remaining fast tiers
#   2b. zoo      — all vision-zoo entries (own tier: ~8 min on 1 core)
#   3. dist      — multi-process kvstore/launcher tier (incl. dist_async)
#   4. examples  — example-script smoke tier
#   5. bench     — bench.py smoke on whatever backend is present (CPU-safe)
#   6. profiler  — tracing-subsystem smoke: tiny train loop with the span
#                  recorder on, chrome-trace file must parse, trace_report
#                  must exit 0, every profiler.incr(...) literal in the
#                  tree must name a declared counter AND the
#                  docs/observability.md counter table must match it
#                  (lint_counters.py), plus the 2-process cluster smoke
#                  (dist_trace_smoke.py): per-rank traces merge into one
#                  offset-corrected timeline and rank 0's /metrics scrape
#                  aggregates every rank; memory_smoke.py: the device-
#                  memory ledger must attribute the train+serve footprint
#                  to named owners, the trace must carry a memory counter
#                  track, and a forced budget breach must produce exactly
#                  one postmortem
#   7. chaos     — fault-injection tier (fixed seed): wire drops/dups/kills
#                  against the async PS with exactly-once accounting, the
#                  2-worker chaos training acceptance run, the
#                  standalone-server SIGKILL+resume subprocess test, and
#                  the elastic dist_sync tier (tests/test_elastic.py):
#                  supervisor kill/resume smoke with exact-loss resume
#                  and the torn-checkpoint restore-refusal matrix
#   8. serving   — inference serving tier: the open-loop throughput-at-SLO
#                  harness in --smoke mode (exits non-zero if any batch
#                  recompiled after warmup — the bucket-miss regression
#                  guard), the continuous-batching generation harness in
#                  --smoke mode (guard raise mode armed; non-zero exit on
#                  any post-warmup compile in the decode loop), plus the
#                  non-slow serving + generation tests
#   9. io        — input-pipeline tier: the synthetic host-bound harness in
#                  --smoke mode (exits non-zero if the async infeed's
#                  consumer stalled after warmup — the host-starvation
#                  regression guard) plus the fast pipeline tests
#  10. parallel  — pipeline/expert-parallel tier: the schedule harness in
#                  --smoke mode (exits non-zero on post-warmup recompiles
#                  in a scheduled step or a bubble-acceptance failure)
#                  plus the fast schedule + MoE + SPMD-parallel tests
#  11. comm      — quantized-collectives tier: the collectives harness in
#                  --smoke mode (exits non-zero on post-warmup recompiles
#                  in the compressed SPMD step, or if the int8 tier stops
#                  moving >= 3.5x fewer gradient bytes than fp32 on either
#                  path — counter-verified) plus the compression tests
#  12. fold      — step-fold tier: the opperf harness in --smoke mode
#                  (exits non-zero if a steady-state folded step is ever
#                  more than ONE host dispatch or recompiles after
#                  warmup) plus the fast fold/overlap tests
#  13. scaling   — goodput/scaling tier: the scaling-curve harness in
#                  --smoke mode (samples/sec-vs-N over the CPU mesh with
#                  per-point goodput ledgers; exits non-zero on a
#                  post-warmup recompile, an efficiency-floor miss, or a
#                  live-vs-merged-trace attribution mismatch), the fast
#                  goodput-ledger tests, then tools/perf_history.py
#                  gating the bench trajectory + the fresh evidence
#                  against the committed baseline (outage rounds are
#                  classified backend_unavailable, never regressions)
#  14. tpu       — (opt-in: CI_TPU=1) on-chip correctness tier, needs a chip
#
# The unit tier is split in two so each invocation fits a ~10 min shell on
# a 1-core box (the full suite exceeds one 600 s window there); `unit` is
# accepted as an alias for both halves.
#
# All output is tee'd to ci_logs/ci_<timestamp>.log and the final summary
# is ALSO written to ci_logs/last_summary.txt, so a round's evidence
# survives a dead terminal.
#
# Usage:  tools/ci.sh [tier ...]   # default: all but the opt-in tpu tier
# Env:    CI_TPU=1 adds the tpu tier; CI_PYTEST_ARGS extra pytest flags.
set -u -o pipefail

cd "$(dirname "$0")/.."

mkdir -p ci_logs
STAMP=$(date -u +%Y%m%d_%H%M%S)
LOG="ci_logs/ci_${STAMP}.log"
exec > >(tee -a "$LOG") 2>&1
TEE_PID=$!
# drain the tee before exiting or the log loses its tail (the summary)
finish() { exec >&- 2>&-; [ -n "${TEE_PID:-}" ] && wait "$TEE_PID" 2>/dev/null; }
trap finish EXIT

# The ambient axon tunnel (PALLAS_AXON_POOL_IPS) routes every eager op to a
# remote chip; CI tiers other than `tpu` must run on the virtual CPU mesh.
CPU_ENV=(env -u PALLAS_AXON_POOL_IPS
         JAX_PLATFORMS=cpu
         XLA_FLAGS="--xla_force_host_platform_device_count=8")

# the operator/gluon half of the suite — the slow compile-heavy files
UNIT1_FILES=(tests/test_operator.py tests/test_operator_core.py
             tests/test_operator_nn.py tests/test_gluon.py
             tests/test_gluon_contrib.py tests/test_rnn.py
             tests/test_optimizer.py)

TIERS=()
for t in "$@"; do
    if [ "$t" = unit ]; then TIERS+=(unit1 unit2); else TIERS+=("$t"); fi
done
[ ${#TIERS[@]} -eq 0 ] && TIERS=(unit1 unit2 zoo dist examples bench profiler chaos serving io parallel comm fold scaling)
[ "${CI_TPU:-0}" = "1" ] && TIERS+=(tpu)

declare -A RESULT
FAIL=0

run_tier() {
    local name="$1"; shift
    echo "===================================================================="
    echo "== tier: $name"
    echo "===================================================================="
    local t0=$SECONDS
    "$@"
    local rc=$?
    if [ $rc -eq 0 ]; then
        RESULT[$name]="PASS ($((SECONDS - t0))s)"
    elif [ $rc -eq 5 ]; then
        # pytest 5 = nothing collected (e.g. a -k filter matching only the
        # other unit half) — not a failure of the selected tests
        RESULT[$name]="PASS/no-tests ($((SECONDS - t0))s)"
    else
        RESULT[$name]="FAIL ($((SECONDS - t0))s)"
        FAIL=1
    fi
}

IGNORE1=()
for f in "${UNIT1_FILES[@]}"; do IGNORE1+=(--ignore="$f"); done

for tier in "${TIERS[@]}"; do
    case "$tier" in
        unit1)
            run_tier unit1 "${CPU_ENV[@]}" python -m pytest "${UNIT1_FILES[@]}" -q \
                ${CI_PYTEST_ARGS:-}
            ;;
        unit2)
            run_tier unit2 "${CPU_ENV[@]}" python -m pytest tests/ -q \
                "${IGNORE1[@]}" \
                --ignore=tests/test_examples.py --ignore=tests/test_dist.py \
                --ignore=tests/test_gluon_model_zoo.py \
                ${CI_PYTEST_ARGS:-}
            ;;
        zoo)
            # all 34 vision-zoo entries (eval_shape at full size + one
            # numeric forward per family) — ~8 min on a 1-core box, so a
            # tier of its own
            run_tier zoo "${CPU_ENV[@]}" python -m pytest \
                tests/test_gluon_model_zoo.py -q ${CI_PYTEST_ARGS:-}
            ;;
        dist)
            run_tier dist "${CPU_ENV[@]}" python -m pytest tests/test_dist.py -q \
                ${CI_PYTEST_ARGS:-}
            ;;
        examples)
            run_tier examples "${CPU_ENV[@]}" python -m pytest tests/test_examples.py -q \
                ${CI_PYTEST_ARGS:-}
            ;;
        bench)
            # CPU smoke: tiny batch, 1-2 steps — proves the headline path runs
            run_tier bench "${CPU_ENV[@]}" \
                env MXNET_TPU_BENCH_BATCH=8 python bench.py
            ;;
        profiler)
            # tracing smoke: recorder-on train loop -> valid chrome trace,
            # trace_report runs clean, counter-name lint passes (incl. the
            # docs/observability.md counter-table diff), the 2-process
            # cluster smoke: per-rank traces -> offset-corrected merge with
            # one process row per rank, rank-0 /metrics scrape sees both
            # ranks, straggler attribution fires exactly once — and the
            # compile-observability smoke: short train+serve run where
            # compile_report must list every jit site and attribute a
            # deliberately forced shape drift to the exact argument
            # per-run trace path: concurrent ci.sh runs on one box must
            # not race on a shared file
            run_tier profiler "${CPU_ENV[@]}" bash -c '
                set -e
                trace="/tmp/ci_profiler_trace_$$.json"
                trap "rm -f \"$trace\"" EXIT
                python tools/profiler_smoke.py --out "$trace"
                python tools/trace_report.py "$trace" --top 10 >/dev/null
                python tools/lint_counters.py
                python tools/dist_trace_smoke.py
                python tools/compile_smoke.py >/dev/null
                python tools/memory_smoke.py >/dev/null'
            ;;
        chaos)
            # deterministic fault injection: the seed pins the p= fault
            # schedules so a chaos failure reproduces exactly.
            # test_elastic.py adds the dist_sync elastic tier: the 2-proc
            # supervisor kill/resume acceptance (proc.kill_rank at a fixed
            # step, exact-loss resume, zero steady-state recompiles) and
            # the torn-checkpoint restore-refusal matrix (SIGKILL at every
            # elastic.kill_* point)
            run_tier chaos "${CPU_ENV[@]}" env MXNET_FAULT_SEED=0 \
                python -m pytest tests/test_chaos.py tests/test_elastic.py \
                -q ${CI_PYTEST_ARGS:-}
            ;;
        serving)
            # serving tier: the smoke harnesses ARE the regression guards
            # (serving.py exits non-zero if any batch bound/compiled after
            # warmup; generation.py exits non-zero if the continuous-
            # batching decode loop compiled anything post-warmup under
            # guard raise mode), then the fast serving + generation tests
            run_tier serving "${CPU_ENV[@]}" bash -c '
                set -e
                python benchmark/opperf/serving.py --smoke >/dev/null
                python benchmark/opperf/generation.py --smoke >/dev/null
                python -m pytest tests/test_serving.py tests/test_generation.py -q -m "not slow" '"${CI_PYTEST_ARGS:-}"
            ;;
        io)
            # input-pipeline tier: the smoke harness IS the
            # host-starvation regression guard (non-zero exit if the
            # infeed's consumer stalled after warmup at the autotuned
            # depth), then the fast pipeline tests
            run_tier io "${CPU_ENV[@]}" bash -c '
                set -e
                python benchmark/opperf/input_pipeline.py --smoke >/dev/null
                python -m pytest tests/test_io_pipeline.py -q -m "not slow" '"${CI_PYTEST_ARGS:-}"
            ;;
        parallel)
            # pipeline/expert-parallel tier: the opperf harness in
            # --smoke mode IS the regression guard (non-zero exit on any
            # post-warmup recompile in a scheduled step, or if 1F1B's
            # measured bubble stops beating GPipe's / leaves 1.5x of the
            # analytic (P-1)/(M+P-1) bound), then the fast schedule +
            # MoE + SPMD-parallel tests
            run_tier parallel "${CPU_ENV[@]}" bash -c '
                set -e
                python benchmark/opperf/pipeline.py --smoke >/dev/null
                python -m pytest tests/test_pipeline_moe.py tests/test_parallel.py -q -m "not slow" '"${CI_PYTEST_ARGS:-}"
            ;;
        comm)
            # quantized-collectives tier: the opperf harness in --smoke
            # mode IS the regression guard (non-zero exit on any
            # post-warmup recompile in the compressed SPMD step, or an
            # int8 bytes-on-wire ratio below the 3.5x acceptance floor on
            # either gradient path — per-HOP for the ring half of the
            # default psum/ring A/B), then the compression tests
            run_tier comm "${CPU_ENV[@]}" bash -c '
                set -e
                python benchmark/opperf/collectives.py --smoke >/dev/null
                python -m pytest tests/test_grad_compression.py -q -m "not slow" '"${CI_PYTEST_ARGS:-}"
            ;;
        fold)
            # step-fold tier: the opperf harness in --smoke mode IS the
            # regression guard (non-zero exit if the folded step stops
            # being exactly ONE host dispatch, or recompiles in steady
            # state after warmup), then the fast fold/overlap tests
            run_tier fold "${CPU_ENV[@]}" bash -c '
                set -e
                python benchmark/opperf/step_fold.py --smoke >/dev/null
                python benchmark/opperf/step_fold.py --k --smoke >/dev/null
                python -m pytest tests/test_step_fold.py -q -m "not slow" '"${CI_PYTEST_ARGS:-}"
            ;;
        scaling)
            # goodput/scaling tier: the harness in --smoke mode IS the
            # regression guard (each curve point is a fresh subprocess
            # under MXNET_COMPILE_GUARD=raise; non-zero exit on a
            # post-warmup recompile, an efficiency-floor miss, or if the
            # live numbers stop matching the merged per-rank trace
            # ledgers), the fast goodput-ledger tests, then perf_history
            # gates the BENCH trajectory + this evidence against the
            # committed baseline
            run_tier scaling "${CPU_ENV[@]}" bash -c '
                set -e
                ev="/tmp/ci_scaling_evidence_$$.json"
                trap "rm -f \"$ev\"" EXIT
                python benchmark/opperf/scaling.py --smoke --json "$ev" >/dev/null
                python -m pytest tests/test_goodput.py -q -m "not slow" '"${CI_PYTEST_ARGS:-}"'
                python tools/perf_history.py --scaling "$ev"'
            ;;
        tpu)
            # on-chip tier: runs under the ambient axon env (NOT cpu-cleaned)
            run_tier tpu env MXNET_TEST_CTX=tpu python -m pytest tpu_tests/ -q \
                ${CI_PYTEST_ARGS:-}
            ;;
        *)
            echo "unknown tier: $tier" >&2; exit 2
            ;;
    esac
done

{
    echo "===================================================================="
    echo "== CI summary ($STAMP, log: $LOG)"
    for tier in "${TIERS[@]}"; do
        printf '  %-10s %s\n' "$tier" "${RESULT[$tier]:-SKIPPED}"
    done
} | tee ci_logs/last_summary.txt
exit $FAIL
