#!/usr/bin/env bash
# One-command CI entry (the [U:ci/build.py] + runtime_functions.sh analog).
#
# Runs the four evidence tiers in order and prints a per-tier summary:
#   1. unit      — CPU suite on the 8-device virtual mesh (fast tiers)
#   2. dist      — multi-process kvstore/launcher tier
#   3. examples  — example-script smoke tier
#   4. bench     — bench.py smoke on whatever backend is present (CPU-safe)
#   5. tpu       — (opt-in: CI_TPU=1) on-chip correctness tier, needs a chip
#
# Usage:  tools/ci.sh [tier ...]      # default: unit dist examples bench
# Env:    CI_TPU=1 adds the tpu tier; CI_PYTEST_ARGS extra pytest flags.
set -u -o pipefail

cd "$(dirname "$0")/.."

# The ambient axon tunnel (PALLAS_AXON_POOL_IPS) routes every eager op to a
# remote chip; CI tiers 1-4 must run on the virtual CPU mesh.
CPU_ENV=(env -u PALLAS_AXON_POOL_IPS
         JAX_PLATFORMS=cpu
         XLA_FLAGS="--xla_force_host_platform_device_count=8")

TIERS=("$@")
[ ${#TIERS[@]} -eq 0 ] && TIERS=(unit dist examples bench)
[ "${CI_TPU:-0}" = "1" ] && TIERS+=(tpu)

declare -A RESULT
FAIL=0

run_tier() {
    local name="$1"; shift
    echo "===================================================================="
    echo "== tier: $name"
    echo "===================================================================="
    local t0=$SECONDS
    if "$@"; then
        RESULT[$name]="PASS ($((SECONDS - t0))s)"
    else
        RESULT[$name]="FAIL ($((SECONDS - t0))s)"
        FAIL=1
    fi
}

for tier in "${TIERS[@]}"; do
    case "$tier" in
        unit)
            run_tier unit "${CPU_ENV[@]}" python -m pytest tests/ -q \
                --ignore=tests/test_examples.py --ignore=tests/test_dist.py \
                ${CI_PYTEST_ARGS:-}
            ;;
        dist)
            run_tier dist "${CPU_ENV[@]}" python -m pytest tests/test_dist.py -q \
                ${CI_PYTEST_ARGS:-}
            ;;
        examples)
            run_tier examples "${CPU_ENV[@]}" python -m pytest tests/test_examples.py -q \
                ${CI_PYTEST_ARGS:-}
            ;;
        bench)
            # CPU smoke: tiny batch, 1-2 steps — proves the headline path runs
            run_tier bench "${CPU_ENV[@]}" \
                env MXNET_TPU_BENCH_BATCH=8 python bench.py
            ;;
        tpu)
            # on-chip tier: runs under the ambient axon env (NOT cpu-cleaned)
            run_tier tpu env MXNET_TEST_CTX=tpu python -m pytest tpu_tests/ -q \
                ${CI_PYTEST_ARGS:-}
            ;;
        *)
            echo "unknown tier: $tier" >&2; exit 2
            ;;
    esac
done

echo "===================================================================="
echo "== CI summary"
for tier in "${TIERS[@]}"; do
    printf '  %-10s %s\n' "$tier" "${RESULT[$tier]:-SKIPPED}"
done
exit $FAIL
