#!/usr/bin/env python
"""Fuse per-rank chrome-trace JSONs from ``profiler.dump()`` into ONE
Perfetto-viewable timeline (ISSUE 7 multi-rank trace aggregation).

Each rank of a dist_sync / dist_async run dumps its own trace with
``otherData.process`` metadata: rank, host, pid, the wall-clock instant of
its ts=0 (``epoch_unix``), and a midpoint-of-RTT clock-offset estimate
against the cluster reference (``clock_offset_s``; sampled over the PS
heartbeat wire or a one-shot mesh broadcast).  The merge:

* remaps every event's ``pid`` to the rank (one process row per rank,
  labeled ``rank N (host)`` and sorted by rank),
* shifts every timestamp onto the common corrected timeline
  (``corrected_unix = epoch_unix - clock_offset_s``, earliest rank = 0),
* carries each rank's counters/step-telemetry/process metadata under
  ``otherData.ranks``.

``--check`` validates the result the CI smoke relies on: one process row
per rank, B/E pairs that nest, and offset-corrected per-rank step spans
with monotone step ids.  Inputs and ``-o`` output may be ``.json.gz``.

Usage::

    python tools/trace_merge.py rank0.json rank1.json.gz -o merged.json \
                                [--check] [--expect-ranks 2]

Exit codes: 0 ok, 2 unreadable/invalid input or a failed --check.
"""
from __future__ import annotations

import argparse
import gzip
import json
import sys
from collections import defaultdict


def open_trace(path, mode="rt"):
    """Open a trace for reading, transparently gunzipping (by suffix or
    magic — a ``.json`` that is secretly gzip still loads)."""
    if "r" in mode:
        with open(path, "rb") as f:
            magic = f.read(2)
        if path.endswith(".gz") or magic == b"\x1f\x8b":
            return gzip.open(path, mode)
        return open(path, mode.replace("t", ""))
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    return open(path, mode.replace("t", ""))


def load_trace(path):
    """Load one trace document; bare-array traces are wrapped into the
    object form with empty metadata."""
    with open_trace(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):
        doc = {"traceEvents": doc, "otherData": {}}
    if not isinstance(doc.get("traceEvents"), list):
        raise ValueError("traceEvents is not a list")
    return doc


def merge_traces(paths):
    """Merge per-rank trace files into one document (see module doc)."""
    docs = [(p, load_trace(p)) for p in paths]
    ranks = {}
    for i, (path, doc) in enumerate(docs):
        proc = (doc.get("otherData") or {}).get("process") or {}
        rank = int(proc.get("rank", i))
        if rank in ranks:
            raise ValueError(
                f"duplicate rank {rank} ({ranks[rank]['source']} and "
                f"{path}): per-rank traces must carry distinct "
                "otherData.process.rank metadata")
        base = None
        if proc.get("epoch_unix") is not None:
            base = float(proc["epoch_unix"]) - float(
                proc.get("clock_offset_s") or 0.0)
        ranks[rank] = {"source": path, "doc": doc, "process": proc,
                       "base_unix": base}
    bases = [r["base_unix"] for r in ranks.values()
             if r["base_unix"] is not None]
    t0_unix = min(bases) if bases else None

    events = []
    other_ranks = {}
    for rank in sorted(ranks):
        entry = ranks[rank]
        doc, proc = entry["doc"], entry["process"]
        shift_us = ((entry["base_unix"] - t0_unix) * 1e6
                    if entry["base_unix"] is not None else 0.0)
        host = proc.get("host", "?")
        events.append({"ph": "M", "pid": rank, "name": "process_name",
                       "args": {"name": f"rank {rank} ({host})"}})
        events.append({"ph": "M", "pid": rank, "name": "process_sort_index",
                       "args": {"sort_index": rank}})
        for ev in doc["traceEvents"]:
            if not isinstance(ev, dict):
                continue
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # re-emitted above with the rank label
            ev = dict(ev)
            ev["pid"] = rank
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = ev["ts"] + shift_us
            events.append(ev)
        od = doc.get("otherData") or {}
        other_ranks[str(rank)] = {
            "source": entry["source"],
            "process": proc,
            "shift_us": shift_us,
            "counters": od.get("counters"),
            "steps": od.get("steps"),
            "memory_watermark_bytes": od.get("memory_watermark_bytes"),
            "memory": od.get("memory"),   # ledger/postmortems (ISSUE 12)
            "goodput": od.get("goodput"),  # run ledger (ISSUE 20)
        }
    # stable ts sort keeps each file's intra-instant B/E ordering (pairing
    # is per (pid, tid), so cross-rank interleaving at equal ts is inert)
    events.sort(key=lambda e: (0, e["ts"]) if isinstance(
        e.get("ts"), (int, float)) else (-1, 0.0))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"merged": True, "t0_unix": t0_unix,
                      "ranks": other_ranks},
    }


def check_merged(doc, expect_ranks=None):
    """Validate a merged trace: one labeled process row per rank, B/E
    pairs that nest per (pid, tid), and per-rank step spans whose ids are
    strictly monotone on the corrected timeline.  Raises ValueError;
    returns a summary dict."""
    events = doc["traceEvents"]
    names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names.setdefault(e["pid"], e["args"]["name"])
    span_pids = sorted({e["pid"] for e in events
                        if e.get("ph") in ("B", "E", "X")})
    if expect_ranks is not None:
        want = sorted(range(expect_ranks))
        if span_pids != want:
            raise ValueError(
                f"expected one process row per rank {want}, got {span_pids}")
    missing = [p for p in span_pids if p not in names]
    if missing:
        raise ValueError(f"process rows without a rank label: {missing}")

    stacks = defaultdict(list)
    step_ids = defaultdict(list)
    step_bounds = defaultdict(list)
    n_spans = 0
    for e in sorted((e for e in events if e.get("ph") in ("B", "E")),
                    key=lambda e: e["ts"]):
        k = (e["pid"], e["tid"])
        if e["ph"] == "B":
            stacks[k].append(e)
        else:
            if not stacks[k]:
                raise ValueError(f"unpaired E event at ts={e['ts']}")
            b = stacks[k].pop()
            n_spans += 1
            if b.get("cat") == "step":
                step_ids[e["pid"]].append((b["args"] or {}).get("step"))
                step_bounds[e["pid"]].append((b["ts"], e["ts"]))
    dangling = sum(len(s) for s in stacks.values())
    if dangling:
        raise ValueError(f"{dangling} B event(s) never closed")
    for pid, ids in step_ids.items():
        if any(i is None for i in ids):
            raise ValueError(f"rank {pid}: step span without a step id")
        if ids != sorted(ids) or len(set(ids)) != len(ids):
            raise ValueError(
                f"rank {pid}: step ids not strictly monotone on the "
                f"corrected timeline: {ids}")
        bounds = step_bounds[pid]
        for (b0, e0), (b1, _) in zip(bounds, bounds[1:]):
            if b1 < e0:
                raise ValueError(
                    f"rank {pid}: overlapping step spans after offset "
                    f"correction ({e0} > {b1})")
    # per-device memory counter tracks ("C" events) ride the merge with
    # their pid remapped to the rank — Perfetto shows one memory timeline
    # per rank row
    n_counters = sum(1 for e in events if e.get("ph") == "C")
    return {"ranks": span_pids,
            "labels": {p: names.get(p) for p in span_pids},
            "spans": n_spans,
            "counter_events": n_counters,
            "steps_per_rank": {p: len(v) for p, v in step_ids.items()}}


def goodput_summary(doc):
    """Cluster goodput from a merged trace's per-rank ledger snapshots
    (``otherData.ranks.*.goodput``, ISSUE 20)::

        {"ranks", "wall_s", "goodput", "buckets_s", "per_rank",
         "worst": {"rank", "goodput", "bucket", "bucket_s"}}

    Whole-job goodput is wall-weighted (sum compute / sum wall) — the
    same aggregation ``profiler.cluster_goodput()`` computes live over
    the heartbeat piggyback, recomputed offline from the dumps.  Returns
    None when no rank carried a ledger."""
    rank_snaps = []
    for rank, entry in sorted(((doc.get("otherData") or {}).get("ranks")
                               or {}).items(), key=lambda kv: int(kv[0])):
        gp = (entry or {}).get("goodput")
        if isinstance(gp, dict) and (gp.get("wall_s") or 0) > 0:
            rank_snaps.append((int(rank), gp))
    if not rank_snaps:
        return None
    tot_wall = sum(gp["wall_s"] for _, gp in rank_snaps)
    buckets = {}
    per_rank = {}
    for rank, gp in rank_snaps:
        for k, v in (gp.get("buckets_s") or {}).items():
            buckets[k] = buckets.get(k, 0.0) + (v or 0.0)
        per_rank[rank] = {"wall_s": gp["wall_s"],
                          "goodput": gp.get("goodput"),
                          "top_overhead": gp.get("top_overhead") or []}
    worst_rank, worst = min(rank_snaps,
                            key=lambda r: r[1].get("goodput") or 0.0)
    wtop = (worst.get("top_overhead") or [[None, 0.0]])[0]
    return {
        "ranks": len(rank_snaps),
        "wall_s": round(tot_wall, 6),
        "goodput": (round(buckets.get("compute", 0.0) / tot_wall, 6)
                    if tot_wall > 0 else None),
        "buckets_s": {k: round(v, 6) for k, v in buckets.items()},
        "per_rank": per_rank,
        "worst": {"rank": worst_rank, "goodput": worst.get("goodput"),
                  "bucket": wtop[0], "bucket_s": wtop[1]},
    }


def format_goodput(summary):
    """Human-readable ``--goodput`` section lines."""
    lines = [f"goodput: {summary['ranks']} rank(s), wall "
             f"{summary['wall_s']:.3f} s, job goodput "
             f"{(summary['goodput'] or 0) * 100:.1f}%"]
    for rank, row in sorted(summary["per_rank"].items()):
        top = ", ".join(f"{k} {v:.3f}s" for k, v in row["top_overhead"])
        lines.append(f"  rank {rank}: wall {row['wall_s']:.3f} s, goodput "
                     f"{(row['goodput'] or 0) * 100:.1f}%"
                     + (f" ({top})" if top else ""))
    w = summary["worst"]
    if w["bucket"]:
        lines.append(f"  worst: rank {w['rank']} "
                     f"({(w['goodput'] or 0) * 100:.1f}%) — top overhead "
                     f"{w['bucket']} {w['bucket_s']:.3f} s")
    return lines


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("traces", nargs="+",
                   help="per-rank chrome-trace JSON(.gz) from profiler.dump()")
    p.add_argument("-o", "--out", default="merged_trace.json",
                   help="merged output path (.gz compresses)")
    p.add_argument("--check", action="store_true",
                   help="validate the merged trace (rows/pairing/step "
                        "monotonicity) and fail loudly when broken")
    p.add_argument("--expect-ranks", type=int, default=None,
                   help="with --check: require exactly ranks 0..N-1")
    p.add_argument("--goodput", action="store_true",
                   help="print the cluster goodput section (per-rank "
                        "ledgers + wall-weighted job goodput)")
    args = p.parse_args(argv)
    try:
        merged = merge_traces(args.traces)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"trace_merge: invalid input: {e}", file=sys.stderr)
        return 2
    if args.check:
        try:
            summary = check_merged(merged, expect_ranks=args.expect_ranks)
        except ValueError as e:
            print(f"trace_merge: merged trace failed validation: {e}",
                  file=sys.stderr)
            return 2
        print(f"trace_merge check OK: ranks {summary['ranks']}, "
              f"{summary['spans']} spans, "
              f"{summary['counter_events']} counter events, steps/rank "
              f"{summary['steps_per_rank']}")
    if args.goodput:
        gp = goodput_summary(merged)
        if gp is None:
            print("goodput: no per-rank ledger in these traces "
                  "(pre-ISSUE-20 dumps?)")
        else:
            print("\n".join(format_goodput(gp)))
    with open_trace(args.out, "wt") as f:
        json.dump(merged, f)
    print(f"merged {len(args.traces)} trace(s) -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
