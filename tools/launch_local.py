#!/usr/bin/env python
"""Single-host multi-process launcher — the [U:tools/launch.py] local-mode
analog ([U:3rdparty/dmlc-core/tracker/dmlc_tracker/local.py]).

Spawns N worker processes on this host with the DMLC_* environment the
reference's tracker sets; the framework's KVStoreDist maps that onto
``jax.distributed.initialize`` (worker 0's in-process coordinator plays the
scheduler role; there is no server tier — workers are SPMD peers).

Usage:
    python tools/launch_local.py -n 2 python my_training_script.py [args...]

Differences from the reference, by design (SURVEY.md §3.4): no -s/--num-servers
(accepted, ignored, for script compat — the PS tier is subsumed by XLA
collectives), and workers run on the CPU backend unless the caller overrides
JAX_PLATFORMS (multi-process TPU runs bootstrap via their pod runtime
instead).
"""
import argparse
import os
import socket
import subprocess
import sys


def reserve_port():
    """Bind a free port and KEEP the socket open (SO_REUSEADDR) until the
    workers have spawned — closing before spawn is a TOCTOU race where
    another process claims the port first."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    return s, s.getsockname()[1]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference-script compat; ignored (no PS tier)")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VALUE for the workers")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no worker command given")

    holder, port = reserve_port()
    # separate ephemeral port for the async parameter server: the old
    # convention (coordinator port + 1000) collides with whatever else
    # landed on that port — the flake behind the async dist-test failures
    ps_holder, ps_port = reserve_port()
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update(
            DMLC_ROLE="worker",
            DMLC_PS_ROOT_URI="127.0.0.1",
            DMLC_PS_ROOT_PORT=str(port),
            DMLC_NUM_WORKER=str(args.num_workers),
            DMLC_NUM_SERVER=str(args.num_servers),
            DMLC_WORKER_ID=str(rank),
        )
        env.setdefault("MXNET_ASYNC_PS_PORT", str(ps_port))
        env.setdefault("JAX_PLATFORMS", "cpu")
        if env["JAX_PLATFORMS"] == "cpu":
            # CPU workers must not register/claim a tunneled accelerator
            # backend (single-chip tunnels can't be shared by N processes)
            env.pop("PALLAS_AXON_POOL_IPS", None)
        for kv in args.env:
            k, _, v = kv.partition("=")
            env[k] = v
        procs.append(subprocess.Popen(args.command, env=env))

    holder.close()  # workers spawned; the coordinator (worker 0) binds next
    ps_holder.close()

    # poll instead of sequential waits: when one worker dies, its SPMD
    # peers block forever inside collectives — kill them immediately
    import time

    rc = 0
    live = list(procs)
    while live:
        for p in list(live):
            code = p.poll()
            if code is None:
                continue
            live.remove(p)
            if code != 0:
                rc = rc or code
                for q in procs:
                    if q.poll() is None:
                        q.kill()
        time.sleep(0.1)
    sys.exit(rc)


if __name__ == "__main__":
    main()
