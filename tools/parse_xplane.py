#!/usr/bin/env python
"""Aggregate device-op time from an xprof trace directory (.xplane.pb).

Usage: python tools/parse_xplane.py /tmp/trace_dir [topN]

Thin presentation layer over ``incubator_mxnet_tpu.profiler.iter_xplane_ops``
(the single shared xplane reader): sums event durations per HLO opcode and
per collapsed instruction name, printing the top-N with % of total device
time — the same table xprof's op_profile shows, without TensorBoard.
"""
import os

import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from incubator_mxnet_tpu.profiler import collapse_hlo_name, iter_xplane_ops

    trace_dir = sys.argv[1]
    topn = int(sys.argv[2]) if len(sys.argv) > 2 else 40

    by_opcode = defaultdict(int)
    by_inst = defaultdict(int)
    grand = 0
    for name, ps in iter_xplane_ops(trace_dir):
        grand += ps
        inst, opcode = collapse_hlo_name(name)
        by_opcode[opcode or inst] += ps
        by_inst[inst] += ps

    if not grand:
        raise SystemExit(f"no device 'XLA Ops' events under {trace_dir}")
    print(f"total device time: {grand/1e12*1000:.3f} ms over trace")
    print("== by opcode ==")
    for name, ps in sorted(by_opcode.items(), key=lambda kv: -kv[1])[:20]:
        print(f"  {ps/grand*100:5.2f}%  {ps/1e9:10.1f} ms  {name}")
    print("== by instruction (collapsed) ==")
    for name, ps in sorted(by_inst.items(), key=lambda kv: -kv[1])[:topn]:
        print(f"  {ps/grand*100:5.2f}%  {ps/1e9:10.1f} ms  {name[:100]}")


if __name__ == "__main__":
    main()
