#!/usr/bin/env bash
# Round-5 on-chip evidence capture — run the COMPLETE measurement set the
# moment the axon tunnel is healthy.  Each step appends to
# docs/BENCH_EVIDENCE_r05.txt; nothing here stops the sequence (a step
# failure records the error JSON and moves on).
#
# Usage: tools/r05_evidence.sh            # everything
#        tools/r05_evidence.sh bench     # just the five-config bench set
set -u
cd "$(dirname "$0")/.."

EV=docs/BENCH_EVIDENCE_r05.txt
WHAT="${1:-all}"
stamp() { date -u +%FT%TZ; }

note() { echo "[$(stamp)] $*" | tee -a "$EV"; }

# commit evidence after EVERY section: the round-5 box was reset mid-capture
# once already, wiping an uncommitted evidence file (ROUND5_NOTES.md)
checkpoint() {
    # per-file add (git add is all-or-nothing on a missing pathspec, and
    # TPU_TIER_LOG_r05.txt does not exist until the tier section runs);
    # commit constrained to the evidence paths so staged code can't be
    # swept into a log-only commit
    local f paths=()
    for f in docs/BENCH_EVIDENCE_r05.txt docs/TPU_TIER_LOG_r05.txt "$EV".err; do
        [ -e "$f" ] && { git add -- "$f" 2>/dev/null || true; paths+=("$f"); }
    done
    [ "${#paths[@]}" -gt 0 ] || return 0
    git commit -q -m "Evidence checkpoint: $1 ($(stamp))" \
        -m "No-Verification-Needed: evidence log checkpoint, no code change" \
        -- "${paths[@]}" || true
}

run_bench() {
    local tag="$1"; shift
    note "== bench: $tag ($*)"
    env "$@" timeout 3600 python bench.py 2>>"$EV".err | tee -a "$EV"
}

echo "# round-5 evidence, started $(stamp)" >> "$EV"

if [ "$WHAT" = all ] || [ "$WHAT" = bench ]; then
    # the five-config set (VERDICT item 1): BERT gate number first
    run_bench bert
    run_bench bert-repeat2
    run_bench bert-repeat3
    run_bench bert-ln-custom MXNET_TPU_LN_CUSTOM_BWD=1
    run_bench resnet50      MXNET_TPU_BENCH=resnet50
    run_bench resnet50-pallas-bn MXNET_TPU_BENCH=resnet50 MXNET_TPU_PALLAS_BN=1
    run_bench transformer   MXNET_TPU_BENCH=transformer
    # 360-step window: same amortization argument as the BERT 180-step
    # window, valid only alongside the transformer window-sweep fit below
    run_bench transformer-360 MXNET_TPU_BENCH=transformer MXNET_TPU_BENCH_STEPS=360
    # engine-bulking A/B: does scanning 8 steps per dispatch move tokens/s?
    run_bench transformer-bulk8 MXNET_TPU_BENCH=transformer MXNET_TPU_BENCH_BULK=8
    # score-layout A/B: does the bqhk score tensor avoid the profiled
    # head-split relayout copies? (numerics pinned identical by test)
    run_bench transformer-attn-bqhk MXNET_TPU_BENCH=transformer MXNET_TPU_ATTN_SCORE_LAYOUT=bqhk
    run_bench bert-attn-bqhk MXNET_TPU_ATTN_SCORE_LAYOUT=bqhk
    run_bench transformer-ln-custom MXNET_TPU_BENCH=transformer MXNET_TPU_LN_CUSTOM_BWD=1
    run_bench ssd-resnet18  MXNET_TPU_BENCH=ssd
    run_bench ssd-vgg16     MXNET_TPU_BENCH=ssd MXNET_TPU_BENCH_SSD_BACKBONE=vgg16
    run_bench yolo3         MXNET_TPU_BENCH=yolo3
    run_bench mnist         MXNET_TPU_BENCH=mnist
    checkpoint bench
fi

if [ "$WHAT" = all ] || [ "$WHAT" = profile ]; then
    note "== BERT 20-step xprof trace -> /tmp/r05_prof (parsed summary below)"
    MXNET_TPU_BENCH_PROFILE=/tmp/r05_prof MXNET_TPU_BENCH_STEPS=20 \
        timeout 3600 python bench.py 2>>"$EV".err | tee -a "$EV"
    timeout 600 python tools/parse_xplane.py /tmp/r05_prof 2>>"$EV".err | head -40 | tee -a "$EV" || true
    checkpoint profile
fi

if [ "$WHAT" = all ] || [ "$WHAT" = sweep ]; then
    note "== window sweep (VERDICT item 2)"
    timeout 7200 python tools/bench_window_sweep.py 2>>"$EV".err | tee -a "$EV"
    note "== transformer window sweep (gate corroboration at S=256)"
    MXNET_TPU_BENCH=transformer MXNET_TPU_BENCH_BATCH=32 \
        timeout 7200 python tools/bench_window_sweep.py 2>>"$EV".err | tee -a "$EV"
    checkpoint sweep
fi

if [ "$WHAT" = all ] || [ "$WHAT" = control ]; then
    note "== pipeline time-sliced single-chip bound (VERDICT weak #6)"
    timeout 1800 python tools/bench_pipeline.py 4 512 2>>"$EV".err | tee -a "$EV"
    note "== long-context flash vs XLA crossover (exceeds-reference row)"
    timeout 1800 python tools/bench_longcontext.py 2>>"$EV".err | tee -a "$EV"
    note "== raw-JAX ResNet-50 control (VERDICT item 4a)"
    timeout 3600 python tools/resnet_control.py 2>>"$EV".err | tee -a "$EV"
    note "== Pallas fused BN A/B, stages 2+3 (VERDICT item 4b)"
    MXNET_TPU_BN_STAGE=2 timeout 1800 python tools/bench_fused_bn.py 2>>"$EV".err | tee -a "$EV"
    MXNET_TPU_BN_STAGE=3 timeout 1800 python tools/bench_fused_bn.py 2>>"$EV".err | tee -a "$EV"
    checkpoint control
fi

if [ "$WHAT" = all ] || [ "$WHAT" = tier ]; then
    note "== full-suite chip tier (VERDICT item 5) -> docs/TPU_TIER_LOG_r05.txt"
    tools/run_tpu_tier.sh docs/TPU_TIER_LOG_r05.txt 420 | tee -a "$EV"
    note "== tpu_tests family rows"
    MXNET_TEST_CTX=tpu timeout 3600 python -m pytest tpu_tests/ -q 2>&1 | tail -3 | tee -a "$EV"
    checkpoint tier
fi

note "== evidence capture complete"

# commit the evidence so a round-end snapshot can never race past it
git add docs/BENCH_EVIDENCE_r05.txt docs/TPU_TIER_LOG_r05.txt 2>/dev/null
git add "$EV".err 2>/dev/null || true
git -c user.name="$(git config user.name)" commit -q \
    -m "Round-5 on-chip evidence capture ($(stamp))" || true
echo "evidence committed (if changed)"
