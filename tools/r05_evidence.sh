#!/usr/bin/env bash
# Round-5 on-chip evidence capture — run the COMPLETE measurement set the
# moment the axon tunnel is healthy.  Each step appends to
# docs/BENCH_EVIDENCE_r05.txt; nothing here stops the sequence (a step
# failure records the error JSON and moves on).
#
# Usage: tools/r05_evidence.sh            # everything
#        tools/r05_evidence.sh bench     # just the five-config bench set
set -u
cd "$(dirname "$0")/.."

EV=docs/BENCH_EVIDENCE_r05.txt
WHAT="${1:-all}"
stamp() { date -u +%FT%TZ; }

note() { echo "[$(stamp)] $*" | tee -a "$EV"; }

run_bench() {
    local tag="$1"; shift
    note "== bench: $tag ($*)"
    env "$@" timeout 3600 python bench.py 2>>"$EV".err | tee -a "$EV"
}

echo "# round-5 evidence, started $(stamp)" >> "$EV"

if [ "$WHAT" = all ] || [ "$WHAT" = bench ]; then
    # the five-config set (VERDICT item 1): BERT gate number first
    run_bench bert
    run_bench bert-repeat2
    run_bench bert-repeat3
    run_bench bert-ln-custom MXNET_TPU_LN_CUSTOM_BWD=1
    run_bench resnet50      MXNET_TPU_BENCH=resnet50
    run_bench resnet50-pallas-bn MXNET_TPU_BENCH=resnet50 MXNET_TPU_PALLAS_BN=1
    run_bench transformer   MXNET_TPU_BENCH=transformer
    run_bench transformer-ln-custom MXNET_TPU_BENCH=transformer MXNET_TPU_LN_CUSTOM_BWD=1
    run_bench ssd-resnet18  MXNET_TPU_BENCH=ssd
    run_bench ssd-vgg16     MXNET_TPU_BENCH=ssd MXNET_TPU_BENCH_SSD_BACKBONE=vgg16
    run_bench yolo3         MXNET_TPU_BENCH=yolo3
    run_bench mnist         MXNET_TPU_BENCH=mnist
fi

if [ "$WHAT" = all ] || [ "$WHAT" = profile ]; then
    note "== BERT 20-step xprof trace -> /tmp/r05_prof (parsed summary below)"
    MXNET_TPU_BENCH_PROFILE=/tmp/r05_prof MXNET_TPU_BENCH_STEPS=20 \
        timeout 3600 python bench.py 2>>"$EV".err | tee -a "$EV"
    timeout 600 python tools/parse_xplane.py /tmp/r05_prof 2>>"$EV".err | head -40 | tee -a "$EV" || true
fi

if [ "$WHAT" = all ] || [ "$WHAT" = sweep ]; then
    note "== window sweep (VERDICT item 2)"
    timeout 7200 python tools/bench_window_sweep.py 2>>"$EV".err | tee -a "$EV"
fi

if [ "$WHAT" = all ] || [ "$WHAT" = control ]; then
    note "== raw-JAX ResNet-50 control (VERDICT item 4a)"
    timeout 3600 python tools/resnet_control.py 2>>"$EV".err | tee -a "$EV"
    note "== Pallas fused BN A/B, stages 2+3 (VERDICT item 4b)"
    MXNET_TPU_BN_STAGE=2 timeout 1800 python tools/bench_fused_bn.py 2>>"$EV".err | tee -a "$EV"
    MXNET_TPU_BN_STAGE=3 timeout 1800 python tools/bench_fused_bn.py 2>>"$EV".err | tee -a "$EV"
fi

if [ "$WHAT" = all ] || [ "$WHAT" = tier ]; then
    note "== full-suite chip tier (VERDICT item 5) -> docs/TPU_TIER_LOG_r05.txt"
    tools/run_tpu_tier.sh docs/TPU_TIER_LOG_r05.txt 420 | tee -a "$EV"
    note "== tpu_tests family rows"
    MXNET_TEST_CTX=tpu timeout 3600 python -m pytest tpu_tests/ -q 2>&1 | tail -3 | tee -a "$EV"
fi

note "== evidence capture complete"

# commit the evidence so a round-end snapshot can never race past it
git add docs/BENCH_EVIDENCE_r05.txt docs/TPU_TIER_LOG_r05.txt 2>/dev/null
git add "$EV".err 2>/dev/null || true
git -c user.name="$(git config user.name)" commit -q \
    -m "Round-5 on-chip evidence capture ($(stamp))" || true
echo "evidence committed (if changed)"
