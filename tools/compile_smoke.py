#!/usr/bin/env python
"""Compile-observability smoke (tools/ci.sh ``profiler`` tier).

Drives a short train + serve run that touches the jit sites the compile
registry must see — eager dispatch, hybridized CachedOp, engine bulk
flush, fused optimizer group_apply, SPMD step, serving bucket warmup —
then DELIBERATELY drifts the SPMD batch shape after the steady-state
guard has armed and asserts:

* every expected site appears in the registry and in
  ``tools/compile_report.py``'s output;
* the forced drift is attributed to the EXACT offending argument
  (``input0``, shape drift) and counted as a steady-state recompile;
* serving registered one warmup compile per (batch, length) bucket pair
  and compiled NOTHING for in-bucket steady traffic;
* XLA cost accounting (MXNET_COMPILE_COST=1) captured FLOPs for the
  predictor-path compiles.

Exit 0 = all of the above; non-zero with a one-line diagnosis otherwise.
"""
import io
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("MXNET_COMPILE_COST", "1")

import numpy as np  # noqa: E402


def fail(msg):
    print(f"compile_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, engine, profiler
    from incubator_mxnet_tpu.gluon import Trainer, nn
    from incubator_mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    from incubator_mxnet_tpu.parallel import SPMDTrainer
    from incubator_mxnet_tpu.serving import InferenceServer
    import incubator_mxnet_tpu.symbol as S

    profiler.reset_compiles()
    profiler.disarm_compile_guard()

    # -- eager dispatch + bulk micro-graph ------------------------------
    a = mx.nd.array(np.ones((4, 4), np.float32))
    for _ in range(3):
        (a + a).asnumpy()           # level-1 cache compile (warmup=1)
    with engine.bulk(8):
        b = a + 1.0
        c = b * 2.0
    c.asnumpy()                     # flush -> engine.bulk compile

    # -- hybridized CachedOp + fused optimizer group_apply --------------
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).rand(4, 6).astype(np.float32))
    net(x)
    opt = mx.optimizer.create("adam", learning_rate=0.01)
    opt.aggregate_num = 100
    tr = Trainer(net.collect_params(), opt)
    for _ in range(2):
        with autograd.record():
            loss = (net(x) * net(x)).sum()
        loss.backward()
        tr.step(4)

    # -- SPMD train: 2 steps, guard arms, then a FORCED shape drift -----
    mx.random.seed(1)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net2.initialize()
    net2(mx.nd.zeros((2, 12)))
    loss_fn = SoftmaxCrossEntropyLoss()
    spmd = SPMDTrainer(net2, loss_fn, "sgd", {"learning_rate": 0.01})
    rng = np.random.RandomState(2)
    xb = rng.randn(16, 12).astype(np.float32)
    yb = rng.randint(0, 4, size=(16,)).astype(np.float32)
    steady0 = profiler.counters()["recompile_steady_state"]
    spmd.step(xb, yb)
    spmd.step(xb, yb)
    if not profiler.compile_guard_state()["armed"]:
        fail("guard not armed after the first SPMD step")
    # the deliberate drift: batch 16 -> 24 must recompile AND be caught
    spmd.step(rng.randn(24, 12).astype(np.float32),
              rng.randint(0, 4, size=(24,)).astype(np.float32))
    steady1 = profiler.counters()["recompile_steady_state"]
    if steady1 <= steady0:
        fail("forced shape drift was not counted as a steady-state "
             f"recompile ({steady0} -> {steady1})")

    # -- serving: bucket warmup + in-bucket steady traffic --------------
    S.symbol._reset_naming()
    data = S.var("data")
    fc = S.FullyConnected(data, num_hidden=6, flatten=False, name="fc1")
    sym = S.Activation(fc, act_type="tanh", name="t1")
    srng = np.random.RandomState(3)
    params = {"arg:fc1_weight": mx.nd.array(
                  srng.randn(6, 4).astype(np.float32)),
              "arg:fc1_bias": mx.nd.array(srng.randn(6).astype(np.float32))}
    srv = InferenceServer(sym, params, {"data": (None, 4)},
                          max_batch_size=4, max_queue_ms=20.0,
                          length_buckets=[8, 16], batch_buckets=[4],
                          name="compile_smoke")
    try:
        warm_sites = profiler.compile_stats()
        nwarm = warm_sites.get("serving.warmup", {}).get("count", 0)
        if nwarm < 2:   # 1 batch bucket x 2 length buckets
            fail(f"serving.warmup registered {nwarm} compiles, "
                 "expected one per bucket pair (>= 2)")
        before = profiler.counters()["compile_total"]
        for L in (3, 8, 12, 16, 5):
            out = srv.infer({"data": srng.rand(L, 4).astype(np.float32)},
                            timeout=30.0)
            if out.shape != (L, 6):
                fail(f"serving output shape {out.shape} != ({L}, 6)")
        if profiler.counters()["compile_total"] != before:
            fail("in-bucket steady serving traffic compiled something")
    finally:
        srv.close()

    # -- registry dump -> compile_report --------------------------------
    reg = profiler.compile_registry()
    path = os.path.join(tempfile.gettempdir(),
                        f"compile_smoke_{os.getpid()}.json")
    with open(path, "w") as f:
        json.dump(reg, f)
    try:
        import compile_report

        expected_sites = ["ops.dispatch", "engine.bulk", "block.cached_op",
                          "optimizer.group_apply", "spmd.step",
                          "serving.warmup"]
        for site in expected_sites:
            if site not in reg["sites"]:
                fail(f"site {site} missing from the registry "
                     f"(saw {sorted(reg['sites'])})")
        buf = io.StringIO()
        compile_report.report(compile_report.load_registry(path), out=buf)
        text = buf.getvalue()
        print(text)
        for site in expected_sites:
            if site not in text:
                fail(f"compile_report output misses site {site}")
        summ = compile_report.summarize(reg)
        culprit = next((c for c in summ["culprits"]
                        if c["site"] == "spmd.step"), None)
        if culprit is None:
            fail("compile_report found no spmd.step recompile culprit")
        if culprit["arg"] != "input0" or culprit["kind"] != "shape":
            fail("forced drift misattributed: expected (input0, shape), "
                 f"got ({culprit['arg']}, {culprit['kind']})")
        # MXNET_COMPILE_COST=1: the predictor-path warmup compiles must
        # carry XLA cost analysis
        if not any((r.get("cost") or {}).get("flops")
                   for r in reg["records"]
                   if r["site"] == "serving.warmup"):
            fail("no FLOPs captured for serving.warmup despite "
                 "MXNET_COMPILE_COST=1")
    finally:
        os.unlink(path)

    print("compile_smoke OK: "
          f"{len(reg['sites'])} sites, "
          f"{sum(e['count'] for e in reg['sites'].values())} compiles, "
          "forced drift attributed to input0 (shape), "
          f"{steady1 - steady0} steady-state recompile(s) caught")
    return 0


if __name__ == "__main__":
    sys.exit(main())
