#!/usr/bin/env python
"""ResNet-50 training-step HBM byte accounting (the VERDICT r4 roofline
proof): enumerate every feature map in resnet50_v1 at a given batch size,
count the minimum HBM traffic a conv+BN+ReLU training step must move, and
compare the implied bandwidth-bound step time against the measured one.

Traffic model per conv→BN→ReLU unit (bf16 activations), counting only
feature-map traffic (weights are ~25M params ≈ 50 MB bf16, noise at B=256):

  forward:  conv writes out (W) · BN stats read (R) · BN normalize
            read+write (R+W) · next-op read (R)           = 3R + 2W
  backward: d(out) write+read (W+R) · saved normalized act read for dgamma/
            dbeta+dx (R) · conv dgrad reads d(out) (counted above) and
            writes d(in) (= next unit's d(out), counted there) · wgrad
            reads saved input act (R)                      = 2R + 1W
            BN bwd second pass read (R)                    = 1R

  ≈ 6R + 3W  = 9 passes over each feature map per step (conservative:
  XLA's fusion can shave the normalize read by fusing into the consumer,
  and the one-pass stats trick already removed one stats pass).

Maxpool/residual-add/loss-head traffic is counted separately below.
"""
import sys


def feature_maps(B):
    """(name, elements) for every conv output in resnet50_v1 at batch B."""
    maps = [("conv0", B * 64 * 112 * 112)]
    cfg = [(3, 64, 56), (4, 128, 28), (6, 256, 14), (3, 512, 7)]
    for si, (blocks, f, hw) in enumerate(cfg, start=1):
        for b in range(blocks):
            maps.append((f"s{si}b{b}_c1", B * f * hw * hw))
            maps.append((f"s{si}b{b}_c2", B * f * hw * hw))
            maps.append((f"s{si}b{b}_c3", B * 4 * f * hw * hw))
            if b == 0:
                maps.append((f"s{si}b{b}_sc", B * 4 * f * hw * hw))
    return maps


def main():
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    HBM = float(sys.argv[2]) if len(sys.argv) > 2 else 819e9  # v5e GB/s
    bf16 = 2

    maps = feature_maps(B)
    conv_el = sum(e for _, e in maps)
    # residual adds: 4 stages' block outputs (read two, write one) ≈ 3
    # passes over each block's 4f map
    res_el = sum(e for n, e in maps if n.endswith("_c3"))
    pool_el = B * 64 * 56 * 56

    res_bytes = res_el * bf16 * 3 * 2        # fwd add + bwd split
    pool_bytes = pool_el * bf16 * 4          # fwd R/W + bwd select-scatter
    # optimizer: 25.6M params, fp32 momentum R/W + weight R/W + bf16 grad
    opt_bytes = 25.6e6 * (4 * 4 + 2 * 2)

    print(f"B={B}: {conv_el / B / 1e6:.1f}M conv-out elements/img "
          f"({len(maps)} feature maps)")
    # bracket the roofline between an optimistic (9-pass) and realistic
    # (11-pass: BN backward's two fused passes over both dy and x_hat)
    # per-feature-map traffic model
    for passes, label in ((9, "optimistic"), (11, "realistic")):
        conv_bytes = conv_el * bf16 * passes
        total = conv_bytes + res_bytes + pool_bytes + opt_bytes
        t_bw = total / HBM
        print(f"[{label}: {passes} passes/map] conv+BN "
              f"{conv_bytes / 1e9:.1f} GB + residual {res_bytes / 1e9:.1f} "
              f"+ pool {pool_bytes / 1e9:.1f} + opt {opt_bytes / 1e9:.1f} "
              f"= {total / 1e9:.1f} GB/step  -> floor "
              f"{t_bw * 1e3:.1f} ms ({B / t_bw:.0f} img/s)")
    # MXU floor: 12.3 GFLOP/img fwd+bwd (3x fwd 4.1), bf16 peak 197 TFLOP/s
    t_mxu = B * 12.3e9 / 197e12
    print(f"MXU-bound floor: {t_mxu * 1e3:.1f} ms ({B / t_mxu:.0f} img/s) "
          f"-> bandwidth-bound by ~5x at this batch")


if __name__ == "__main__":
    main()
