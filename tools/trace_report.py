#!/usr/bin/env python
"""Offline report over a chrome://tracing JSON written by ``profiler.dump()``.

Prints per-category span totals, the top-N longest spans, and a step-time
histogram — the quick-look attribution pass (host dispatch vs comms vs
device) MLPerf-style scaling work starts from, without opening Perfetto.
Optionally merges the device-side HLO-op table parsed from an xprof
capture directory (``--xplane``; the same ``iter_xplane_ops`` reader the
profiler's ``dumps()`` uses, so op attribution cannot drift between them).

Usage::

    python tools/trace_report.py profile.json [--top 15] [--bins 10]
                                 [--xplane DIR/mxtpu_profile]
    python tools/trace_report.py rank0.json rank1.json.gz --merge merged.json

With several traces, ``--merge PATH`` first fuses them through
``tools/trace_merge.py`` (rank-labeled process rows, offset-corrected
timestamps) and reports on the merged timeline.  ``.json.gz`` inputs are
read transparently.

Exit codes: 0 on success, 2 on an unreadable/invalid/empty trace file.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import trace_merge  # noqa: E402 — gz-aware loader + the --merge engine


def load_spans(path):
    """Parse the trace into completed spans ``(name, cat, ts_us, dur_us,
    step, args, pid)``.  Accepts both the object form ({"traceEvents":
    [...]}) and the bare-array form of the chrome trace spec (gzipped or
    not); pairs B/E events per thread with a stack and takes X (complete)
    events as-is."""
    if os.path.getsize(path) == 0:
        raise ValueError("empty trace file (0 bytes) — did profiler.dump() "
                         "run, or was the run killed mid-write?")
    with trace_merge.open_trace(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError("traceEvents is not a list")
    spans = []
    stacks = defaultdict(list)  # (pid, tid) -> [B events]
    for e in sorted((e for e in events if isinstance(e, dict)),
                    key=lambda e: e.get("ts", 0)):
        ph = e.get("ph")
        tkey = (e.get("pid"), e.get("tid"))
        if ph == "B":
            stacks[tkey].append(e)
        elif ph == "E":
            if not stacks[tkey]:
                raise ValueError(f"unpaired E event at ts={e.get('ts')}")
            b = stacks[tkey].pop()
            args = b.get("args") or {}
            spans.append((b.get("name", "<unk>"), b.get("cat", ""),
                          b["ts"], e["ts"] - b["ts"],
                          args.get("step"), args, e.get("pid")))
        elif ph == "X":
            args = e.get("args") or {}
            spans.append((e.get("name", "<unk>"), e.get("cat", ""),
                          e.get("ts", 0), e.get("dur", 0),
                          args.get("step"), args, e.get("pid")))
    dangling = sum(len(s) for s in stacks.values())
    if dangling:
        raise ValueError(f"{dangling} B event(s) never closed")
    other = doc.get("otherData", {}) if isinstance(doc, dict) else {}
    return spans, other


def histogram(values, bins):
    """ASCII histogram rows [(lo, hi, count, bar)] over ``values``."""
    if not values:
        return []
    lo, hi = min(values), max(values)
    if hi <= lo:
        hi = lo + 1e-9
    width = (hi - lo) / bins
    counts = [0] * bins
    for v in values:
        i = min(int((v - lo) / width), bins - 1)
        counts[i] += 1
    peak = max(counts)
    return [(lo + i * width, lo + (i + 1) * width, c,
             "#" * max(1, round(40 * c / peak)) if c else "")
            for i, c in enumerate(counts)]


# span-name -> goodput bucket for the header's trace-derived fallback
# (pre-ISSUE-20 dumps carry no otherData.goodput); mirrors
# profiler._GOODPUT_BUCKET_OF
_GOODPUT_SPAN_BUCKET = {
    "dispatch.cache_hit": "host", "dispatch.fallback": "host",
    "dispatch.raw": "host", "dispatch.backward": "host",
    "bulk.flush": "host", "fused.group_apply": "host",
    "spmd.shard_batch": "host", "io.wait": "data_wait",
    "kvstore.pushpull": "comm", "kvstore.push": "comm",
    "kvstore.pull": "comm", "compile.jit": "compile",
    "elastic.snapshot": "checkpoint", "elastic.restore": "checkpoint",
}


def run_summary(other, spans):
    """The numbers behind the run-summary header: ``(wall_s, goodput,
    top_overhead, source)``.  Prefers the embedded ledger
    (``otherData.goodput`` of a single-rank dump, or the per-rank
    ledgers of a merged trace aggregated the same way ``trace_merge
    --goodput`` does); falls back to approximating from the spans
    themselves (span extent as wall, bucket-mapped span sums as
    overhead) so pre-ledger traces still get a header."""
    gp = (other or {}).get("goodput")
    if isinstance(gp, dict) and (gp.get("wall_s") or 0) > 0:
        return (gp["wall_s"], gp.get("goodput"),
                gp.get("top_overhead") or [], "ledger")
    if (other or {}).get("ranks"):
        summ = trace_merge.goodput_summary({"otherData": other})
        if summ is not None:
            top3 = sorted(((k, v) for k, v in summ["buckets_s"].items()
                           if k != "compute" and v > 0),
                          key=lambda kv: -kv[1])[:3]
            return summ["wall_s"], summ["goodput"], top3, "ledger(merged)"
    if not spans:
        return None
    t0 = min(s[2] for s in spans)
    t1 = max(s[2] + s[3] for s in spans)
    wall_s = max(0.0, (t1 - t0) / 1e6)
    buckets = defaultdict(float)
    for name, _, _, dur, _, _, _ in spans:
        b = _GOODPUT_SPAN_BUCKET.get(name)
        if b is not None:
            buckets[b] += dur / 1e6
    overhead = sum(buckets.values())
    goodput = (max(0.0, wall_s - overhead) / wall_s) if wall_s > 0 else None
    top3 = sorted(buckets.items(), key=lambda kv: -kv[1])[:3]
    return wall_s, goodput, [[k, round(v, 6)] for k, v in top3], "spans"


def report(path, spans, other, top=15, bins=10, xplane=None,
           out=sys.stdout):
    w = out.write

    # the first line answers "where did the time go" (ISSUE 20)
    summ = run_summary(other, spans)
    if summ is not None:
        wall_s, goodput, top3, source = summ
        over = ", ".join(f"{k} {v:.3f}s" for k, v in top3) or "none"
        w(f"run: wall {wall_s:.3f} s, goodput "
          f"{(goodput or 0) * 100:.1f}% [{source}] — top overhead: "
          f"{over}\n")
    w(f"trace: {path} — {len(spans)} spans\n\n")

    by_cat = defaultdict(lambda: [0, 0.0])
    by_name = defaultdict(lambda: [0, 0.0])
    for name, cat, _, dur, _, _, _ in spans:
        by_cat[cat][0] += 1
        by_cat[cat][1] += dur
        by_name[(cat, name)][0] += 1
        by_name[(cat, name)][1] += dur

    w("Per-category totals (spans overlap across categories by design —\n"
      "a trainer.update span contains its fused/dispatch children):\n")
    w(f"{'category':<14}{'count':>8}{'total(ms)':>12}{'avg(us)':>10}\n")
    for cat, (cnt, tot) in sorted(by_cat.items(), key=lambda kv: -kv[1][1]):
        w(f"{cat:<14}{cnt:>8}{tot / 1e3:>12.3f}{tot / cnt:>10.1f}\n")

    w("\nPer-span-name totals:\n")
    w(f"{'name':<28}{'category':<12}{'count':>8}{'total(ms)':>12}\n")
    for (cat, name), (cnt, tot) in sorted(by_name.items(),
                                          key=lambda kv: -kv[1][1]):
        w(f"{name:<28}{cat:<12}{cnt:>8}{tot / 1e3:>12.3f}\n")

    w(f"\nTop {top} spans by duration:\n")
    w(f"{'name':<28}{'category':<12}{'step':>6}{'dur(ms)':>12}\n")
    for name, cat, _, dur, step, _, _ in sorted(spans,
                                                key=lambda s: -s[3])[:top]:
        w(f"{name:<28}{cat:<12}{step if step is not None else '-':>6}"
          f"{dur / 1e3:>12.3f}\n")

    # step-fold attribution (docs/step_fold.md): host-issued device
    # dispatches PER STEP — the number whole-program folding exists to
    # drive to 1.  A regression back to multi-dispatch (a fold falling
    # back, an op escaping the fold) is visible here as the median
    # jumping above 1 while trainer.step_fold spans are present.
    _DISPATCH_SPANS = frozenset((
        "dispatch.cache_hit", "dispatch.jit_compile", "dispatch.fallback",
        "dispatch.raw", "dispatch.backward", "bulk.flush",
        "fused.group_apply", "kvstore.pushpull", "kvstore.push",
        "kvstore.pull", "kvstore.bucketed_pushpull", "trainer.step_fold",
    ))
    # one bucket exchange = ONE dispatch: its kvstore.pushpull (or
    # push+pull) children nest inside the kvstore.bucketed_pushpull span
    # and must not count again
    _WIRE_CHILDREN = frozenset(("kvstore.pushpull", "kvstore.push",
                                "kvstore.pull"))
    buckets_by_pid = defaultdict(list)   # pid -> [(ts, ts_end)]
    for name, _cat, ts, dur, _, _, pid in spans:
        if name == "kvstore.bucketed_pushpull":
            buckets_by_pid[pid].append((ts, ts + dur))
    per_step = defaultdict(int)
    fold_steps = set()
    fold_k = {}                      # step -> K logical steps in that window
    for name, _cat, ts, _, step, args, pid in spans:
        if step is None or name not in _DISPATCH_SPANS:
            continue
        if name in _WIRE_CHILDREN and any(
                lo <= ts <= hi for lo, hi in buckets_by_pid.get(pid, ())):
            continue
        per_step[step] += 1
        if name == "trainer.step_fold":
            fold_steps.add(step)
            k = int((args or {}).get("k") or 1)
            if k > fold_k.get(step, 1):
                fold_k[step] = k
    if per_step:
        counts = sorted(per_step.values())
        med = counts[len(counts) // 2]
        w("\nHost dispatches per step "
          f"({len(per_step)} steps with dispatch spans): "
          f"median {med}, min {counts[0]}, max {counts[-1]}")
        if fold_steps:
            fold_counts = sorted(per_step[s] for s in fold_steps)
            w(f"; folded steps: {len(fold_steps)} "
              f"(median {fold_counts[len(fold_counts) // 2]} dispatch/step)")
        w("\n")
        # K-step fold (Trainer.fold_steps, k > 1): one trainer.step_fold
        # span covers K logical training steps (span arg "k"), so the
        # honest dispatch-amortisation number is per LOGICAL step — it
        # reads 1/K when the fold held and snaps back to ~1 on fallback.
        logical = sum(fold_k.get(s, 1) for s in per_step)
        if logical > len(per_step):
            disp = sum(per_step.values())
            w(f"Host dispatches per LOGICAL step (K-fold): {disp} "
              f"dispatches / {logical} logical steps = "
              f"{disp / logical:.3f}\n")

    # gradient-exchange payloads (docs/gradient_compression.md): the
    # bucketed-pushpull and spmd-step spans carry bytes_raw/bytes_wire
    # args; per-pid aggregation = per-RANK in a merged trace, so
    # straggler attribution can tell "slow network" from "big payload"
    payload = defaultdict(lambda: [0, 0, 0])   # pid -> [spans, raw, wire]
    # (pid, algo) -> [spans, raw, wire, hops, hop_bytes]: spans from the
    # quantized exchange also carry the ALGORITHM ("psum" = one fused
    # exchange, "ring" = explicit encoded ppermute hops) plus the
    # per-LOGICAL-step hop count and per-hop wire bytes, so the report
    # can show bytes per hop per algorithm — the ring acceptance is
    # hop-granular (ISSUE 19)
    by_algo = defaultdict(lambda: [0, 0, 0, 0, 0])
    for name, _cat, _, _, _, args, pid in spans:
        if args and "bytes_wire" in args and "bytes_raw" in args:
            row = payload[pid]
            row[0] += 1
            row[1] += int(args.get("bytes_raw") or 0)
            row[2] += int(args.get("bytes_wire") or 0)
            if args.get("algo"):
                k = int(args.get("k") or 1)
                arow = by_algo[(pid, str(args["algo"]))]
                arow[0] += 1
                arow[1] += int(args.get("bytes_raw") or 0)
                arow[2] += int(args.get("bytes_wire") or 0)
                arow[3] += int(args.get("hops") or 0) * k
                arow[4] = int(args.get("bytes_hop") or 0) or arow[4]
    if payload:
        w("\nComms payload per rank (raw = fp32 bytes the gradient "
          "exchange replaces, wire = encoded payload):\n")
        w(f"{'rank/pid':>9}{'spans':>7}{'raw(MB)':>11}{'wire(MB)':>11}"
          f"{'ratio':>8}\n")
        for pid, (cnt, raw, wire) in sorted(payload.items(),
                                            key=lambda kv: str(kv[0])):
            w(f"{pid!s:>9}{cnt:>7}{raw / 1e6:>11.3f}{wire / 1e6:>11.3f}"
              f"{(raw / wire if wire else 0.0):>8.2f}\n")
    if by_algo:
        w("\nComms per algorithm (hops = encoded ppermute exchanges; "
          "psum is one fused exchange, hops n/a):\n")
        w(f"{'rank/pid':>9}{'algo':>6}{'spans':>7}{'wire(MB)':>11}"
          f"{'hops':>7}{'bytes/hop':>11}{'ratio':>8}\n")
        for (pid, algo), (cnt, raw, wire, hops, bh) in sorted(
                by_algo.items(), key=lambda kv: (str(kv[0][0]), kv[0][1])):
            w(f"{pid!s:>9}{algo:>6}{cnt:>7}{wire / 1e6:>11.3f}"
              f"{(hops if hops else '-'):>7}"
              f"{(bh if bh else '-'):>11}"
              f"{(raw / wire if wire else 0.0):>8.2f}\n")

    step_walls = [dur / 1e3 for name, cat, _, dur, _, _, _ in spans
                  if cat == "step"]
    if step_walls:
        w(f"\nStep-time histogram ({len(step_walls)} steps, ms):\n")
        for lo, hi, cnt, bar in histogram(step_walls, bins):
            w(f"  [{lo:>10.2f}, {hi:>10.2f}) {cnt:>5}  {bar}\n")

    steps = other.get("steps") or []
    if steps:
        tot_host = sum(s.get("host_ms", 0) for s in steps)
        tot_comms = sum(s.get("comms_ms", 0) for s in steps)
        tot_dev = sum(s.get("device_ms", 0) for s in steps)
        w("\nStep bucket attribution (telemetry window): "
          f"host-dispatch {tot_host:.1f} ms, comms {tot_comms:.1f} ms, "
          f"device/other {tot_dev:.1f} ms\n")
    wm = other.get("memory_watermark_bytes") or {}
    for dev, b in sorted(wm.items()):
        w(f"memory watermark {dev}: {b} bytes\n")

    if other.get("merged"):
        w("\nPer-rank attribution (merged trace):\n")
        w(f"{'rank':>5} {'host':<18}{'steps':>6}{'wall(ms)':>11}"
          f"{'host(ms)':>10}{'comms(ms)':>11}{'device(ms)':>11}"
          f"{'clk-off(ms)':>12}\n")
        for rank, info in sorted(other.get("ranks", {}).items(),
                                 key=lambda kv: int(kv[0])):
            steps = info.get("steps") or []
            proc = info.get("process") or {}
            w(f"{rank:>5} {proc.get('host', '?'):<18}{len(steps):>6}"
              f"{sum(s.get('wall_ms', 0) for s in steps):>11.1f}"
              f"{sum(s.get('host_ms', 0) for s in steps):>10.1f}"
              f"{sum(s.get('comms_ms', 0) for s in steps):>11.1f}"
              f"{sum(s.get('device_ms', 0) for s in steps):>11.1f}"
              f"{(proc.get('clock_offset_s') or 0) * 1e3:>12.3f}\n")

    if xplane:
        from incubator_mxnet_tpu import profiler as _p

        agg = defaultdict(lambda: [0, 0])
        for hlo, ps in _p.iter_xplane_ops(xplane):
            inst, _ = _p.collapse_hlo_name(hlo)
            agg[inst][0] += 1
            agg[inst][1] += ps
        if agg:
            w(f"\nDevice HLO ops ({xplane}):\n")
            w(f"{'HLO op':<44}{'count':>8}{'total(ms)':>12}\n")
            for inst, (cnt, ps) in sorted(agg.items(),
                                          key=lambda kv: -kv[1][1])[:top]:
                w(f"{inst[:44]:<44}{cnt:>8}{ps / 1e9:>12.3f}\n")
        else:
            w(f"\n(no device plane found under {xplane})\n")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("trace", nargs="+",
                   help="chrome-trace JSON(.gz) from profiler.dump(); "
                        "several per-rank traces need --merge")
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--bins", type=int, default=10)
    p.add_argument("--xplane", default=None,
                   help="xprof trace dir to merge the device HLO table from")
    p.add_argument("--merge", metavar="OUT", default=None,
                   help="fuse the per-rank input traces (trace_merge.py) "
                        "into OUT and report on the merged timeline")
    args = p.parse_args(argv)
    path = args.trace[0]
    try:
        # only trace LOADING maps to exit 2 — a BrokenPipeError from the
        # report writes (| head) must not masquerade as an invalid trace
        if len(args.trace) > 1 or args.merge:
            if not args.merge:
                p.error("several traces given: add --merge OUT to fuse them")
            merged = trace_merge.merge_traces(args.trace)
            with trace_merge.open_trace(args.merge, "wt") as f:
                json.dump(merged, f)
            path = args.merge
        spans, other = load_spans(path)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"trace_report: invalid trace {path!r}: {e}",
              file=sys.stderr)
        return 2
    try:
        report(path, spans, other, top=args.top, bins=args.bins,
               xplane=args.xplane)
    except BrokenPipeError:
        pass  # downstream consumer closed the pipe: not an error
    return 0


if __name__ == "__main__":
    sys.exit(main())
