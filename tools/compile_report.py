#!/usr/bin/env python
"""Offline report over the profiler's compile registry — "what compiled,
why, and what did it cost", by jit site.

Input is either a chrome-trace JSON written by ``profiler.dump()`` (the
registry rides under ``otherData.compiles``) or a bare registry dump
(``json.dump(profiler.compile_registry(), f)``); several inputs (per-rank
dumps) are merged.  ``.json.gz`` files are read transparently.

Usage::

    python tools/compile_report.py profile.json [--top 15] [--json]
                                   [--xplane DIR/mxtpu_profile]
    python tools/compile_report.py --analytic            # bench-config
                                   [--configs resnet50 ...]  # FLOPs table

Sections:

* **per-site totals** — compiles, wall ms, recompiles, steady-state
  violations, and (when XLA cost accounting was captured —
  ``MXNET_COMPILE_COST=1``) FLOPs / bytes-accessed / code-size totals;
* **top recompile culprits** — recompiles grouped by (site, offending
  argument, drift kind) with the attribution line, sorted by wall cost:
  the "why is this still compiling" answer;
* **individual compiles** — the top-N by wall time with program +
  signature summary;
* ``--xplane DIR`` — the device HLO-op table parsed from an xprof capture
  via the shared ``profiler.iter_xplane_ops`` reader (same stream
  ``tools/parse_xplane.py`` and ``dumps()`` present);
* ``--analytic`` — with no dump, the bench-config analytic FLOPs/MFU
  table that used to live in ``tools/flops_report.py`` (kept there as a
  deprecated shim); with a dump, the K-fold scan-body attribution table
  (whole-program cost / K iterations for ``gluon.step_fold_k`` /
  ``gluon.fold_eval`` compiles — see docs/step_fold.md).

Exit codes: 0 on success, 2 on an unreadable/empty registry.
"""
from __future__ import annotations

import argparse
import gzip
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PEAK_TFLOPS = float(os.environ.get("MXNET_TPU_PEAK_TFLOPS", "197"))

# measured per-chip throughput the --analytic mode folds in (round-4
# driver-era numbers; refresh from BENCH_EVIDENCE when a capture lands)
MEASURED = {
    "resnet50": ("img/s", 2455.0),
    "ssd512-resnet18": ("img/s", 867.0),
    "ssd512-vgg16": ("img/s", None),
    "yolo3-darknet53": ("img/s", 566.0),
    "bert-base-mlm": ("samples/s", 1474.0),
    "transformer-big": ("samples/s", None),
}


def _open(path):
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    return open(path)


def load_registry(path):
    """Compile registry from a profiler.dump() trace or a bare
    compile_registry() dump."""
    if os.path.getsize(path) == 0:
        raise ValueError("empty file (0 bytes)")
    with _open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "records" in doc:
        return doc
    if isinstance(doc, dict):
        comp = (doc.get("otherData") or {}).get("compiles")
        if comp is not None:
            return comp
    raise ValueError("no compile registry found (neither a "
                     "compile_registry() dump nor a profiler.dump() trace "
                     "with otherData.compiles)")


def merge_registries(regs):
    sites = defaultdict(lambda: {"count": 0, "ms": 0.0, "recompiles": 0,
                                 "signatures": 0})
    records = []
    for reg in regs:
        for s, e in (reg.get("sites") or {}).items():
            d = sites[s]
            for k in ("count", "recompiles", "signatures"):
                d[k] += e.get(k, 0)
            d["ms"] += e.get("ms", 0.0)
        records.extend(reg.get("records") or [])
    records.sort(key=lambda r: r.get("time_unix", 0))
    return {"sites": dict(sites), "records": records}


def _sig_summary(sig, limit=4):
    parts = []
    for k in sorted(k for k in sig if k != "__program__"):
        v = sig[k]
        if isinstance(v, dict) and v.get("k") == "array":
            shape = "x".join(str(d) for d in v.get("shape", ()))
            parts.append(f"{k}={v.get('dtype', '?')}[{shape}]")
        else:
            val = v.get("value") if isinstance(v, dict) else v
            parts.append(f"{k}={val}")
    extra = f" (+{len(parts) - limit})" if len(parts) > limit else ""
    return ", ".join(parts[:limit]) + extra


def summarize(reg):
    """Machine-readable summary (--json; also what the report prints)."""
    sites = reg.get("sites") or {}
    records = reg.get("records") or []
    cost = defaultdict(lambda: {"flops": 0.0, "bytes_accessed": 0.0,
                                "code_bytes": 0, "with_cost": 0})
    steady = defaultdict(int)
    culprits = {}
    for r in records:
        site = r.get("site", "?")
        if r.get("steady_state"):
            steady[site] += 1
        c = r.get("cost") or {}
        if c:
            d = cost[site]
            d["flops"] += c.get("flops") or 0.0
            d["bytes_accessed"] += c.get("bytes_accessed") or 0.0
            d["code_bytes"] += c.get("code_bytes") or 0
            d["with_cost"] += 1
        if r.get("recompile"):
            f = (r.get("findings") or [{}])[0]
            key = (site, f.get("arg", "<none>"), f.get("kind", "<repeat>"))
            cu = culprits.setdefault(key, {"site": site,
                                           "arg": f.get("arg"),
                                           "kind": f.get("kind"),
                                           "count": 0, "ms": 0.0,
                                           "example": r.get("attribution")})
            cu["count"] += 1
            cu["ms"] += r.get("wall_ms", 0.0)
    return {
        "sites": sites,
        "steady_state_by_site": dict(steady),
        "cost_by_site": {k: dict(v) for k, v in cost.items()},
        "culprits": sorted(culprits.values(), key=lambda c: -c["ms"]),
        "total_compiles": sum(e.get("count", 0) for e in sites.values()),
        "total_ms": round(sum(e.get("ms", 0.0) for e in sites.values()), 3),
        "total_recompiles": sum(e.get("recompiles", 0)
                                for e in sites.values()),
        "total_steady_state": sum(steady.values()),
    }


def report(reg, top=15, out=sys.stdout):
    w = out.write
    summ = summarize(reg)
    records = reg.get("records") or []

    w(f"compile registry: {summ['total_compiles']} compiles, "
      f"{summ['total_ms']:.1f} ms total, {summ['total_recompiles']} "
      f"recompiles ({summ['total_steady_state']} in steady state)\n\n")

    w("Per-site totals:\n")
    w(f"{'site':<26}{'compiles':>9}{'wall(ms)':>11}{'recompile':>10}"
      f"{'steady':>8}{'GFLOP':>10}{'MB moved':>10}\n")
    for site, e in sorted(summ["sites"].items(), key=lambda kv: -kv[1]["ms"]):
        c = summ["cost_by_site"].get(site) or {}
        gflop = (f"{c['flops'] / 1e9:.2f}" if c.get("flops") else "-")
        mb = (f"{c['bytes_accessed'] / 1e6:.1f}"
              if c.get("bytes_accessed") else "-")
        w(f"{site:<26}{e['count']:>9}{e['ms']:>11.1f}{e['recompiles']:>10}"
          f"{summ['steady_state_by_site'].get(site, 0):>8}{gflop:>10}"
          f"{mb:>10}\n")

    # step-fold callout (docs/step_fold.md): the fold sites compile once
    # per (batch signature, optimizer-group-set[, K]); ANY steady-state
    # compile here means the single-program-per-(K-)step contract broke.
    # gluon.step_fold_k is the K-step scan program, gluon.fold_eval the
    # folded eval program — distinct program names per K are expected,
    # steady-state recompiles of an already-seen one are not.
    _FOLD_SITES = ("gluon.step_fold", "gluon.step_fold_k", "gluon.fold_eval")
    fold_records = [r for r in records if r.get("site") in _FOLD_SITES]
    if fold_records:
        progs = defaultdict(int)
        for r in fold_records:
            progs[str(r.get("program") or "step_fold")] += 1
        steady_fold = sum(summ["steady_state_by_site"].get(s, 0)
                          for s in _FOLD_SITES)
        w("\nStep fold (" + "/".join(
            s for s in _FOLD_SITES
            if any(r.get("site") == s for r in fold_records)) + "): "
          + ", ".join(f"{p} x{n}" for p, n in sorted(progs.items()))
          + (f" — {steady_fold} STEADY-STATE recompile(s): the one-"
             "dispatch-per-step contract broke" if steady_fold
             else " — zero steady-state recompiles") + "\n")

    if summ["culprits"]:
        w(f"\nTop recompile culprits (by wall cost):\n")
        w(f"{'site':<26}{'argument':<16}{'drift':<12}{'count':>6}"
          f"{'wall(ms)':>10}\n")
        for cu in summ["culprits"][:top]:
            w(f"{cu['site']:<26}{str(cu['arg']):<16}{str(cu['kind']):<12}"
              f"{cu['count']:>6}{cu['ms']:>10.1f}\n")
            if cu.get("example"):
                w(f"    e.g. {cu['example']}\n")

    if records:
        w(f"\nTop {top} compiles by wall time:\n")
        w(f"{'site':<26}{'program':<22}{'step':>6}{'wall(ms)':>10}"
          "  signature\n")
        for r in sorted(records, key=lambda r: -r.get("wall_ms", 0))[:top]:
            sig = r.get("signature") or {}
            prog = str(r.get("program") or "-")
            w(f"{r.get('site', '?'):<26}{prog[:22]:<22}"
              f"{r.get('step', '-'):>6}{r.get('wall_ms', 0):>10.1f}"
              f"  {_sig_summary(sig)}\n")


def xplane_report(trace_dir, top=20, out=sys.stdout):
    """Device HLO-op cost table via the shared xplane reader (the summary
    that used to require tools/parse_xplane.py alongside flops_report)."""
    from incubator_mxnet_tpu.profiler import collapse_hlo_name, iter_xplane_ops

    w = out.write
    by_inst = defaultdict(lambda: [0, 0])
    grand = 0
    for name, ps in iter_xplane_ops(trace_dir):
        inst, _ = collapse_hlo_name(name)
        by_inst[inst][0] += 1
        by_inst[inst][1] += ps
        grand += ps
    if not grand:
        w(f"(no device 'XLA Ops' events under {trace_dir})\n")
        return
    w(f"\nDevice HLO ops ({trace_dir}; total "
      f"{grand / 1e9:.3f} ms device time):\n")
    w(f"{'HLO op':<44}{'count':>8}{'total(ms)':>12}{'%':>7}\n")
    for inst, (cnt, ps) in sorted(by_inst.items(),
                                  key=lambda kv: -kv[1][1])[:top]:
        w(f"{inst[:44]:<44}{cnt:>8}{ps / 1e9:>12.3f}"
          f"{100 * ps / grand:>6.1f}%\n")


def fold_analytic_report(reg, out=sys.stdout):
    """Per-iteration cost attribution for K-step fold scan bodies.

    A ``gluon.step_fold_k`` compile covers K scan iterations in ONE
    program, so the XLA cost analysis captured under
    ``MXNET_COMPILE_COST=1`` reports K iterations' worth of flops and
    bytes.  The honest per-logical-step number is whole-program cost / K;
    K is parsed from the program name (``step_fold_k[4]``,
    ``fold_eval[8]``).  Comparing GFLOP/iter across K values is the quick
    check that the scan body really is the K=1 step and the fold is pure
    dispatch amortisation, not a different program."""
    import re
    rows = []
    for r in reg.get("records") or []:
        site = r.get("site")
        if site not in ("gluon.step_fold", "gluon.step_fold_k",
                        "gluon.fold_eval"):
            continue
        prog = str(r.get("program") or "step_fold")
        m = re.search(r"\[(\d+)\]", prog)
        k = int(m.group(1)) if m else 1
        c = r.get("cost") or {}
        rows.append((site, prog, k, c.get("flops"),
                     c.get("bytes_accessed"), r.get("wall_ms", 0.0)))
    w = out.write
    if not rows:
        w("\n(no step-fold compiles in the registry — nothing to "
          "attribute per scan iteration)\n")
        return
    w("\nK-fold scan-body attribution (whole-program cost / K iterations; "
      "needs MXNET_COMPILE_COST=1 for flops/bytes):\n")
    w(f"{'site':<22}{'program':<22}{'K':>4}{'GFLOP/iter':>12}"
      f"{'MB/iter':>10}{'compile(ms)':>13}\n")
    for site, prog, k, fl, by, ms in sorted(rows, key=lambda r: (r[0], r[2])):
        g = f"{fl / k / 1e9:.3f}" if fl else "-"
        mb = f"{by / k / 1e6:.2f}" if by else "-"
        w(f"{site:<22}{prog[:22]:<22}{k:>4}{g:>12}{mb:>10}{ms:>13.1f}\n")


# -- analytic bench-config FLOPs (absorbed from tools/flops_report.py) -------


def _fwd_flops_per_sample(net, *inputs):
    import jax

    fn, params = net.export_jittable()
    lowered = jax.jit(lambda p, *xs: fn(p, *xs)).lower(params, *inputs)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    return float(cost["flops"]) / inputs[0].shape[0]


def _build(config):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import incubator_mxnet_tpu as mx

    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        mx.random.seed(0)
        if config == "resnet50":
            from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
            net = resnet50_v1()
            x = jnp.zeros((1, 3, 224, 224), jnp.float32)
        elif config == "ssd512-resnet18":
            from incubator_mxnet_tpu.gluon.model_zoo.ssd import ssd_512_resnet18
            net = ssd_512_resnet18()
            x = jnp.zeros((1, 3, 512, 512), jnp.float32)
        elif config == "ssd512-vgg16":
            from incubator_mxnet_tpu.gluon.model_zoo.ssd import (
                ssd_512_vgg16_atrous)
            net = ssd_512_vgg16_atrous()
            x = jnp.zeros((1, 3, 512, 512), jnp.float32)
        elif config == "yolo3-darknet53":
            from incubator_mxnet_tpu.gluon.model_zoo.yolo import yolo3_darknet53
            net = yolo3_darknet53()
            x = jnp.zeros((1, 3, 416, 416), jnp.float32)
        elif config == "bert-base-mlm":
            from incubator_mxnet_tpu.gluon.model_zoo.bert import (
                BERTForPretrain, bert_base)
            net = BERTForPretrain(bert_base(vocab_size=30522, max_length=512,
                                            dropout=0.0), vocab_size=30522)
            S, Pn = 128, 20
            xs = (jnp.zeros((1, S), jnp.int32), jnp.zeros((1, S), jnp.int32),
                  jnp.zeros((1, Pn), jnp.int32))
        elif config == "transformer-big":
            from incubator_mxnet_tpu.gluon.model_zoo.transformer import (
                transformer_big)
            net = transformer_big(vocab_size=32768, max_length=512,
                                  dropout=0.0)
            S = 256
            xs = (jnp.zeros((1, S), jnp.int32), jnp.zeros((1, S), jnp.int32))
        else:
            raise ValueError(config)
        net.initialize()
        if config in ("bert-base-mlm", "transformer-big"):
            net(*[mx.nd.array(np.asarray(v)) for v in xs])
            return net, xs
        net(mx.nd.array(np.asarray(x)))  # materialize deferred shapes
        return net, (x,)


def analytic_report(configs=None, out=sys.stdout):
    """The bench-config analytic FLOP/MFU table (3x-fwd training
    convention; see PERF_NOTES) — exactly what tools/flops_report.py used
    to print before it became a shim over this entry point."""
    rows = []
    for config in (configs or list(MEASURED)):
        unit, rate = MEASURED.get(config, ("items/s", None))
        net, xs = _build(config)
        gflops = _fwd_flops_per_sample(net, *xs) / 1e9
        mfu = (rate * 3 * gflops / (PEAK_TFLOPS * 1e3)) if rate else None
        rows.append((config, gflops, rate, mfu))
        out.write(json.dumps({
            "metric": f"{config}_fwd_gflops_per_sample",
            "value": round(gflops, 2),
            "measured_per_sec": rate,
            "train_mfu_at_measured": round(mfu, 4) if mfu else None,
        }) + "\n")
        out.flush()

    out.write(f"\n| config | fwd GFLOP/sample | measured/s/chip | train MFU "
              f"(3x fwd, {PEAK_TFLOPS:.0f} TF peak) |\n")
    out.write("|---|---|---|---|\n")
    for config, gflops, rate, mfu in rows:
        out.write(f"| {config} | {gflops:.1f} | {rate if rate else '—'} | "
                  f"{f'{100 * mfu:.1f}%' if mfu else '—'} |\n")
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("dump", nargs="*",
                   help="profiler.dump() trace(s) or compile_registry() "
                        "JSON dump(s); merged when several")
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--json", action="store_true",
                   help="print the machine-readable summary instead")
    p.add_argument("--xplane", default=None,
                   help="xprof trace dir: append the device HLO-op table")
    p.add_argument("--analytic", action="store_true",
                   help="no dump: bench-config analytic FLOPs table (ex "
                        "tools/flops_report.py); with a dump: per-iteration "
                        "K-fold scan-body cost attribution")
    p.add_argument("--configs", nargs="*", default=None,
                   help="--analytic: subset of bench configs")
    args = p.parse_args(argv)

    if args.analytic and not args.dump:
        return analytic_report(args.configs)
    if not args.dump:
        p.error("give at least one dump file (or --analytic)")
    try:
        reg = merge_registries([load_registry(d) for d in args.dump])
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"compile_report: invalid dump: {e}", file=sys.stderr)
        return 2
    if not (reg.get("records") or reg.get("sites")):
        print("compile_report: registry is empty — nothing ever compiled "
              "or the dump predates the compile registry", file=sys.stderr)
        return 2
    try:
        if args.json:
            json.dump(summarize(reg), sys.stdout, indent=2, default=str)
            sys.stdout.write("\n")
        else:
            report(reg, top=args.top)
        if args.analytic:
            # with a dump: per-iteration scan-body attribution instead of
            # (in addition to --configs would be ambiguous) the bench table
            fold_analytic_report(reg)
        if args.xplane:
            xplane_report(args.xplane, top=args.top)
    except BrokenPipeError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
