#!/usr/bin/env python
"""Analytic FLOP accounting for the bench configs (VERDICT r4 weak #3/#7:
SSD/YOLO MFU unstated).

Builds each model exactly as bench.py does, exports the pure forward via
``Block.export_jittable()``, and reads XLA's HLO cost analysis on CPU at
B=1 to get fwd FLOPs/sample.  Training FLOPs use the standard fwd+bwd=3x
convention (the same accounting PERF_NOTES applies to BERT/transformer).
MFU = measured_items_per_sec x 3 x fwd_flops / peak, peak = 197 TFLOP/s
bf16 (TPU v5e chip).

Run on CPU (no chip needed):
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/flops_report.py
Emits a markdown table + one JSON line per config for PERF_NOTES.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

PEAK_TFLOPS = float(os.environ.get("MXNET_TPU_PEAK_TFLOPS", "197"))

# measured per-chip throughput to fold in (round-4 driver-era numbers;
# refresh from BENCH_EVIDENCE_r05 when the capture lands)
MEASURED = {
    "resnet50": ("img/s", 2455.0),
    "ssd512-resnet18": ("img/s", 867.0),
    "ssd512-vgg16": ("img/s", None),     # never measured pre-r5
    "yolo3-darknet53": ("img/s", 566.0),  # r3 number (r4 blocked by wedge)
    # cross-checks of PERF_NOTES' analytic accounting (68.5 GFLOP/sample
    # BERT => fwd ~22.8; 0.66 GFLOP/token transformer => fwd/sample at
    # S=256 ~56.3 over both streams)
    "bert-base-mlm": ("samples/s", 1474.0),
    "transformer-big": ("samples/s", None),
}


def _fwd_flops_per_sample(net, *inputs):
    import jax

    fn, params = net.export_jittable()
    lowered = jax.jit(lambda p, *xs: fn(p, *xs)).lower(params, *inputs)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    return float(cost["flops"]) / inputs[0].shape[0]


def _build(config):
    import jax
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx

    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        mx.random.seed(0)
        if config == "resnet50":
            from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
            net = resnet50_v1()
            x = jnp.zeros((1, 3, 224, 224), jnp.float32)
        elif config == "ssd512-resnet18":
            from incubator_mxnet_tpu.gluon.model_zoo.ssd import ssd_512_resnet18
            net = ssd_512_resnet18()
            x = jnp.zeros((1, 3, 512, 512), jnp.float32)
        elif config == "ssd512-vgg16":
            from incubator_mxnet_tpu.gluon.model_zoo.ssd import ssd_512_vgg16_atrous
            net = ssd_512_vgg16_atrous()
            x = jnp.zeros((1, 3, 512, 512), jnp.float32)
        elif config == "yolo3-darknet53":
            from incubator_mxnet_tpu.gluon.model_zoo.yolo import yolo3_darknet53
            net = yolo3_darknet53()
            x = jnp.zeros((1, 3, 416, 416), jnp.float32)
        elif config == "bert-base-mlm":
            from incubator_mxnet_tpu.gluon.model_zoo.bert import (
                BERTForPretrain, bert_base)
            net = BERTForPretrain(bert_base(vocab_size=30522, max_length=512,
                                            dropout=0.0), vocab_size=30522)
            S, Pn = 128, 20
            xs = (jnp.zeros((1, S), jnp.int32), jnp.zeros((1, S), jnp.int32),
                  jnp.zeros((1, Pn), jnp.int32))
        elif config == "transformer-big":
            from incubator_mxnet_tpu.gluon.model_zoo.transformer import (
                transformer_big)
            net = transformer_big(vocab_size=32768, max_length=512, dropout=0.0)
            S = 256
            xs = (jnp.zeros((1, S), jnp.int32), jnp.zeros((1, S), jnp.int32))
        else:
            raise ValueError(config)
        net.initialize()
        if config in ("bert-base-mlm", "transformer-big"):
            net(*[mx.nd.array(np.asarray(v)) for v in xs])
            return net, xs
        net(mx.nd.array(np.asarray(x)))  # materialize deferred shapes
        return net, (x,)


def main():
    rows = []
    for config, (unit, rate) in MEASURED.items():
        net, xs = _build(config)
        gflops = _fwd_flops_per_sample(net, *xs) / 1e9
        mfu = (rate * 3 * gflops / (PEAK_TFLOPS * 1e3)) if rate else None
        rows.append((config, gflops, rate, mfu))
        print(json.dumps({
            "metric": f"{config}_fwd_gflops_per_sample",
            "value": round(gflops, 2),
            "measured_per_sec": rate,
            "train_mfu_at_measured": round(mfu, 4) if mfu else None,
        }), flush=True)

    print(f"\n| config | fwd GFLOP/sample | measured/s/chip | train MFU "
          f"(3x fwd, {PEAK_TFLOPS:.0f} TF peak) |")
    print("|---|---|---|---|")
    for config, gflops, rate, mfu in rows:
        print(f"| {config} | {gflops:.1f} | {rate if rate else '—'} | "
              f"{f'{100 * mfu:.1f}%' if mfu else '—'} |")


if __name__ == "__main__":
    main()
