#!/usr/bin/env python
"""DEPRECATED shim — the analytic FLOP accounting moved into
``tools/compile_report.py --analytic`` (one CLI surface for all compile
cost accounting: registry dumps, xplane device tables, and this analytic
bench-config table).  This entry point stays so existing invocations and
PERF_NOTES recipes keep working:

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/flops_report.py

is now exactly

    ... python tools/compile_report.py --analytic
"""
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from compile_report import MEASURED, PEAK_TFLOPS, analytic_report  # noqa: F401,E402
from compile_report import _build, _fwd_flops_per_sample  # noqa: F401,E402


def main():
    warnings.warn(
        "tools/flops_report.py is deprecated; use "
        "tools/compile_report.py --analytic", DeprecationWarning,
        stacklevel=2)
    return analytic_report()


if __name__ == "__main__":
    sys.exit(main())
