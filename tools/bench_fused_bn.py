#!/usr/bin/env python
"""A/B microbench: stock XLA conv→BN→ReLU(+residual) vs the Pallas fused
epilogue (``ops/pallas_bn.py``) on ResNet-50 stage shapes — the experiment
VERDICT r4 item 4b names.  Run on a real chip (ambient axon env):

    python tools/bench_fused_bn.py            # stage-3 shape, B=256
    MXNET_TPU_BN_STAGE=2 python tools/bench_fused_bn.py

Prints one JSON line per variant with ms/iter and the implied HBM
passes-per-feature-map (time · BW / bytes-per-map), feeding the
resnet_roofline.py pass-count assumption with a measurement.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

# B=256 ResNet-50 v1 stage shapes (after the stage's stride-2 entry)
STAGE_SHAPES = {
    1: (256, 256, 56, 56),
    2: (256, 512, 28, 28),
    3: (256, 1024, 14, 14),
    4: (256, 2048, 7, 7),
}
HBM_GBPS = 819.0  # v5e


def _fence(x):
    np.asarray(jax.device_get(x if not isinstance(x, tuple) else x[0]))


def _time(fn, *args, iters=30):
    out = fn(*args)
    _fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _fence(out)
    return (time.perf_counter() - t0) / iters * 1e3, out


def main():
    stage = int(os.environ.get("MXNET_TPU_BN_STAGE", "3"))
    N, C, H, W = STAGE_SHAPES[stage]
    if jax.default_backend() == "cpu":
        N = 8  # smoke shape
    mid = C // 4
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(N, mid, H, W).astype(np.float32)).astype(jnp.bfloat16)
    w = jnp.asarray(rng.randn(C, mid, 3, 3).astype(np.float32) * 0.05).astype(jnp.bfloat16)
    res = jnp.asarray(rng.rand(N, C, H, W).astype(np.float32)).astype(jnp.bfloat16)
    gamma = jnp.asarray(rng.rand(C).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(C).astype(np.float32))

    def conv(xx):
        return lax.conv_general_dilated(
            xx, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    @jax.jit
    def xla_path(xx, rr):
        h = conv(xx)
        h32 = h.astype(jnp.float32)
        mean = jnp.mean(h32, axis=(0, 2, 3))
        var = jnp.maximum(jnp.mean(jnp.square(h32), axis=(0, 2, 3))
                          - jnp.square(mean), 0.0)
        inv = lax.rsqrt(var + 1e-5) * gamma
        out = (h32 - mean[None, :, None, None]) * inv[None, :, None, None] \
            + beta[None, :, None, None]
        return jnp.maximum(out + rr.astype(jnp.float32), 0.0).astype(h.dtype)

    from incubator_mxnet_tpu.ops.pallas_bn import fused_bn_relu

    interpret = jax.default_backend() == "cpu"

    @jax.jit
    def pallas_path(xx, rr):
        h = conv(xx)
        out, _, _ = fused_bn_relu(h, gamma, beta, residual=rr,
                                  interpret=interpret)
        return out

    bytes_per_map = N * C * H * W * 2  # bf16
    results = {}
    for name, fn in (("xla", xla_path), ("pallas_epilogue", pallas_path)):
        ms, out = _time(fn, x, res)
        results[name] = (ms, out)
        passes = (ms / 1e3) * HBM_GBPS * 1e9 / bytes_per_map
        print(json.dumps({
            "metric": f"conv_bn_relu_add_stage{stage}_{name}",
            "value": round(ms, 3), "unit": "ms/iter",
            "implied_hbm_passes_per_map": round(passes, 2),
        }))
    a = np.asarray(jax.device_get(results["xla"][1]), np.float32)
    b = np.asarray(jax.device_get(results["pallas_epilogue"][1]), np.float32)
    print(json.dumps({"metric": "max_abs_diff", "value": float(np.abs(a - b).max())}))


if __name__ == "__main__":
    main()
