#!/usr/bin/env python
"""Elastic run supervisor for the dist_sync/SPMD path (ISSUE 16).

``tools/launch_local.py`` with a recovery loop: spawns N ranks with the
DMLC_* environment, monitors liveness (process exit AND a heartbeat lease
over a lightweight control socket — workers opt in via
``incubator_mxnet_tpu.parallel.elastic.init()``), and when any rank dies
or goes silent it kills the survivors, reserves a FRESH coordinator port
(the old ``jax.distributed`` cohort is unrecoverable — re-forming the job
re-runs ``mesh.init_distributed`` with a new coordinator in every
relaunched rank), and restarts the command under a bounded restart budget
with exponential backoff.  Workers resume from their latest COMMITTED
``RunCheckpoint`` snapshot — the supervisor restarts processes; exact
resume is the workers' two-phase snapshot contract.

Usage:
    python tools/supervise.py -n 2 [--max-restarts 3] python train.py ...

Per generation ``g`` the workers additionally see:

* ``MXNET_ELASTIC_SOCKET``  — this supervisor's control address
* ``MXNET_ELASTIC_RESTART`` — ``g`` (0 on the first launch), so fault
  gating (``gen=``) and the restart metrics gauge see the generation
* ``MXNET_ELASTIC_DOWNTIME_S`` — cumulative supervisor-observed downtime
  (previous generation's end → this spawn, including backoff) which
  ``parallel.elastic.init()`` folds into the goodput ledger's downtime
  bucket (ISSUE 20)

Reports exactly ONE ``ELASTIC_RESTART {json}`` line per re-formation
(and one ``ELASTIC_GIVEUP`` line if the budget runs out) — chaos tests
count these lines.  With ``--manifest PATH`` (or
``MXNET_ELASTIC_MANIFEST``) the same story is kept machine-readable: a
JSON run manifest (schema-versioned; per-generation start/end
timestamps, exit causes, downtime seconds, restart totals) atomically
rewritten at every transition, so tooling reads the run's fault history
from ONE file instead of scraping log lines.

Env defaults: ``MXNET_ELASTIC_MAX_RESTARTS`` (3),
``MXNET_ELASTIC_BACKOFF_S`` (1.0, doubled per restart, capped at 30),
``MXNET_ELASTIC_LEASE_S`` (15 — a rank that heartbeated once and then
goes silent this long is declared dead even if its process lingers,
e.g. wedged inside a collective with no watchdog).
"""
import argparse
import json
import os
import pickle
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

_LEN = struct.Struct("!I")


def reserve_port():
    """Bind a free port and KEEP the socket open until the workers have
    spawned (same TOCTOU discipline as tools/launch_local.py)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    return s, s.getsockname()[1]


def _recv_exact(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class ControlServer(threading.Thread):
    """Accepts worker connections; tracks the last heartbeat per rank
    (the lease table) and logs one-shot events.  One-way wire: workers
    send length-prefixed pickled tuples, nothing is replied."""

    def __init__(self):
        super().__init__(name="elastic-control", daemon=True)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._lock = threading.Lock()
        self._beats = {}   # rank -> time.monotonic() of last heartbeat
        self._gen = 0

    def run(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn):
        gen = self._gen  # connections from a dead generation are ignored
        try:
            while True:
                (length,) = _LEN.unpack(_recv_exact(conn, _LEN.size))
                msg = pickle.loads(_recv_exact(conn, length))
                if not isinstance(msg, tuple) or not msg:
                    continue
                if msg[0] == "hb" and gen == self._gen:
                    with self._lock:
                        self._beats[int(msg[1])] = time.monotonic()
                elif msg[0] == "event":
                    _, rank, kind, payload = msg
                    print(f"[supervise] rank {rank} event {kind}: "
                          f"{json.dumps(payload, default=str)}",
                          file=sys.stderr, flush=True)
        except (ConnectionError, OSError, pickle.UnpicklingError, EOFError,
                struct.error, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def new_generation(self):
        with self._lock:
            self._gen += 1
            self._beats.clear()

    def expired(self, lease_s):
        """Ranks whose lease lapsed — only ranks that heartbeated at
        least once are on lease (plain scripts never beat)."""
        now = time.monotonic()
        with self._lock:
            return [r for r, t in self._beats.items() if now - t > lease_s]


def spawn_ranks(args, ctrl_port, gen, downtime_s=0.0):
    holder, port = reserve_port()
    ps_holder, ps_port = reserve_port()
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update(
            DMLC_ROLE="worker",
            DMLC_PS_ROOT_URI="127.0.0.1",
            DMLC_PS_ROOT_PORT=str(port),
            DMLC_NUM_WORKER=str(args.num_workers),
            DMLC_NUM_SERVER="0",
            DMLC_WORKER_ID=str(rank),
            MXNET_ELASTIC_SOCKET=f"127.0.0.1:{ctrl_port}",
            MXNET_ELASTIC_RESTART=str(gen),
            MXNET_ELASTIC_DOWNTIME_S=f"{downtime_s:.3f}",
        )
        env["MXNET_ASYNC_PS_PORT"] = str(ps_port)
        env.setdefault("JAX_PLATFORMS", "cpu")
        if env["JAX_PLATFORMS"] == "cpu":
            env.pop("PALLAS_AXON_POOL_IPS", None)
        for kv in args.env:
            k, _, v = kv.partition("=")
            env[k] = v
        procs.append(subprocess.Popen(args.command, env=env))
    holder.close()
    ps_holder.close()
    return procs


def kill_all(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass


def write_manifest(path, manifest):
    """Atomically (tmp + rename) rewrite the run manifest — a crashed
    supervisor leaves the last complete transition, never a torn file."""
    if not path:
        return
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1)
        os.replace(tmp, path)
    except OSError as e:
        print(f"[supervise] manifest write failed: {e}",
              file=sys.stderr, flush=True)


def run_generation(args, ctrl, gen, downtime_s=0.0):
    """Run one cohort to completion.  Returns ``(rc, failure)`` —
    ``(0, None)`` when every rank exits cleanly."""
    ctrl.new_generation()
    procs = spawn_ranks(args, ctrl.port, gen, downtime_s)
    try:
        while True:
            live = [p for p in procs if p.poll() is None]
            failed = [(r, p.returncode) for r, p in enumerate(procs)
                      if p.poll() is not None and p.returncode != 0]
            if failed:
                rank, code = failed[0]
                kill_all(procs)
                return code, {"reason": "rank_exit", "rank": rank,
                              "exit_code": code}
            if not live:
                return 0, None
            stale = ctrl.expired(args.lease_s)
            if stale:
                kill_all(procs)
                return 1, {"reason": "lease_expired", "rank": stale[0],
                           "lease_s": args.lease_s}
            time.sleep(0.1)
    except (KeyboardInterrupt, SystemExit):
        kill_all(procs)
        raise


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VALUE for the workers")
    ap.add_argument("--max-restarts", type=int,
                    default=int(os.environ.get(
                        "MXNET_ELASTIC_MAX_RESTARTS", "3")))
    ap.add_argument("--backoff", type=float,
                    default=float(os.environ.get(
                        "MXNET_ELASTIC_BACKOFF_S", "1.0")))
    ap.add_argument("--lease-s", type=float,
                    default=float(os.environ.get(
                        "MXNET_ELASTIC_LEASE_S", "15")))
    ap.add_argument("--manifest",
                    default=os.environ.get("MXNET_ELASTIC_MANIFEST") or None,
                    help="path for the machine-readable JSON run manifest"
                         " (generations, exit causes, downtime)")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no worker command given")

    ctrl = ControlServer()
    ctrl.start()

    def on_term(signum, frame):
        raise SystemExit(128 + signum)

    signal.signal(signal.SIGTERM, on_term)

    gen = 0
    total_downtime = 0.0
    manifest = {
        "schema": 1,
        "started_unix": time.time(),
        "num_workers": args.num_workers,
        "command": list(args.command),
        "generations": [],
        "restarts": 0,
        "total_downtime_s": 0.0,
        "final": None,
        "ended_unix": None,
    }
    write_manifest(args.manifest, manifest)
    while True:
        gen_start = time.time()
        rc, failure = run_generation(args, ctrl, gen, total_downtime)
        gen_end = time.time()
        gen_rec = {"generation": gen, "start_unix": gen_start,
                   "end_unix": gen_end,
                   "exit_cause": failure or {"reason": "clean"},
                   "downtime_s": 0.0}
        manifest["generations"].append(gen_rec)
        if rc == 0:
            if gen:
                print(f"[supervise] run complete after {gen} restart(s)",
                      file=sys.stderr, flush=True)
            manifest.update(final="complete", ended_unix=time.time())
            write_manifest(args.manifest, manifest)
            return 0
        report = dict(failure or {}, event="elastic_restart", generation=gen,
                      restarts_left=args.max_restarts - gen)
        if gen >= args.max_restarts:
            report["event"] = "elastic_giveup"
            print("ELASTIC_GIVEUP " + json.dumps(report),
                  file=sys.stderr, flush=True)
            manifest.update(final="giveup", ended_unix=time.time())
            write_manifest(args.manifest, manifest)
            return rc if rc > 0 else 1
        # exactly ONE restart report line per re-formation (chaos tests
        # count these)
        print("ELASTIC_RESTART " + json.dumps(report),
              file=sys.stderr, flush=True)
        try:
            from incubator_mxnet_tpu import profiler as _profiler
            _profiler.incr("elastic_restart")
        except Exception:
            pass
        time.sleep(min(args.backoff * (2 ** gen), 30.0))
        # supervisor-observed downtime for THIS re-formation: generation
        # end (death detected + survivors killed) → the instant the next
        # cohort spawns.  The cumulative figure rides
        # MXNET_ELASTIC_DOWNTIME_S into the relaunched workers' ledgers.
        gen_rec["downtime_s"] = round(time.time() - gen_end, 3)
        total_downtime = round(total_downtime + gen_rec["downtime_s"], 3)
        manifest["restarts"] = gen + 1
        manifest["total_downtime_s"] = total_downtime
        write_manifest(args.manifest, manifest)
        gen += 1


if __name__ == "__main__":
    sys.exit(main())
