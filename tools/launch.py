#!/usr/bin/env python
"""Cluster launcher — the [U:tools/launch.py] analog beyond localhost.

Launchers:

* ``--launcher local``  — delegate to tools/launch_local.py (tested tier).
* ``--launcher ssh``    — one worker per line of ``--hostfile``, started
  over ssh with the DMLC_* env the trackers set
  ([U:3rdparty/dmlc-core/tracker/dmlc_tracker/ssh.py]); worker 0's host
  doubles as the jax.distributed coordinator.
* ``--launcher tpu-pod`` — the TPU-native deployment: one process per pod
  host via ``gcloud compute tpus tpu-vm ssh --worker=all``.  On a pod the
  TPU runtime itself supplies topology, so workers only need
  ``jax.distributed.initialize()`` with no args; the launcher's job is
  fan-out + env hygiene, not rendezvous.

``--dry-run`` prints every command instead of executing — the only mode
exercisable in this sandbox (no ssh targets, no pods); the local tier is
the executed-and-tested path (tests/test_dist.py).
"""
import argparse
import os
import shlex
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def _read_hostfile(path):
    with open(path) as f:
        hosts = [ln.strip() for ln in f if ln.strip() and not ln.startswith("#")]
    if not hosts:
        raise SystemExit(f"hostfile {path} has no hosts")
    return hosts


def launch_local(args, cmd):
    sub = [sys.executable, os.path.join(HERE, "launch_local.py"),
           "-n", str(args.num_workers)] + ["--env=" + e for e in args.env] + cmd
    if args.dry_run:
        print(" ".join(shlex.quote(c) for c in sub))
        return 0
    return subprocess.call(sub)


def launch_ssh(args, cmd):
    hosts = _read_hostfile(args.hostfile)
    n = args.num_workers or len(hosts)
    if n > len(hosts):
        raise SystemExit(f"{n} workers > {len(hosts)} hosts in {args.hostfile}")
    coord = f"{hosts[0]}:{args.port}"
    procs = []
    rc = 0
    for rank in range(n):
        env = {
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": hosts[0],
            "DMLC_PS_ROOT_PORT": str(args.port),
            "DMLC_NUM_WORKER": str(n),
            "DMLC_WORKER_ID": str(rank),
        }
        for e in args.env:
            k, _, v = e.partition("=")
            env[k] = v
        env_prefix = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        remote = f"cd {shlex.quote(args.workdir)} && {env_prefix} " + \
            " ".join(shlex.quote(c) for c in cmd)
        ssh_cmd = ["ssh", "-o", "StrictHostKeyChecking=no", hosts[rank], remote]
        if args.dry_run:
            print(" ".join(shlex.quote(c) for c in ssh_cmd))
            continue
        procs.append(subprocess.Popen(ssh_cmd))
    for p in procs:
        rc |= p.wait()
    if args.dry_run:
        print(f"# coordinator: {coord}")
    return rc


def launch_tpu_pod(args, cmd):
    """Fan the command out to every host of a Cloud TPU pod slice.  The pod
    runtime provides rendezvous (jax.distributed.initialize() no-args), so
    no DMLC_* env is needed — only the user's --env extras."""
    if not args.tpu_name:
        raise SystemExit("--launcher tpu-pod requires --tpu-name")
    def _assign(e):
        k, _, v = e.partition("=")
        return f"{k}={shlex.quote(v)}"

    env_prefix = " ".join(_assign(e) for e in args.env)
    remote = ((env_prefix + " ") if env_prefix else "") + \
        " ".join(shlex.quote(c) for c in cmd)
    g = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", args.tpu_name,
         "--worker=all"]
    if args.zone:  # omitted -> gcloud's configured default zone
        g.append(f"--zone={args.zone}")
    g += ["--command", remote]
    if args.dry_run:
        print(" ".join(shlex.quote(c) for c in g))
        return 0
    return subprocess.call(g)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-n", "--num-workers", type=int, default=0)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference-script compat; ignored (no PS tier)")
    ap.add_argument("--launcher", choices=("local", "ssh", "tpu-pod"),
                    default="local")
    ap.add_argument("-H", "--hostfile", help="one host per line (ssh mode)")
    ap.add_argument("--tpu-name", help="TPU pod slice name (tpu-pod mode)")
    ap.add_argument("--zone", default=os.environ.get("CLOUDSDK_COMPUTE_ZONE", ""),
                    help="GCE zone (tpu-pod mode)")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--workdir", default=os.getcwd(),
                    help="remote working directory (ssh mode)")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VALUE for the workers")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the launch commands without executing")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no worker command given")
    cmd = args.command[1:] if args.command[0] == "--" else args.command

    if args.launcher == "local":
        if not args.num_workers:
            ap.error("-n is required for --launcher local")
        return launch_local(args, cmd)
    if args.launcher == "ssh":
        if not args.hostfile:
            ap.error("--hostfile is required for --launcher ssh")
        return launch_ssh(args, cmd)
    return launch_tpu_pod(args, cmd)


if __name__ == "__main__":
    raise SystemExit(main())
