#!/usr/bin/env python
"""Offline report over the profiler's device-memory ledger — "what owns
the bytes", by owner and category.

Input is either a chrome-trace JSON written by ``profiler.dump()`` (the
ledger rides under ``otherData.memory``, watermarks under
``otherData.memory_watermark_bytes``, the counter track as ``"C"``
events) or a bare ledger dump (``json.dump(profiler.memory_ledger(),
f)``); several inputs (per-rank dumps, or a ``trace_merge.py`` output
whose ``otherData.ranks`` carries per-rank memory blocks) are merged.
``.json.gz`` files are read transparently.

Usage::

    python tools/memory_report.py profile.json [--top 15] [--json]

Sections:

* **per-owner totals** — live bytes, peak, alloc/free counts, category;
* **per-category rollup** + the ledger total;
* **device watermarks + attribution** — peak ``bytes_in_use`` per device
  and the fraction of it the ledger attributes to named owners (the
  ≥ 90 % acceptance bar of ``tools/memory_smoke.py``);
* **watermark timeline** — an ASCII sparkline per memory counter track
  (the chrome-trace ``C`` events Perfetto renders graphically);
* **postmortems** — every OOM/budget-breach report with its top owners
  and the failed allocation size.

Exit codes: 0 on success, 2 on an unreadable input or one carrying no
memory data at all (no owners, no watermark, no samples — one-line
diagnosis, no traceback; the sibling report CLIs' contract).
"""
from __future__ import annotations

import argparse
import gzip
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPARK = "▁▂▃▄▅▆▇█"


def _open(path):
    if path.endswith(".gz"):
        return gzip.open(path, "rt")
    return open(path)


def _fmt_bytes(n):
    if n is None:
        return "?"
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def load_memory(path):
    """Memory document from a profiler.dump() trace, a trace_merge.py
    output, or a bare memory_ledger() dump.  Returns
    ``{"ledger", "postmortems", "watermark", "tracks"}`` where ``tracks``
    maps counter-track name -> [(ts_us, {series: value})]."""
    if os.path.getsize(path) == 0:
        raise ValueError("empty file (0 bytes)")
    with _open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError("not a JSON object")
    if "owners" in doc and "total_bytes" in doc:      # bare ledger dump
        return {"ledger": doc, "postmortems": [], "watermark": {},
                "tracks": {}}
    od = doc.get("otherData") or {}
    out = {"ledger": None, "postmortems": [], "watermark": {}, "tracks": {}}
    blocks = []
    if od.get("memory") is not None:
        blocks.append((od.get("memory"),
                       od.get("memory_watermark_bytes") or {}))
    for rank, entry in sorted((od.get("ranks") or {}).items()):
        if isinstance(entry, dict) and entry.get("memory") is not None:
            blocks.append((entry["memory"],
                           entry.get("memory_watermark_bytes") or {}))
    if not blocks and od.get("memory_watermark_bytes"):
        blocks.append((None, od["memory_watermark_bytes"]))
    for mem, wm in blocks:
        if mem:
            out["ledger"] = merge_ledgers(
                [x for x in (out["ledger"], mem.get("ledger")) if x])
            out["postmortems"].extend(mem.get("postmortems") or [])
        for dev, b in (wm or {}).items():
            if b > out["watermark"].get(dev, -1):
                out["watermark"][dev] = b
    for ev in doc.get("traceEvents") or []:
        if isinstance(ev, dict) and ev.get("ph") == "C" \
                and str(ev.get("name", "")).startswith("memory"):
            out["tracks"].setdefault(ev["name"], []).append(
                (ev.get("ts", 0.0), ev.get("args") or {}))
    if out["ledger"] is None and not out["watermark"] and not out["tracks"]:
        raise ValueError(
            "no memory data found (neither a memory_ledger() dump nor a "
            "profiler.dump() trace with otherData.memory)")
    return out


def merge_ledgers(ledgers):
    """Sum per-rank ledgers (same-named owners add — each rank's trainer
    legitimately owns its own copy)."""
    owners = defaultdict(lambda: {"category": "other", "bytes": 0,
                                  "peak": 0, "allocs": 0, "frees": 0})
    for led in ledgers:
        for o, info in (led.get("owners") or {}).items():
            d = owners[o]
            d["category"] = info.get("category", d["category"])
            for k in ("bytes", "peak", "allocs", "frees"):
                d[k] += info.get(k, 0)
    by_cat = defaultdict(int)
    total = 0
    for info in owners.values():
        by_cat[info["category"]] += info["bytes"]
        total += info["bytes"]
    return {"owners": dict(owners), "by_category": dict(by_cat),
            "total_bytes": total}


def summarize(mem):
    """Machine-readable summary (--json; also what the report prints)."""
    led = mem["ledger"] or {"owners": {}, "by_category": {},
                            "total_bytes": 0}
    wm = mem["watermark"]
    attribution = None
    if wm:
        peak = max(wm.values())
        if peak > 0:
            attribution = led["total_bytes"] / peak
    tracks = {}
    for name, pts in mem["tracks"].items():
        pts = sorted(pts)
        series = defaultdict(list)
        for _, args in pts:
            for k, v in args.items():
                if isinstance(v, (int, float)):
                    series[k].append(v)
        tracks[name] = {k: {"n": len(v), "min": min(v), "max": max(v),
                            "last": v[-1]}
                        for k, v in series.items() if v}
    return {
        "owners": led["owners"],
        "by_category": led["by_category"],
        "total_bytes": led["total_bytes"],
        "watermark_bytes": wm,
        "attributed_fraction": attribution,
        "tracks": tracks,
        "postmortems": mem["postmortems"],
    }


def _spark(vals, width=48):
    if not vals:
        return ""
    if len(vals) > width:           # downsample to the display width
        step = len(vals) / width
        vals = [vals[int(i * step)] for i in range(width)]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1
    return "".join(SPARK[int((v - lo) / span * (len(SPARK) - 1))]
                   for v in vals)


def report(mem, top=15, out=sys.stdout):
    summ = summarize(mem)
    w = out.write
    owners = summ["owners"]
    if owners:
        w("Device-memory ledger (live bytes by owner):\n")
        w(f"  {'owner':<34}{'category':<18}{'bytes':>12}{'peak':>12}"
          f"{'allocs':>8}{'frees':>8}\n")
        rows = sorted(owners.items(), key=lambda kv: -kv[1]["bytes"])
        for o, i in rows[:top]:
            w(f"  {o:<34}{i['category']:<18}{_fmt_bytes(i['bytes']):>12}"
              f"{_fmt_bytes(i['peak']):>12}{i['allocs']:>8}{i['frees']:>8}\n")
        if len(rows) > top:
            w(f"  ... +{len(rows) - top} more owners\n")
        w("\n  by category: "
          + ", ".join(f"{c}={_fmt_bytes(b)}" for c, b in
                      sorted(summ["by_category"].items(),
                             key=lambda kv: -kv[1]))
          + f"  |  TOTAL {_fmt_bytes(summ['total_bytes'])}\n")
    else:
        w("Device-memory ledger: no registered owners.\n")
    if summ["watermark_bytes"]:
        w("\nDevice watermarks (peak bytes_in_use):\n")
        for dev, b in sorted(summ["watermark_bytes"].items()):
            w(f"  {dev:<40}{_fmt_bytes(b):>12}\n")
        if summ["attributed_fraction"] is not None:
            w(f"  ledger attributes {summ['attributed_fraction']:.1%} of "
              "the peak to named owners\n")
    if mem["tracks"]:
        w("\nMemory counter tracks (chrome-trace 'C' events; Perfetto "
          "renders the timeline):\n")
        for name, pts in sorted(mem["tracks"].items()):
            pts = sorted(pts)
            series = defaultdict(list)
            for _, args in pts:
                for k, v in args.items():
                    if isinstance(v, (int, float)):
                        series[k].append(v)
            for k, vals in sorted(series.items()):
                w(f"  {name} / {k}: {len(vals)} samples, "
                  f"last {_fmt_bytes(vals[-1])}, peak "
                  f"{_fmt_bytes(max(vals))}\n    {_spark(vals)}\n")
    if summ["postmortems"]:
        w(f"\nPostmortems ({len(summ['postmortems'])}):\n")
        for rep in summ["postmortems"]:
            tops = ", ".join(
                f"{t['owner']}={_fmt_bytes(t['bytes'])}"
                for t in (rep.get("top_owners") or [])[:3])
            w(f"  [{rep.get('kind', '?')}] at {rep.get('where', '?')} "
              f"(step {rep.get('step', '?')}): failed "
              f"{_fmt_bytes(rep.get('failed_bytes'))}; ledger "
              f"{_fmt_bytes(rep.get('ledger_total_bytes'))}; "
              f"top owners: {tops or 'none'}\n")
    return summ


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument("traces", nargs="+",
                   help="profiler.dump() trace(s), trace_merge output, or "
                        "bare memory_ledger() dump(s); .json.gz ok")
    p.add_argument("--top", type=int, default=15,
                   help="owners shown in the per-owner table")
    p.add_argument("--json", action="store_true",
                   help="print the machine-readable summary instead")
    args = p.parse_args(argv)
    docs = []
    for path in args.traces:
        try:
            docs.append(load_memory(path))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"memory_report: {path}: {e}", file=sys.stderr)
            return 2
    mem = docs[0]
    for other in docs[1:]:
        mem["ledger"] = merge_ledgers(
            [x for x in (mem["ledger"], other["ledger"]) if x])
        mem["postmortems"].extend(other["postmortems"])
        for dev, b in other["watermark"].items():
            if b > mem["watermark"].get(dev, -1):
                mem["watermark"][dev] = b
        for name, pts in other["tracks"].items():
            mem["tracks"].setdefault(name, []).extend(pts)
    if args.json:
        json.dump(summarize(mem), sys.stdout, indent=2)
        print()
        return 0
    report(mem, top=args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
