#!/usr/bin/env python
"""Standalone native data-pipeline benchmark: RecordIO pack → C++ decode/
augment pool → batches, reported as img/s/host (the number that must beat
the chip's consumption rate for input overlap — SURVEY.md hard-part #5).

Usage: python tools/bench_io.py [n_images] [batch_size] [threads]
"""
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    threads = int(sys.argv[3]) if len(sys.argv) > 3 else os.cpu_count()

    import numpy as np
    from PIL import Image

    root = tempfile.mkdtemp(prefix="mxtpu_io_bench_")
    img_dir = os.path.join(root, "imgs", "cls0")
    os.makedirs(img_dir)
    rng = np.random.RandomState(0)
    # realistic ImageNet-ish JPEG sizes
    for i in range(64):
        arr = rng.randint(0, 255, (360, 480, 3), dtype=np.uint8)
        Image.fromarray(arr).save(os.path.join(img_dir, f"i{i}.jpg"), quality=85)
    prefix = os.path.join(root, "pack")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    subprocess.run([sys.executable, os.path.join(repo, "tools", "im2rec.py"),
                    prefix, os.path.join(root, "imgs")],
                   check=True, capture_output=True)

    from incubator_mxnet_tpu.io.record_iter import ImageRecordIter

    def run(epochs):
        it = ImageRecordIter(
            path_imgrec=prefix + ".rec", path_imgidx=prefix + ".idx",
            batch_size=batch, data_shape=(3, 224, 224), shuffle=True,
            preprocess_threads=threads, rand_crop=True, rand_mirror=True)
        seen = 0
        for _ in range(epochs):
            it.reset()
            for b in it:
                seen += b.data[0].shape[0]
        return seen

    run(1)  # warm the pool / page cache
    t0 = time.perf_counter()
    seen = run(max(1, n // 64))
    dt = time.perf_counter() - t0
    print(f"native pipeline: {seen} imgs in {dt:.2f}s -> {seen/dt:.0f} img/s/host "
          f"({threads} decode threads, 224x224 crops from 480x360 JPEGs)")


if __name__ == "__main__":
    main()
