#!/usr/bin/env python
"""Performance-trajectory regression gate (ISSUE 20).

Bench rounds 4–5 silently lost their headline numbers to infra — an
outage round looks exactly like a catastrophic regression unless the
harvester distinguishes them.  This tool reads every ``BENCH_r*.json``
round (and optionally the scaling harness's ``--json`` evidence) into
ONE classified trajectory:

* ``good``                — rc 0 and a parsed numeric value,
* ``backend_unavailable`` — the bench ran but the backend never came up
  (``parsed.value`` null / an ``error`` field / nonzero rc with no
  value): **reported, never gated** — an outage is not a regression,

compares the newest good round of each metric against the committed
rolling baseline (``docs/PERF_BASELINE.json``), and exits non-zero on a
``>X%`` drop (``--threshold``, default 0.25 — generous: real-hardware
rounds carry machine variance; the gate exists to catch the 2x cliff,
not 3% noise).  Scaling evidence is gated structurally: the harness's
own acceptance gates (efficiency floor, zero post-warmup recompiles,
attribution match) must have passed.

Usage::

    python tools/perf_history.py [--bench-glob 'BENCH_r*.json']
        [--baseline docs/PERF_BASELINE.json] [--threshold 0.25]
        [--scaling EVIDENCE.json] [--update-baseline] [--json]

``--update-baseline`` rewrites the committed baseline from the rolling
median of the newest good rounds (run it deliberately, commit the
diff — the baseline is reviewed history, not a ratchet that silently
follows every fast round).

Exit codes: 0 ok, 1 regression / failed scaling gate, 2 unreadable input.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# rolling-baseline window: median of the newest K good rounds
_BASELINE_WINDOW = 5


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def classify_round(doc):
    """One bench round -> ``(status, metric, value)``.

    ``backend_unavailable`` covers every infra shape the rounds have
    actually produced: an explicit ``status``/``error`` field with a
    null value (r05), and a nonzero rc with nothing parsed at all (r04,
    the backend-init traceback)."""
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        metric = parsed.get("metric")
        value = parsed.get("value")
        if isinstance(value, (int, float)):
            return "good", metric, float(value)
        return "backend_unavailable", metric, None
    if doc.get("rc", 0) != 0:
        return "backend_unavailable", None, None
    return "no_metric", None, None


def load_trajectory(bench_glob):
    """Every round, classified, ordered by round number:
    ``{metric: [{"round", "status", "value"}]}`` plus the unattributed
    infra rounds under the ``None`` key."""
    rounds = []
    for path in sorted(glob.glob(bench_glob)):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ValueError(f"{path}: unreadable bench round: {e}")
        status, metric, value = classify_round(doc)
        rounds.append({"round": doc.get("n"), "path": path,
                       "status": status, "metric": metric, "value": value})
    traj = {}
    for r in rounds:
        traj.setdefault(r["metric"], []).append(r)
    return traj


def load_baseline(path):
    if not os.path.exists(path):
        return {"schema": 1, "metrics": {}}
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc.get("metrics"), dict):
        raise ValueError(f"{path}: not a perf baseline (no 'metrics')")
    return doc


def rebuild_baseline(traj, window=_BASELINE_WINDOW):
    metrics = {}
    for metric, rounds in traj.items():
        if metric is None:
            continue
        good = [r for r in rounds if r["status"] == "good"]
        if not good:
            continue
        tail = good[-window:]
        metrics[metric] = {
            "baseline": round(_median([r["value"] for r in tail]), 3),
            "window_rounds": [r["round"] for r in tail],
        }
    return {"schema": 1, "metrics": metrics}


def check_metrics(traj, baseline, threshold):
    """Newest good round of each metric vs its committed baseline.
    Returns (failures, report_rows)."""
    failures, rows = [], []
    for metric, rounds in sorted(traj.items(), key=lambda kv: str(kv[0])):
        if metric is None:
            for r in rounds:
                rows.append({"metric": None, "round": r["round"],
                             "status": r["status"], "value": None,
                             "verdict": "ignored (infra)"})
            continue
        infra = sum(1 for r in rounds if r["status"] != "good")
        good = [r for r in rounds if r["status"] == "good"]
        base = (baseline.get("metrics") or {}).get(metric, {}).get("baseline")
        if not good:
            rows.append({"metric": metric, "round": None,
                         "status": "backend_unavailable", "value": None,
                         "verdict": f"no good round ({infra} infra) — "
                                    "not a regression"})
            continue
        latest = good[-1]
        row = {"metric": metric, "round": latest["round"],
               "status": "good", "value": latest["value"],
               "baseline": base, "infra_rounds": infra}
        if base is None:
            row["verdict"] = "no baseline (run --update-baseline)"
        else:
            floor = base * (1.0 - threshold)
            if latest["value"] < floor:
                row["verdict"] = (f"REGRESSION: {latest['value']} < "
                                  f"{floor:.3f} ({threshold:.0%} below "
                                  f"baseline {base})")
                failures.append(row)
            else:
                row["verdict"] = (f"ok ({latest['value'] / base - 1:+.1%} "
                                  "vs baseline)")
        rows.append(row)
    return failures, rows


def check_scaling(path):
    """Scaling-harness evidence: the gates the harness computed must have
    passed, and no point may have recompiled post-warmup."""
    with open(path) as f:
        ev = json.load(f)
    problems = []
    gates = ev.get("gates") or {}
    if not ev.get("pass"):
        problems.append(f"harness gates failed: {gates}")
    for pt in ev.get("points") or []:
        if pt.get("recompile_steady_state", 0) != 0:
            problems.append(
                f"point devices={pt.get('devices')} procs={pt.get('procs')}"
                f" recompiled post-warmup "
                f"({pt['recompile_steady_state']}x)")
    curve = [[pt.get("devices", 1) * pt.get("procs", 1),
              pt.get("samples_per_sec"), pt.get("efficiency")]
             for pt in ev.get("points") or []]
    return problems, {"curve": curve, "gates": gates,
                      "pass": not problems}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--bench-glob",
                    default=os.path.join(_REPO, "BENCH_r*.json"))
    ap.add_argument("--baseline",
                    default=os.path.join(_REPO, "docs",
                                         "PERF_BASELINE.json"))
    ap.add_argument("--threshold", type=float,
                    default=float(os.environ.get(
                        "MXNET_PERF_REGRESSION_PCT", "0.25")))
    ap.add_argument("--scaling", default=None,
                    help="scaling.py --json evidence to gate structurally")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline from the good-round rolling "
                         "median and exit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    try:
        traj = load_trajectory(args.bench_glob)
    except ValueError as e:
        print(f"perf_history: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        doc = rebuild_baseline(traj)
        os.makedirs(os.path.dirname(args.baseline) or ".", exist_ok=True)
        with open(args.baseline, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"perf_history: baseline -> {args.baseline} "
              f"({len(doc['metrics'])} metric(s))")
        return 0

    try:
        baseline = load_baseline(args.baseline)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"perf_history: {e}", file=sys.stderr)
        return 2

    failures, rows = check_metrics(traj, baseline, args.threshold)
    scaling_report = None
    if args.scaling:
        try:
            problems, scaling_report = check_scaling(args.scaling)
        except (OSError, json.JSONDecodeError) as e:
            print(f"perf_history: scaling evidence unreadable: {e}",
                  file=sys.stderr)
            return 2
        for p in problems:
            failures.append({"metric": "scaling", "verdict": p})

    if args.json:
        print(json.dumps({"schema": 1, "rows": rows,
                          "scaling": scaling_report,
                          "failures": failures,
                          "pass": not failures}, indent=1))
    else:
        for row in rows:
            val = (f"{row['value']}" if row.get("value") is not None
                   else "-")
            print(f"perf_history: {row.get('metric') or '<infra>'} "
                  f"round {row.get('round')}: {val} — {row['verdict']}")
        if scaling_report is not None:
            print(f"perf_history: scaling curve "
                  f"{scaling_report['curve']} — "
                  f"{'ok' if scaling_report['pass'] else 'FAILED'}")
        for f_ in failures:
            print(f"perf_history: FAIL {f_.get('metric')}: "
                  f"{f_['verdict']}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
