#!/usr/bin/env python
"""Measurement-window corroboration for bench.py (VERDICT r4 item 2).

Runs the BERT bench at MXNET_TPU_BENCH_STEPS = 60/120/180/360 (or
--steps ...), recovers the measured wall time per run from the reported
throughput (dt = items_per_step·steps / (value·chips), where
items_per_step is B for samples/s metrics and 2·B·S for the transformer's
tokens/s — mirroring bench.py:305), and fits dt = intercept + slope·steps.  The claim under test: per-step time (the slope) is
window-invariant and the intercept equals the fence's fixed D2H cost —
i.e. the 180-step window amortizes measurement overhead without touching
the steady-state rate.  If the slope drifts with window, the gate number
reverts to the 60-step discipline.

Run on the real chip (ambient axon env):
    python tools/bench_window_sweep.py
    MXNET_TPU_BENCH=transformer python tools/bench_window_sweep.py
Emits a markdown table + fit for docs/PERF_NOTES.md, plus one JSON line.
"""
import argparse
import json
import os
import subprocess
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _chip_count():
    import jax

    return max(1, len(jax.devices()))


def run_once(steps, batch, n_chips):
    env = dict(os.environ)
    env["MXNET_TPU_BENCH_STEPS"] = str(steps)
    env["MXNET_TPU_BENCH_BATCH"] = str(batch)  # keep bench and fit in sync
    r = subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                       capture_output=True, text=True, timeout=3600, env=env)
    line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
    rec = json.loads(line)
    if rec.get("value") in (None, 0):
        raise RuntimeError(f"bench failed at steps={steps}: {rec.get('error')}")
    # bench reports per-CHIP throughput (global/dt/n_chips); undo the chip
    # division or the intercept inflates n_chips-fold.  The transformer
    # config reports tokens/s = 2·B·S·steps/dt (src+tgt, bench.py:305), so
    # recover dt with the per-step token count or the fit's intercept is
    # off by 2·S and loses its D2H-fixed-cost reading.
    per_step = batch * 1.0
    unit = rec.get("unit", "samples/sec/chip")
    if "tokens" in unit:
        per_step *= 2 * int(os.environ.get("MXNET_TPU_BENCH_SEQ", "256"))
    dt = per_step * steps / (rec["value"] * n_chips)
    return rec["value"], dt, unit, per_step


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, nargs="+", default=[60, 120, 180, 360])
    ap.add_argument("--batch", type=int,
                    default=int(os.environ.get("MXNET_TPU_BENCH_BATCH", "64")))
    ap.add_argument("--repeats", type=int, default=1)
    args = ap.parse_args()

    n_chips = _chip_count()
    rows = []
    unit, per_step = "samples/sec/chip", float(args.batch)
    for s in args.steps:
        for _ in range(args.repeats):
            val, dt, unit, per_step = run_once(s, args.batch, n_chips)
            rows.append((s, val, dt))
            print(f"# steps={s}: {val} {unit}, dt={dt:.3f} s", flush=True)

    xs = np.array([r[0] for r in rows], float)
    ys = np.array([r[2] for r in rows], float)
    slope, intercept = np.polyfit(xs, ys, 1)
    resid = ys - (intercept + slope * xs)

    print(f"\n| steps | {unit} | dt (s) | fit residual (ms) |")
    print("|---|---|---|---|")
    for (s, val, dt), r in zip(rows, resid):
        print(f"| {s} | {val} | {dt:.3f} | {r * 1e3:+.1f} |")
    per_step_ms = slope * 1e3
    steady = per_step / slope / n_chips
    print(f"\nfit: dt = {intercept:.3f} s + {per_step_ms:.3f} ms/step "
          f"(window-invariant steady rate = {steady:.1f} {unit}; "
          f"intercept = fixed fence/D2H cost)")
    print(json.dumps({
        "metric": "bench_window_fit",
        "unit": unit,
        "slope_ms_per_step": round(per_step_ms, 4),
        "intercept_s": round(intercept, 4),
        "steady_per_sec_per_chip": round(steady, 1),
        "max_abs_residual_ms": round(float(np.abs(resid).max() * 1e3), 2),
    }))


if __name__ == "__main__":
    main()
