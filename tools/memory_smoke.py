#!/usr/bin/env python
"""Device-memory observability smoke (tools/ci.sh ``profiler`` tier).

Drives a short train + serve run with the span recorder armed and
asserts the ISSUE 12 acceptance bar end to end:

* the ledger attributes ≥ 90 % of the peak device ``bytes_in_use`` to
  named owners (backends without ``memory_stats`` — CPU — are checked
  against an independently computed expected footprint instead, which is
  the stricter wiring test);
* the expected owners are present and exact: ``trainer.params`` /
  ``trainer.optimizer_state`` (weight+grad+state bytes of the gluon
  trainer) and ``predictor.params`` (the serving tier's bound store);
* the dumped chrome trace carries a memory counter track (``"C"``
  events) and ``tools/memory_report.py`` renders it (exit 0, owners
  listed);
* a forced budget breach produces EXACTLY ONE postmortem naming the top
  owner and the failed allocation size;
* ``Trainer.close()`` releases its ledger share.

Exit 0 = all of the above; non-zero with a one-line diagnosis otherwise.
"""
import io
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def fail(msg):
    print(f"memory_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def _nd_bytes(x):
    if x is None:
        return 0
    if isinstance(x, (list, tuple)):
        return sum(_nd_bytes(s) for s in x)
    n = 1
    for d in x.shape:
        n *= int(d)
    return n * np.dtype(x.dtype).itemsize


def main():
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, profiler
    from incubator_mxnet_tpu.gluon import Trainer, nn
    from incubator_mxnet_tpu.serving import InferenceServer
    import incubator_mxnet_tpu.symbol as S

    profiler.disarm_compile_guard()
    trace = os.path.join(tempfile.gettempdir(),
                         f"memory_smoke_{os.getpid()}.json")
    profiler.set_config(filename=trace)
    profiler.start()

    # -- train: gluon Trainer owns params + grads + optimizer state -----
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(32), nn.Dense(8))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).rand(16, 24).astype(np.float32))
    net(x)
    opt = mx.optimizer.create("adam", learning_rate=0.01)
    opt.aggregate_num = 100
    tr = Trainer(net.collect_params(), opt)
    for _ in range(3):
        with autograd.record():
            loss = (net(x) * net(x)).sum()
        loss.backward()
        tr.step(16)
    led1 = profiler.memory_ledger()

    exp_train = sum(2 * _nd_bytes(p._data)
                    for p in net.collect_params().values())
    exp_state = sum(_nd_bytes(st) for st in tr._states.values())
    got_p = led1["owners"].get("trainer.params", {}).get("bytes", 0)
    got_s = led1["owners"].get("trainer.optimizer_state", {}).get("bytes", 0)
    if got_p != exp_train:
        fail(f"trainer.params ledger bytes {got_p} != expected {exp_train}")
    if got_s != exp_state:
        fail(f"trainer.optimizer_state ledger bytes {got_s} != expected "
             f"{exp_state}")
    # donation exactness: two more steps must not move a single byte
    for _ in range(2):
        with autograd.record():
            loss = (net(x) * net(x)).sum()
        loss.backward()
        tr.step(16)
    led2 = profiler.memory_ledger()
    if led2["owners"]["trainer.params"]["bytes"] != got_p \
            or led2["owners"]["trainer.optimizer_state"]["bytes"] != got_s:
        fail("donated optimizer steps moved ledger bytes "
             f"({got_p}/{got_s} -> "
             f"{led2['owners']['trainer.params']['bytes']}/"
             f"{led2['owners']['trainer.optimizer_state']['bytes']})")

    # -- serve: InferenceServer's predictor owns the bound store --------
    S.symbol._reset_naming()
    data = S.var("data")
    fc = S.FullyConnected(data, num_hidden=6, flatten=False, name="fc1")
    sym = S.Activation(fc, act_type="tanh", name="t1")
    srng = np.random.RandomState(3)
    params = {"arg:fc1_weight": mx.nd.array(
                  srng.randn(6, 4).astype(np.float32)),
              "arg:fc1_bias": mx.nd.array(srng.randn(6).astype(np.float32))}
    exp_store = sum(_nd_bytes(v) for v in params.values())
    srv = InferenceServer(sym, params, {"data": (None, 4)},
                          max_batch_size=4, max_queue_ms=20.0,
                          length_buckets=[8], batch_buckets=[4],
                          name="memory_smoke")
    try:
        for L in (3, 8, 5):
            srv.infer({"data": srng.rand(L, 4).astype(np.float32)},
                      timeout=30.0)
        led3 = profiler.memory_ledger()
        got_pred = led3["owners"].get("predictor.params", {}).get("bytes", 0)
        if got_pred != exp_store:
            fail(f"predictor.params ledger bytes {got_pred} != store bytes "
                 f"{exp_store}")
    finally:
        srv.close()
    if profiler.memory_ledger()["owners"].get(
            "predictor.params", {}).get("bytes", 0) != 0:
        fail("InferenceServer.close() did not release predictor.params")

    # -- attribution: ledger vs peak device bytes_in_use ----------------
    led = profiler.memory_ledger()
    dev = profiler.device_memory_stats()
    expected = exp_train + exp_state
    if dev:
        peak = max(s["peak_bytes_in_use"] for s in dev.values())
        frac = led["total_bytes"] / peak if peak else 1.0
        print(f"memory_smoke: device peak {peak} bytes, ledger attributes "
              f"{frac:.1%}")
        if frac < 0.9:
            fail(f"ledger attributes only {frac:.1%} of peak bytes_in_use "
                 "(>= 90% required)")
    else:
        # no memory_stats on this backend (CPU): the wiring check against
        # the independently computed footprint is the bar instead
        if expected <= 0 or led["total_bytes"] < 0.9 * expected:
            fail(f"ledger total {led['total_bytes']} < 90% of expected "
                 f"{expected} (no device stats on this backend)")

    # -- dump: counter track + memory_report must render it -------------
    path = profiler.dump()
    with open(path) as f:
        doc = json.load(f)
    cev = [e for e in doc["traceEvents"]
           if e.get("ph") == "C" and str(e.get("name", "")).startswith(
               "memory")]
    if not cev:
        fail("dumped trace carries no memory counter track ('C' events)")
    import memory_report

    buf = io.StringIO()
    memory_report.report(memory_report.load_memory(path), out=buf)
    text = buf.getvalue()
    print(text)
    for owner in ("trainer.params", "trainer.optimizer_state"):
        if owner not in text:
            fail(f"memory_report output misses owner {owner}")

    # -- forced budget breach: EXACTLY ONE postmortem -------------------
    budget = profiler.MemoryBudget(limit_mb=1)
    before = profiler.counters()["memory_oom_postmortem"]
    try:
        budget.check(64 << 20, "memory_smoke.forced")
        fail("budget.check let a 64 MiB allocation through a 1 MiB budget")
    except profiler.MemoryBudgetError:
        pass
    after = profiler.counters()["memory_oom_postmortem"]
    if after - before != 1:
        fail(f"forced budget breach produced {after - before} postmortems, "
             "expected exactly 1")
    rep = profiler.memory_postmortems()[-1]
    if rep["failed_bytes"] != 64 << 20:
        fail(f"postmortem failed_bytes {rep['failed_bytes']} != {64 << 20}")
    top = sorted(led["owners"].items(), key=lambda kv: -kv[1]["bytes"])[0][0]
    if not rep["top_owners"] or rep["top_owners"][0]["owner"] != top:
        fail(f"postmortem top owner {rep['top_owners'][:1]} != ledger top "
             f"{top}")

    # -- trainer close releases its share -------------------------------
    tr.close()
    led4 = profiler.memory_ledger()
    if led4["owners"].get("trainer.params", {}).get("bytes", 0) != 0:
        fail("Trainer.close() did not release trainer.params")

    os.unlink(path)
    print("memory_smoke OK: "
          f"{len(led['owners'])} owners, ledger {led['total_bytes']} bytes, "
          f"{len(cev)} counter-track events, exactly 1 postmortem on the "
          "forced breach")
    return 0


if __name__ == "__main__":
    sys.exit(main())
