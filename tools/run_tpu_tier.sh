#!/usr/bin/env bash
# On-chip test tier: run the FULL tests/ suite file-by-file on the real
# TPU (MXNET_TEST_CTX=tpu — tests/conftest.py skips mesh-contract files
# with documented reasons), appending per-file results to the log.
# File-by-file (not one pytest run) so a wedged tunnel costs one file's
# timeout, not the tier; each file gets its own process + fresh backend.
#
# Usage: tools/run_tpu_tier.sh [logfile] [per-file timeout seconds]
set -u
cd "$(dirname "$0")/.."

LOG="${1:-docs/TPU_TIER_LOG_r04.txt}"
TMO="${2:-420}"

{
    echo "# On-chip tier (MXNET_TEST_CTX=tpu), $(date -u +%FT%TZ)"
    echo "# per-file timeout ${TMO}s; mesh-contract files skip via conftest"
    python - <<'PYEOF'
import jax
print(f"# backend: {jax.default_backend()}, devices: {jax.devices()}")
PYEOF
} > "$LOG"

PASS=0; FAIL=0; TOUT=0; SKIPFILES=0
for f in tests/test_*.py; do
    base=$(basename "$f")
    start=$SECONDS
    out=$(MXNET_TEST_CTX=tpu timeout "$TMO" python -m pytest "$f" -q --no-header 2>&1)
    rc=$?
    dur=$((SECONDS - start))
    tail_line=$(echo "$out" | grep -E "passed|failed|skipped|error" | tail -1)
    if [ $rc -eq 124 ]; then
        echo "TIMEOUT  ${base} (${dur}s)" >> "$LOG"
        TOUT=$((TOUT + 1))
    elif [ $rc -eq 0 ]; then
        if echo "$tail_line" | grep -q "passed"; then
            echo "PASS     ${base} (${dur}s): ${tail_line}" >> "$LOG"
            PASS=$((PASS + 1))
        else
            echo "SKIP     ${base} (${dur}s): ${tail_line}" >> "$LOG"
            SKIPFILES=$((SKIPFILES + 1))
        fi
    else
        echo "FAIL     ${base} (${dur}s): ${tail_line}" >> "$LOG"
        echo "$out" | tail -20 | sed 's/^/    | /' >> "$LOG"
        FAIL=$((FAIL + 1))
    fi
done
echo "# summary: ${PASS} files passed, ${FAIL} failed, ${TOUT} timed out, ${SKIPFILES} all-skipped" >> "$LOG"
tail -1 "$LOG"
[ $FAIL -eq 0 ]
