#!/usr/bin/env python
"""im2rec — pack an image folder/list into RecordIO (parity:
[U:tools/im2rec.py]).  Produces ``.rec`` + ``.idx`` files readable by both
the native C++ pipeline and the reference format.

Usage:
  python tools/im2rec.py <prefix> <root> --list        # generate .lst
  python tools/im2rec.py <prefix> <root>               # pack from .lst
List format (reference-compatible): ``index\\tlabel\\trelpath`` per line.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from incubator_mxnet_tpu.recordio import (  # noqa: E402
    IRHeader, MXIndexedRecordIO, pack, pack_img)

_EXTS = (".jpg", ".jpeg", ".png")


def make_list(prefix, root):
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    label_of = {c: i for i, c in enumerate(classes)}
    entries = []
    if classes:
        for c in classes:
            for fn in sorted(os.listdir(os.path.join(root, c))):
                if fn.lower().endswith(_EXTS):
                    entries.append((label_of[c], os.path.join(c, fn)))
    else:
        for fn in sorted(os.listdir(root)):
            if fn.lower().endswith(_EXTS):
                entries.append((0, fn))
    with open(prefix + ".lst", "w") as f:
        for i, (label, rel) in enumerate(entries):
            f.write(f"{i}\t{float(label)}\t{rel}\n")
    print(f"wrote {len(entries)} entries to {prefix}.lst")


def pack_list(prefix, root, quality=95, resize=0):
    rec = MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    with open(prefix + ".lst") as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx, label, rel = int(parts[0]), float(parts[1]), parts[2]
            path = os.path.join(root, rel)
            header = IRHeader(0, label, idx, 0)
            is_jpeg = rel.lower().endswith((".jpg", ".jpeg"))
            if resize or not is_jpeg:
                # non-JPEG sources are re-encoded: the native training
                # pipeline (native/mxtpu_io.cpp) decodes JPEG only
                import numpy as np
                from PIL import Image
                img = Image.open(path).convert("RGB")
                if resize:
                    w, h = img.size
                    scale = resize / min(w, h)
                    img = img.resize((int(w * scale + 0.5), int(h * scale + 0.5)),
                                     Image.BILINEAR)
                rec.write_idx(idx, pack_img(header, np.asarray(img), quality))
            else:
                with open(path, "rb") as imf:
                    rec.write_idx(idx, pack(header, imf.read()))
            n += 1
    rec.close()
    print(f"packed {n} images into {prefix}.rec")


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix")
    p.add_argument("root")
    p.add_argument("--list", action="store_true", help="generate .lst only")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--resize", type=int, default=0,
                   help="resize shorter side before packing")
    args = p.parse_args()
    if args.list:
        make_list(args.prefix, args.root)
    else:
        if not os.path.exists(args.prefix + ".lst"):
            make_list(args.prefix, args.root)
        pack_list(args.prefix, args.root, args.quality, args.resize)


if __name__ == "__main__":
    main()
