#!/usr/bin/env python
"""2-process cluster-observability smoke (tools/ci.sh ``profiler`` tier).

Drives the whole ISSUE-7 loop end to end on one host:

* launches 2 dist_async workers through ``tools/launch_local.py``; each
  runs a tiny recorder-on push/pull loop with step boundaries and dumps a
  per-rank chrome trace carrying process metadata + a clock-offset
  estimate (sampled over the PS wire);
* rank 0 serves live metrics on an ephemeral port and asserts its OWN
  ``GET /metrics`` scrape contains counters and step buckets from BOTH
  ranks (rank 1's snapshots arrive via heartbeat piggyback), then forces
  one anomalous step and asserts the straggler attribution line fired
  exactly once;
* the driver merges the two traces (``tools/trace_merge.py``) and checks
  one process row per rank with offset-corrected monotone step spans, and
  exercises ``trace_report.py --merge`` on the same pair.

Exit 0 = healthy.  Usage: ``python tools/dist_trace_smoke.py`` (the
``--worker`` mode is internal).
"""
from __future__ import annotations

import argparse
import logging
import os
import subprocess
import sys
import tempfile
import time

TOOLS = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(TOOLS)
sys.path.insert(0, ROOT)
sys.path.insert(0, TOOLS)


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------

def worker():
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import kvstore as kv_mod, profiler

    rank = int(os.environ["DMLC_WORKER_ID"])
    outdir = os.environ["MXNET_TRACE_SMOKE_DIR"]
    profiler.set_config(filename=os.path.join(outdir, f"trace_rank{rank}.json"))
    profiler.start()
    port = profiler.start_metrics(port=0) if rank == 0 else None

    kv = kv_mod.create("dist_async")
    kv.init("w", mx.nd.zeros((4,)))
    out = mx.nd.zeros((4,))
    for _ in range(6):
        with profiler.span("smoke_fwd", "user"):
            time.sleep(0.002 + 0.004 * rank)  # rank 1 is genuinely slower
        kv.pushpull("w", mx.nd.ones((4,)), out=out)
        profiler.step_boundary()

    if rank == 0:
        import urllib.request

        # the peer's step telemetry rides its heartbeat (lease/3 cadence);
        # poll the LIVE endpoint until the cluster view is complete
        deadline = time.monotonic() + 20.0
        need = ('mxnet_profiler_counter_total', 'rank="1"',
                'mxnet_step_last_wall_ms')
        body = ""
        while time.monotonic() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                body = r.read().decode()
            if all(n in body for n in need):
                break
            time.sleep(0.25)
        missing = [n for n in need if n not in body]
        assert not missing, f"rank-0 scrape never aggregated: {missing}"
        assert 'mxnet_step_last_comms_ms{rank="1"' in body, \
            "peer step buckets missing from the scrape"

        # straggler attribution: one anomalous step -> exactly one line
        records = []
        h = logging.Handler()
        h.emit = lambda rec: records.append(rec)
        logging.getLogger("incubator_mxnet_tpu.profiler").addHandler(h)
        profiler.set_config(slow_step_ms=100000.0)
        profiler.step_boundary()          # absorb the scrape/poll gap
        profiler.set_config(slow_step_ms=10.0)
        time.sleep(0.05)
        profiler.step_boundary()          # THE anomalous step
        profiler.set_config(slow_step_ms=None)
        straggler = [r for r in records if "straggler" in r.getMessage()]
        assert len(straggler) == 1, \
            f"want exactly 1 straggler line, got {len(straggler)}"
        msg = straggler[0].getMessage()
        assert "host-dispatch" in msg and "comms" in msg, msg

    kv.barrier()   # both ranks' telemetry settled before anyone leaves
    kv.close()
    path = profiler.dump()
    assert os.path.exists(path)
    info = profiler.process_info()
    assert info["rank"] == rank
    if rank != 0:   # rank 0 talks to its co-located PS: offset may be ~0
        assert info["clock_rtt_s"] is not None, "clock never sampled"
    print(f"trace smoke worker OK (rank {rank})", flush=True)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def driver():
    import trace_merge

    tmp = tempfile.mkdtemp(prefix="dist_trace_smoke_")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)          # workers boot their own backend
    env["JAX_PLATFORMS"] = "cpu"
    env["MXNET_TRACE_SMOKE_DIR"] = tmp
    env["MXNET_KVSTORE_LEASE_S"] = "2.0"   # heartbeat ~0.66 s: snapshots
    proc = subprocess.run(                 # reach the PS fast
        [sys.executable, os.path.join(TOOLS, "launch_local.py"), "-n", "2",
         sys.executable, os.path.abspath(__file__), "--worker"],
        env=env, capture_output=True, text=True, timeout=240)
    sys.stdout.write(proc.stdout[-4000:])
    sys.stderr.write(proc.stderr[-4000:])
    assert proc.returncode == 0, f"workers failed (rc={proc.returncode})"
    assert proc.stdout.count("trace smoke worker OK") == 2

    traces = [os.path.join(tmp, f"trace_rank{r}.json") for r in (0, 1)]
    merged = os.path.join(tmp, "merged.json")
    rc = trace_merge.main(traces + ["-o", merged, "--check",
                                    "--expect-ranks", "2"])
    assert rc == 0, "trace_merge --check failed"

    # both ranks really sampled a clock anchor into their dumps
    doc = trace_merge.load_trace(merged)
    for rank in ("0", "1"):
        proc_meta = doc["otherData"]["ranks"][rank]["process"]
        assert proc_meta.get("epoch_unix") is not None

    # the trace_report --merge front door on the same pair
    rep = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "trace_report.py")] + traces
        + ["--merge", os.path.join(tmp, "merged_report.json"), "--top", "5"],
        capture_output=True, text=True, timeout=120)
    assert rep.returncode == 0, rep.stderr
    assert "Per-rank attribution" in rep.stdout
    print("dist trace smoke OK")
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--worker", action="store_true",
                   help="internal: run as a launched worker")
    args = p.parse_args(argv)
    if args.worker:
        worker()
        return 0
    return driver()


if __name__ == "__main__":
    sys.exit(main())
