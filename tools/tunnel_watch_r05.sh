#!/usr/bin/env bash
# Axon-tunnel watcher for the round-5 evidence capture.
#
# The pool worker behind the tunnel goes down without warning (round-4
# wedge; round-5 start; again 2026-07-31 ~03:20 UTC after passing a small
# probe and then dying under the first real transfer).  This loop:
#   1. probes every 2 min with a tiny matmul (90 s timeout),
#   2. on success, runs a LOAD probe (~256 MB transfer + batched matmul,
#      the pattern that wedged the worker) before trusting the tunnel,
#   3. then (re)launches tools/r05_evidence.sh all,
#   4. exits once the capture has written its completion marker.
#
# Run detached: nohup tools/tunnel_watch_r05.sh >/tmp/tunnel_watch_r05.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
EV=docs/BENCH_EVIDENCE_r05.txt

stamp() { date -u +%FT%TZ; }

LAUNCHES=0
while true; do
    if [ "$LAUNCHES" -ge 4 ]; then
        echo "[$(stamp)] relaunch cap (4) reached -> watcher exiting; inspect $EV"
        exit 1
    fi
    # r05_evidence.sh writes the completion marker unconditionally (it
    # records per-section errors and moves on), so the marker alone does
    # not mean the capture succeeded: require at least one real metric
    # AND the tier log (the last section) before standing down.
    if grep -qs "evidence capture complete" "$EV" \
            && grep -qs '"value":' "$EV" \
            && [ -s docs/TPU_TIER_LOG_r05.txt ]; then
        echo "[$(stamp)] capture complete with results -> watcher exiting"
        exit 0
    fi
    if pgrep -f "r05_evidence.sh" >/dev/null 2>&1; then
        sleep 300
        continue
    fi
    if timeout 90 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((8, 8)); (x @ x).block_until_ready()
print('small probe ok')
" 2>/dev/null; then
        if timeout 300 python -c "
import jax, jax.numpy as jnp, numpy as np
a = jnp.asarray(np.ones((64, 1024, 1024), np.float32)); a.block_until_ready()
b = jnp.einsum('bij,bjk->bik', a[:8], a[:8]); b.block_until_ready()
print('load probe ok')
" 2>/dev/null; then
            echo "[$(stamp)] tunnel healthy under load -> launching capture"
            LAUNCHES=$((LAUNCHES + 1))
            nohup bash tools/r05_evidence.sh all >>/tmp/r05_evidence_run.log 2>&1 &
            sleep 600
            continue
        else
            echo "[$(stamp)] small probe ok but LOAD probe failed (worker dies under load)"
        fi
    else
        echo "[$(stamp)] tunnel down (small probe timeout)"
    fi
    sleep 120
done
