#!/usr/bin/env python
"""Independent ResNet-50 control: an idiomatic raw-JAX train step with NO
framework code, same batch/chip/fence discipline as ``bench.py``'s
resnet50 config (VERDICT r4 item 4a).

Purpose: establish the CEILING the framework should be judged against.  If
this control lands within a few percent of the framework's img/s, the
framework adds no overhead and the remaining gap to 50% MFU is an XLA/
convolution property on this chip, not a framework defect.  If the control
is much faster, the framework has work to do.

Architecture matches ``gluon.model_zoo.vision.resnet50_v1`` (v1 bottleneck,
BN+ReLU, 224², 1000 classes) with the same bf16-AMP policy: bf16 conv/
matmul inputs, fp32 BN statistics/params, fp32 SGD-momentum.

Run (real chip, ambient axon env):
    python tools/resnet_control.py                 # B=256, 60 steps
    MXNET_TPU_BENCH_BATCH=128 python tools/resnet_control.py
Prints one JSON line: {"metric": "resnet50_control_img_per_sec", ...}.
"""
import functools
import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


# ---------------------------------------------------------------------------
# model: functional ResNet-50 v1 (params as a pytree of dicts)
# ---------------------------------------------------------------------------

STAGES = (3, 4, 6, 3)
WIDTHS = (256, 512, 1024, 2048)


def _conv_init(key, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (cout, cin, kh, kw), jnp.float32) * np.sqrt(2.0 / fan_in)


def init_params(key):
    params = {}
    k = iter(jax.random.split(key, 200))
    params["conv0"] = _conv_init(next(k), 7, 7, 3, 64)
    params["bn0"] = {"g": jnp.ones(64), "b": jnp.zeros(64)}
    cin = 64
    for si, (blocks, width) in enumerate(zip(STAGES, WIDTHS)):
        mid = width // 4
        for bi in range(blocks):
            p = {}
            p["c1"] = _conv_init(next(k), 1, 1, cin, mid)
            p["bn1"] = {"g": jnp.ones(mid), "b": jnp.zeros(mid)}
            p["c2"] = _conv_init(next(k), 3, 3, mid, mid)
            p["bn2"] = {"g": jnp.ones(mid), "b": jnp.zeros(mid)}
            p["c3"] = _conv_init(next(k), 1, 1, mid, width)
            p["bn3"] = {"g": jnp.ones(width), "b": jnp.zeros(width)}
            if bi == 0:
                p["proj"] = _conv_init(next(k), 1, 1, cin, width)
                p["bnp"] = {"g": jnp.ones(width), "b": jnp.zeros(width)}
            params[f"s{si}b{bi}"] = p
            cin = width
    params["fc_w"] = jax.random.normal(next(k), (2048, 1000), jnp.float32) * 0.01
    params["fc_b"] = jnp.zeros(1000)
    return params


def _conv(x, w, stride=1):
    # bf16 in, bf16 out (MXU accumulates fp32 internally; fp32
    # preferred_element_type breaks the conv gradient's dtype matching) —
    # BN immediately recomputes statistics in fp32 anyway
    return lax.conv_general_dilated(
        x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
        window_strides=(stride, stride),
        padding=[(w.shape[2] // 2, w.shape[2] // 2)] * 2,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def _bn_relu(x, bn, relu=True):
    # training-mode batch norm, fp32 statistics (one-pass E[x²]−E[x]²,
    # clamped: fp32 cancellation can drive the difference slightly negative)
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 2, 3))
    var = jnp.maximum(jnp.mean(jnp.square(x32), axis=(0, 2, 3))
                      - jnp.square(mean), 0.0)
    inv = lax.rsqrt(var + 1e-5) * bn["g"]
    out = (x32 - mean[None, :, None, None]) * inv[None, :, None, None] \
        + bn["b"][None, :, None, None]
    return jnp.maximum(out, 0.0) if relu else out


def _bottleneck(x, p, stride):
    # v1 bottleneck: stride on the FIRST 1x1, matching the framework's
    # BottleneckV1 (gluon/model_zoo/vision/resnet.py) — NOT v1.5's strided
    # 3x3; the control must be like-for-like or its ceiling is misstated
    h = _bn_relu(_conv(x, p["c1"], stride), p["bn1"])
    h = _bn_relu(_conv(h, p["c2"]), p["bn2"])
    h = _bn_relu(_conv(h, p["c3"]), p["bn3"], relu=False)
    if "proj" in p:
        x = _bn_relu(_conv(x, p["proj"], stride), p["bnp"], relu=False)
    return jnp.maximum(h + x, 0.0)


def forward(params, x):
    h = _conv(x, params["conv0"], stride=2)
    h = _bn_relu(h, params["bn0"])
    h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 1, 3, 3), (1, 1, 2, 2),
                          [(0, 0), (0, 0), (1, 1), (1, 1)])
    for si, blocks in enumerate(STAGES):
        for bi in range(blocks):
            stride = 2 if (bi == 0 and si > 0) else 1
            h = _bottleneck(h, params[f"s{si}b{bi}"], stride)
    h = jnp.mean(h, axis=(2, 3))  # global average pool
    return h.astype(jnp.bfloat16) @ params["fc_w"].astype(jnp.bfloat16) \
        + params["fc_b"]


def loss_fn(params, x, y):
    logits = forward(params, x).astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    return jnp.mean(lse - jnp.take_along_axis(logits, y[:, None], 1)[:, 0])


@functools.partial(jax.jit, donate_argnums=(0, 1))
def train_step(params, momentum, x, y):
    loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
    lr, mom, wd = 0.1, 0.9, 1e-4

    def upd(p, m, g):
        g = g + wd * p
        m = mom * m - lr * g
        return p + m, m

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_m = jax.tree_util.tree_leaves(momentum)
    flat_g = jax.tree_util.tree_leaves(grads)
    out = [upd(p, m, g) for p, m, g in zip(flat_p, flat_m, flat_g)]
    params = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    momentum = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    return params, momentum, loss


def main():
    B = int(os.environ.get("MXNET_TPU_BENCH_BATCH", "256"))
    backend = jax.default_backend()
    warmup, steps = (2, 60) if backend != "cpu" else (1, 2)
    steps = int(os.environ.get("MXNET_TPU_BENCH_STEPS", steps))
    if backend == "cpu":
        B = min(B, 8)

    params = init_params(jax.random.PRNGKey(0))
    momentum = jax.tree_util.tree_map(jnp.zeros_like, params)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(B, 3, 224, 224).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 1000, (B,)).astype(np.int32))

    for _ in range(warmup):
        params, momentum, loss = train_step(params, momentum, x, y)
    # fence: concrete D2H of loss + one param (block_until_ready lies
    # through the axon tunnel — same discipline as bench.py::_fence)
    float(np.asarray(loss))
    np.asarray(jax.tree_util.tree_leaves(params)[0])

    t0 = time.perf_counter()
    for _ in range(steps):
        params, momentum, loss = train_step(params, momentum, x, y)
    float(np.asarray(loss))
    np.asarray(jax.tree_util.tree_leaves(params)[0])
    dt = time.perf_counter() - t0

    print(json.dumps({
        "metric": "resnet50_control_img_per_sec",
        "value": round(B * steps / dt, 2),
        "unit": "img/sec/chip",
        "note": "raw-JAX control, no framework (VERDICT r4 item 4a)",
    }))


if __name__ == "__main__":
    main()
