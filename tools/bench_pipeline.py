#!/usr/bin/env python
"""Pipeline-parallel efficiency sweep (VERDICT r4 evidence).

Measures ``pipeline_apply`` wall time at pp=P over an n_microbatches sweep
on the virtual CPU mesh and reports measured efficiency against the GPipe
bubble model  eff(M) = M / (M + P - 1)  (the fraction of ticks a stage is
busy).  Absolute CPU times are not TPU times — the *shape* of the curve
(efficiency rising toward the model as M grows) is the evidence; on real
chips the same program rides ICI ppermutes.

Usage:
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/bench_pipeline.py [P] [width]
"""
import os
import functools
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    P = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    width = int(sys.argv[2]) if len(sys.argv) > 2 else 512

    import jax
    import jax.numpy as jnp
    import numpy as np

    from incubator_mxnet_tpu.parallel import (
        make_mesh, pipeline_apply, stack_stage_params)

    have_mesh = len(jax.devices()) >= P
    mesh = make_mesh(pp=P, devices=jax.devices()[:P]) if have_mesh else None
    rng = np.random.RandomState(0)
    stages = [{"w": jnp.asarray(rng.randn(width, width).astype(np.float32) * 0.05)}
              for _ in range(P)]
    params = stack_stage_params(stages, mesh) if have_mesh else None

    def stage_fn(p, h):
        # a few matmuls so per-tick compute dominates permute latency
        for _ in range(4):
            h = jnp.tanh(h @ p["w"])
        return h

    B = 32 * P
    x = jnp.asarray(rng.randn(B, width).astype(np.float32))

    # sequential reference for correctness + the no-pipeline unit of work
    ref = x
    for s in stages:
        ref = stage_fn(s, ref)

    # Independent zero-bubble baseline: time the SEQUENTIAL composition on
    # one device; with P stages perfectly parallel and no bubble the
    # pipeline's floor is t_seq / P.  eff_meas = (t_seq / P) / t(M).
    seq_fn = jax.jit(lambda xx: functools.reduce(
        lambda h, s: stage_fn(s, h), stages, xx))
    jax.block_until_ready(seq_fn(x))
    t0 = time.perf_counter()
    for _ in range(5):
        out = seq_fn(x)
    jax.block_until_ready(out)
    t_seq = (time.perf_counter() - t0) / 5 * 1000

    times = {}
    sweep = (1, 2, 4, 8, 16, 32)
    t_ideal = t_seq / P
    if have_mesh:
        for M in sweep:
            fn = jax.jit(functools.partial(
                _apply, stage_fn=stage_fn, mesh=mesh, M=M))
            out = fn(params, x)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-4, atol=2e-5)
            n_rep = 5
            t0 = time.perf_counter()
            for _ in range(n_rep):
                out = fn(params, x)
            jax.block_until_ready(out)
            times[M] = (time.perf_counter() - t0) / n_rep * 1000

        print(f"pp={P}, width={width}, B={B}  t_seq={t_seq:.2f} ms  "
              f"zero-bubble floor={t_ideal:.2f} ms  (GPipe model eff = M/(M+{P - 1}))")
        print(f"{'M':>4} {'wall ms':>9} {'eff (meas)':>11} {'eff (model)':>12}")
        for M in sweep:
            print(f"{M:>4} {times[M]:>9.2f} {t_ideal / times[M]:>11.3f} "
                  f"{M / (M + P - 1):>12.3f}")
    else:
        print(f"pp={P}, width={width}, B={B}  t_seq={t_seq:.2f} ms — "
              f"only {len(jax.devices())} device(s); mesh sweep skipped, "
              f"running the single-device time-sliced bound")

    # single-device time-sliced bound (runs on ONE chip): schedule cost
    # with zero communication.  ideal = t_seq * (M+P-1)/M (masked wavefront
    # slots still compute, exactly like the mesh version's lanes).
    stacked_w = jnp.stack([s["w"] for s in stages])
    stage_fn_w = lambda w, h: stage_fn({"w": w}, h)
    print(f"\ntime-sliced single-device bound "
          f"(overhead = wall - t_seq*(M+{P - 1})/M):")
    print(f"{'M':>4} {'wall ms':>9} {'ideal ms':>10} {'overhead/tick ms':>17}")
    for M in sweep:
        fn = jax.jit(functools.partial(
            _time_sliced, stage_fn_w=stage_fn_w, P=P, M=M))
        out = fn(stacked_w, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)
        t0 = time.perf_counter()
        for _ in range(5):
            out = fn(stacked_w, x)
        jax.block_until_ready(out)
        wall = (time.perf_counter() - t0) / 5 * 1000
        ideal = t_seq * (M + P - 1) / M
        print(f"{M:>4} {wall:>9.2f} {ideal:>10.2f} "
              f"{(wall - ideal) / (M + P - 1):>17.3f}")


def _apply(params, x, *, stage_fn, mesh, M):
    from incubator_mxnet_tpu.parallel import pipeline_apply

    return pipeline_apply(stage_fn, params, x, mesh, n_microbatches=M)


def _time_sliced(stacked_w, x, *, stage_fn_w, P, M):
    """The GPipe wavefront executed on ONE device (VERDICT r4 weak #6's
    single-chip sanity bound): every tick runs all P stage slots — the
    work P devices would do in parallel — as one vmapped batch, then
    shifts the wavefront.  No shard_map, no ppermute, no multi-device
    emulation: wall time minus the ideal t_seq·(M+P-1)/M is pure SCHEDULE
    cost (scan + masking + the vmap batching), the floor the mesh version
    adds its communication to."""
    import jax
    import jax.numpy as jnp

    mb = x.shape[0] // M
    mbs = x.reshape(M, mb, *x.shape[1:])
    bufs0 = jnp.zeros((P, mb) + x.shape[1:], x.dtype)
    outs0 = jnp.zeros((M, mb) + x.shape[1:], x.dtype)

    compute = jax.vmap(stage_fn_w)  # [P, ...] params x [P, mb, ...] inputs

    def tick(carry, t):
        bufs, outs = carry
        feed = jnp.where(t < M, mbs[jnp.minimum(t, M - 1)], bufs[0])
        bufs = bufs.at[0].set(feed)
        done = compute(stacked_w, bufs)
        out_idx = t - (P - 1)
        outs = jax.lax.cond(
            out_idx >= 0,
            lambda o: o.at[jnp.maximum(out_idx, 0)].set(done[P - 1]),
            lambda o: o, outs)
        bufs = jnp.roll(done, 1, axis=0)
        return (bufs, outs), None

    (bufs, outs), _ = jax.lax.scan(tick, (bufs0, outs0),
                                   jnp.arange(M + P - 1))
    return outs.reshape(x.shape)


if __name__ == "__main__":
    main()
