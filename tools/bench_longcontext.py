#!/usr/bin/env python
"""Long-context attention benchmark — the exceeds-reference capability
(SURVEY §5): blockwise Pallas flash fwd+bwd keeps memory linear in S
where the XLA path's S×S buffers blow up.

Times fwd+bwd (jax.grad) of causal attention at growing S, Pallas vs XLA,
on the default backend.  Run on the chip:

    python tools/bench_longcontext.py

CAVEAT (this sandbox): through the tunneled axon backend these
micro-timings vary up to 5x run-to-run (per-call RPC variance), and
S>=16384 programs exceed the remote AOT compile helper — use a
direct-attached chip for publishable numbers.  The standing measurement
is docs/PERF_NOTES.md's round-2 crossover table (S=8192: Pallas bwd
25.9 ms vs XLA 31.1 ms).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from incubator_mxnet_tpu.ops import attention as att

    B, H, D = 1, 8, 64
    print(f"{'S':>7}{'mode':>9}{'fwd+bwd(ms)':>14}{'tokens/s':>12}")
    for S in (4096, 8192, 16384, 32768):
        q = jnp.asarray(np.random.RandomState(0).randn(B, H, S, D)).astype(jnp.bfloat16)
        for mode in ("pallas", "xla"):
            os.environ["MXNET_TPU_FLASH"] = "on" if mode == "pallas" else "off"
            # thresholds are read at import; force the gate decisions
            att._PALLAS_FWD_MIN_SEQ = 0 if mode == "pallas" else 10 ** 9
            att._PALLAS_BWD_MIN_SEQ = 0 if mode == "pallas" else 10 ** 9

            def loss(x):
                return (att.flash_attention(x, x, x, causal=True) ** 2
                        ).sum().astype(jnp.float32)

            try:
                g = jax.jit(jax.grad(loss))
                jax.block_until_ready(g(q))  # compile + smoke
                t0 = time.perf_counter()
                for _ in range(5):
                    out = g(q)
                np.asarray(out[0, 0, 0])  # concrete D2H fence
                dt = (time.perf_counter() - t0) / 5
                print(f"{S:>7}{mode:>9}{dt*1e3:>14.1f}{B*S/dt:>12.0f}")
            except Exception as e:
                print(f"{S:>7}{mode:>9}{'FAILED: ' + type(e).__name__:>14}")


if __name__ == "__main__":
    main()
