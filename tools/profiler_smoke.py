#!/usr/bin/env python
"""CI smoke for the tracing subsystem (tools/ci.sh ``profiler`` tier).

Runs a tiny 3-step train loop with the span recorder armed — forward under
``autograd.record`` (dispatch-cache spans), an eager metric chain inside an
``engine.bulk`` scope (bulk-flush spans), fused optimizer step + kvstore
pushpull (optimizer/comms spans) — then asserts the dumped chrome-trace
JSON is structurally valid: paired B/E events, the four hot-path span
categories present, and monotone step ids.  Exit 0 = healthy.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from trace_report import load_spans  # noqa: E402 — THE B/E pairing validator


def run(out_path):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, engine, profiler
    from incubator_mxnet_tpu.gluon import Trainer, nn

    net = nn.Dense(8)
    net.initialize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore="device")
    x = mx.nd.ones((4, 16))

    profiler.set_config(filename=out_path)
    profiler.start()
    for _ in range(3):
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        with engine.bulk(8):  # eager metric-style chain: bulk spans
            m = loss + 0.0
            for _ in range(4):
                m = m * 1.0
        m.asnumpy()
        trainer.step(4)
    path = profiler.dump()

    # load_spans raises ValueError on any unpaired B/E — the schema check
    spans, other = load_spans(path)
    assert spans, "empty trace"

    cats = {cat for _, cat, _, _, _, _, _ in spans}
    need = {"dispatch", "bulk", "optimizer", "comms", "step"}
    assert need <= cats, f"missing span categories: {need - cats}"

    steps = [step for _, _, _, _, step, _, _ in
             sorted(spans, key=lambda s: s[2]) if step is not None]
    assert steps == sorted(steps), "step ids not monotone"

    assert other["counters"]["fused_step_call"] >= 3
    print(f"profiler smoke OK: {len(spans)} spans, categories "
          f"{sorted(cats)}, steps 1..{max(steps)} -> {path}")
    return path


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default="/tmp/profiler_smoke_trace.json")
    args = p.parse_args(argv)
    run(args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
