#!/usr/bin/env python
"""ImageNet-style training through the NATIVE data pipeline — the
[U:example/image-classification/train_imagenet.py] analog.

Data path: RecordIO pack (im2rec) → C++ decode/augment pool
(native/mxtpu_io.cpp via ImageRecordIter) → Gluon train loop.  With no
pack given, --make-synthetic builds a small JPEG pack first so the script
runs anywhere:

    python example/train_imagenet.py --make-synthetic --epochs 1
    python example/train_imagenet.py --rec data/train.rec --network resnet50
"""
import argparse
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_synthetic_pack(n_images=96, classes=4):
    from PIL import Image

    root = tempfile.mkdtemp(prefix="mxtpu_imagenet_")
    rng = np.random.RandomState(0)
    for c in range(classes):
        d = os.path.join(root, "imgs", f"class{c}")
        os.makedirs(d)
        for i in range(n_images // classes):
            # class-dependent mean color so the task is learnable
            base = np.zeros((120, 160, 3), np.uint8) + np.uint8(40 + 50 * c)
            noise = rng.randint(0, 60, base.shape, dtype=np.uint8)
            Image.fromarray(base + noise).save(os.path.join(d, f"i{i}.jpg"), quality=88)
    prefix = os.path.join(root, "pack")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    subprocess.run([sys.executable, os.path.join(repo, "tools", "im2rec.py"),
                    prefix, os.path.join(root, "imgs")], check=True,
                   capture_output=True)
    return prefix + ".rec", prefix + ".idx", classes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rec", default=None)
    ap.add_argument("--idx", default=None)
    ap.add_argument("--make-synthetic", action="store_true")
    ap.add_argument("--network", default="resnet18",
                    choices=("resnet18", "resnet50"))
    ap.add_argument("--classes", type=int, default=1000)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--image-shape", default="3,112,112")
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet18_v1, resnet50_v1
    from incubator_mxnet_tpu.io.record_iter import ImageRecordIter

    if args.make_synthetic or args.rec is None:
        rec, idx, classes = make_synthetic_pack()
        args.classes = classes
    else:
        rec, idx = args.rec, args.idx or args.rec.replace(".rec", ".idx")

    shape = tuple(int(x) for x in args.image_shape.split(","))
    it = ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                         batch_size=args.batch_size, data_shape=shape,
                         shuffle=True, rand_crop=True, rand_mirror=True,
                         preprocess_threads=max(1, (os.cpu_count() or 1)))

    factory = resnet18_v1 if args.network == "resnet18" else resnet50_v1
    net = factory(classes=args.classes)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9, "wd": 1e-4})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    speed = mx.callback.Speedometer(args.batch_size, frequent=5)

    for epoch in range(args.epochs):
        it.reset()
        metric.reset()
        t0 = time.time()
        n = 0
        for i, batch in enumerate(it):
            data, label = batch.data[0], batch.label[0]
            data = data / 255.0
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update([label], [out])
            n += data.shape[0]
        dt = time.time() - t0
        print(f"epoch {epoch}: train-acc {metric.get()[1]:.3f} "
              f"({n/dt:.0f} img/s through the native pipeline)")


if __name__ == "__main__":
    main()
