#!/usr/bin/env python
"""Model-parallel LSTM — the [U:example/model-parallel/] analog.

The reference places each LSTM layer on a different GPU by hand
(``group2ctx`` in ``Symbol.bind``).  The TPU-native equivalent is
strictly more capable: declare a ``ShardingRules`` table mapping
parameter names to ``PartitionSpec``s over a named mesh axis and jit the
whole step — XLA splits every matmul across the ``tp`` axis and inserts
the collectives the hand-placed version needed explicit device-to-device
copies for.

This example runs on the 8-device virtual CPU mesh (dp=4 × tp=2),
trains a 2-layer LSTM regression model twice — tensor-parallel and
fully replicated — and checks the two learn identical parameters, then
prints the per-device shard shapes to show the weights really are
split.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python example/model_parallel_lstm.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# self-provision the 8-device virtual CPU mesh (same discipline as
# tests/conftest.py: a tunneled-TPU plugin may already be registered from
# sitecustomize, so env vars alone are too late — set jax config and drop
# the foreign backend factory in-process)
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

import numpy as np


def build(hidden, layers, seed):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon

    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.rnn.LSTM(hidden, num_layers=layers, layout="NTC"),
            gluon.nn.Dense(1, flatten=False))
    net.initialize()
    net(mx.nd.zeros((2, 8, 16)))  # materialize deferred shapes
    return net


def train(net, rules, steps=12, seed=0):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.parallel import SPMDTrainer, make_mesh

    mesh = make_mesh(tp=2)  # dp fills the rest: 4×2 on 8 devices
    loss = gluon.loss.L2Loss()
    trainer = SPMDTrainer(net, loss, "adam", {"learning_rate": 3e-3},
                          mesh=mesh, rules=rules)
    rng = np.random.RandomState(seed)
    last = None
    for _ in range(steps):
        x = rng.rand(32, 8, 16).astype(np.float32)
        y = x.sum(axis=2, keepdims=True).astype(np.float32)
        last = trainer.step(x, y)
    return trainer, float(last)


def main():
    from jax.sharding import PartitionSpec as P

    from incubator_mxnet_tpu.parallel import ShardingRules
    from incubator_mxnet_tpu.parallel.sharding import default_rules

    # Megatron-style row split of the stacked-gate matrices over 'tp'.
    # (The 4h gate rows interleave across devices; XLA keeps the math
    # correct by inserting the collectives — that's the point.)
    tp_rules = ShardingRules([
        (r"(i2h|h2h)_weight", P("tp", None)),
        (r"(i2h|h2h)_bias", P("tp")),
        (r"dense.*weight", P(None, "tp")),
    ])

    net_tp = build(64, 2, seed=7)
    net_rep = build(64, 2, seed=7)  # identical init

    tr_tp, loss_tp = train(net_tp, tp_rules)
    tr_rep, loss_rep = train(net_rep, default_rules())

    # same training trajectory regardless of placement
    for (p_tp, a_tp), (p_rep, a_rep) in zip(
            zip(tr_tp._params, tr_tp._param_arrays),
            zip(tr_rep._params, tr_rep._param_arrays)):
        np.testing.assert_allclose(np.asarray(a_tp), np.asarray(a_rep),
                                   rtol=2e-4, atol=2e-4, err_msg=p_tp.name)

    # show the split: an LSTM weight's per-device shard is half the rows
    w = next(a for p, a in zip(tr_tp._params, tr_tp._param_arrays)
             if "h2h_weight" in p.name)
    shard_shapes = {str(s.data.shape) for s in w.addressable_shards}
    print(f"h2h_weight global {w.shape}, per-device shards {sorted(shard_shapes)}")
    print(f"tp loss {loss_tp:.5f} == replicated loss {loss_rep:.5f}")
    print("model-parallel == replicated: OK")


if __name__ == "__main__":
    main()
