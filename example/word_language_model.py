#!/usr/bin/env python
"""Word-level language model — the [U:example/gluon/word_language_model/]
analog: contrib.text vocabulary + Embedding + LSTM + tied softmax,
truncated-BPTT training with hidden-state carry and gradient clipping.

    python example/word_language_model.py --epochs 3
"""
import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def synthetic_corpus(n_sent=400, seed=0):
    """A tiny Markov-ish corpus a small LSTM can actually compress."""
    rng = np.random.RandomState(seed)
    nouns = ["cat", "dog", "bird", "fish"]
    verbs = ["sees", "chases", "likes"]
    sents = []
    for _ in range(n_sent):
        s = ["the", rng.choice(nouns), rng.choice(verbs),
             "the", rng.choice(nouns)]
        sents.append(" ".join(s))
    return "\n".join(sents)


def batchify(ids, batch_size):
    n = len(ids) // batch_size
    return np.asarray(ids[: n * batch_size], np.int32).reshape(batch_size, n)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--bptt", type=int, default=8)
    ap.add_argument("--embed", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--clip", type=float, default=0.25)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon
    from incubator_mxnet_tpu.contrib import text

    corpus = synthetic_corpus()
    vocab = text.Vocabulary(text.count_tokens_from_str(corpus))
    ids = vocab.to_indices(corpus.replace("\n", " <eos> ").split())
    data = batchify(ids, args.batch_size)

    class RNNModel(gluon.Block):
        def __init__(self, vocab_size, embed, hidden, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embedding = gluon.nn.Embedding(vocab_size, embed)
                self.lstm = gluon.rnn.LSTM(hidden, layout="NTC")
                self.decoder = gluon.nn.Dense(vocab_size, flatten=False)

        def forward(self, x, state=None):
            h = self.embedding(x)
            out, state = self.lstm(h, state)
            return self.decoder(out), state

        def begin_state(self, batch_size):
            return self.lstm.begin_state(batch_size)

    mx.random.seed(0)
    net = RNNModel(len(vocab), args.embed, args.hidden)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    n_steps = (data.shape[1] - 1) // args.bptt
    for epoch in range(args.epochs):
        state = net.begin_state(args.batch_size)
        total, count = 0.0, 0
        t0 = time.time()
        for step in range(n_steps):
            lo = step * args.bptt
            x = mx.nd.array(data[:, lo:lo + args.bptt], dtype="int32")
            y = mx.nd.array(data[:, lo + 1:lo + args.bptt + 1], dtype="int32")
            state = [s.detach() for s in state]  # truncated BPTT
            with autograd.record():
                out, state = net(x, state)
                loss = loss_fn(out.reshape((-1, len(vocab))),
                               y.reshape((-1,)))
            loss.backward()
            gluon.utils.clip_global_norm(
                [p.grad() for p in net.collect_params().values()
                 if p.grad_req != "null"],
                args.clip * args.batch_size * args.bptt)
            trainer.step(args.batch_size * args.bptt)
            total += float(loss.mean().asscalar())
            count += 1
        ppl = math.exp(total / count)
        print(f"epoch {epoch}: perplexity {ppl:.1f} "
              f"({count / (time.time() - t0):.1f} steps/s)")


if __name__ == "__main__":
    main()
