#!/usr/bin/env python
"""INT8 post-training quantization — the [U:example/quantization/] analog:
train a small fp32 CNN, calibrate activation ranges on held-out batches,
quantize in place with ``mx.contrib.quantization.quantize_net``, and
report fp32-vs-int8 accuracy and agreement.

TPU-native notes: the int8 path runs weights and activations through the
MXU's native int8 matmul/conv (``ops/quantization.py``); calibration is
minmax over hooked layer inputs (``--calib-mode entropy`` switches to
the KL threshold sweep), matching the reference's ``calib_mode=
'naive'``.

    python example/quantize_int8.py --epochs 2
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

logging.basicConfig(level=logging.INFO)


def synthetic(n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 1, 16, 16).astype(np.float32)
    w = np.random.RandomState(1).randn(256, 10)
    y = (x.reshape(n, -1) @ w).argmax(1).astype(np.float32)
    return x, y


def accuracy(net, X, y, batch=128):
    import incubator_mxnet_tpu as mx
    correct = 0
    for i in range(0, len(X), batch):
        out = net(mx.nd.array(X[i:i + batch])).asnumpy()
        correct += (out.argmax(1) == y[i:i + batch]).sum()
    return correct / len(X)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--calib-batches", type=int, default=4)
    ap.add_argument("--calib-mode", choices=("naive", "entropy"),
                    default="naive",
                    help="minmax ranges or the KL-optimal threshold sweep")
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.contrib.quantization import quantize_net
    from incubator_mxnet_tpu.gluon import nn

    Xtr, ytr = synthetic(2048)
    Xte, yte = synthetic(512, seed=7)

    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, activation="relu"), nn.MaxPool2D(2, 2),
            nn.Flatten(), nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        lsum, nb = 0.0, 0
        for i in range(0, len(Xtr), args.batch_size):
            xb = mx.nd.array(Xtr[i:i + args.batch_size])
            yb = mx.nd.array(ytr[i:i + args.batch_size])
            with mx.autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])
            lsum += loss.mean().asscalar()
            nb += 1
        logging.info("epoch %d: loss=%.4f", epoch, lsum / nb)

    fp32_acc = accuracy(net, Xte, yte)
    fp32_out = net(mx.nd.array(Xte[:256])).asnumpy()

    # -- calibrate + quantize in place -----------------------------------
    n_calib = min(args.calib_batches, len(Xtr) // args.batch_size)
    calib = [mx.nd.array(Xtr[i * args.batch_size:(i + 1) * args.batch_size])
             for i in range(n_calib)]
    quantize_net(net, calib, quantized_dtype="int8", calib_mode=args.calib_mode)

    int8_acc = accuracy(net, Xte, yte)
    int8_out = net(mx.nd.array(Xte[:256])).asnumpy()
    agree = (fp32_out.argmax(1) == int8_out.argmax(1)).mean()

    logging.info("fp32 acc=%.3f  int8 acc=%.3f  top1 agreement=%.3f",
                 fp32_acc, int8_acc, agree)
    print(f"fp32-acc {fp32_acc:.3f} int8-acc {int8_acc:.3f} agreement {agree:.3f}")
    return fp32_acc, int8_acc, agree


if __name__ == "__main__":
    main()
