#!/usr/bin/env python
"""DCGAN — the [U:example/gluon/dcgan] analog: adversarial training with
two networks and two Trainers (generator: Conv2DTranspose stack from a
latent vector; discriminator: strided-conv classifier), BCE-from-logits
loss, alternating D/G updates.

Runs on synthetic 32×32 "images" (a fixed smooth pattern family) so it
needs no dataset download; prints D/G losses and a simple mode-health
stat (std of generated pixels).  Both nets hybridize, so each D and G
update is one compiled program.

    python example/dcgan.py --epochs 2
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

logging.basicConfig(level=logging.INFO)


def build_generator(latent=64, ngf=32):
    from incubator_mxnet_tpu.gluon import nn

    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # latent [B, latent, 1, 1] → [B, 1, 32, 32]
        net.add(nn.Conv2DTranspose(ngf * 4, 4, strides=1, padding=0, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),      # 4×4
                nn.Conv2DTranspose(ngf * 2, 4, strides=2, padding=1, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),      # 8×8
                nn.Conv2DTranspose(ngf, 4, strides=2, padding=1, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),      # 16×16
                nn.Conv2DTranspose(1, 4, strides=2, padding=1, use_bias=False),
                nn.Activation("tanh"))                      # 32×32
    return net


def build_discriminator(ndf=32):
    from incubator_mxnet_tpu.gluon import nn

    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(ndf, 4, strides=2, padding=1, use_bias=False),
                nn.LeakyReLU(0.2),                          # 16×16
                nn.Conv2D(ndf * 2, 4, strides=2, padding=1, use_bias=False),
                nn.BatchNorm(), nn.LeakyReLU(0.2),          # 8×8
                nn.Conv2D(ndf * 4, 4, strides=2, padding=1, use_bias=False),
                nn.BatchNorm(), nn.LeakyReLU(0.2),          # 4×4
                nn.Conv2D(1, 4, strides=1, padding=0, use_bias=False),
                nn.Flatten())                               # logits [B, 1]
    return net


def real_batch(rng, n):
    """Smooth 2-D cosine patterns with random phase/frequency — an easy,
    download-free 'real' distribution in [-1, 1]."""
    yy, xx = np.meshgrid(np.arange(32), np.arange(32), indexing="ij")
    out = np.empty((n, 1, 32, 32), np.float32)
    for i in range(n):
        fx, fy = rng.uniform(0.1, 0.4, 2)
        px, py = rng.uniform(0, 2 * np.pi, 2)
        out[i, 0] = np.cos(fx * xx + px) * np.cos(fy * yy + py)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--latent", type=int, default=64)
    ap.add_argument("--steps-per-epoch", type=int, default=30)
    ap.add_argument("--lr", type=float, default=2e-4)
    args = ap.parse_args()
    if args.epochs < 1 or args.steps_per_epoch < 1:
        raise SystemExit("--epochs and --steps-per-epoch must be >= 1")

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon

    mx.random.seed(0)
    gen = build_generator(args.latent)
    disc = build_discriminator()
    gen.initialize(mx.init.Normal(0.02))
    disc.initialize(mx.init.Normal(0.02))
    gen.hybridize()
    disc.hybridize()

    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})

    rng = np.random.RandomState(0)
    B = args.batch_size
    ones = mx.nd.ones((B,))
    zeros = mx.nd.zeros((B,))
    for epoch in range(args.epochs):
        dl = gl = 0.0
        for _ in range(args.steps_per_epoch):
            real = mx.nd.array(real_batch(rng, B))
            noise = mx.nd.array(rng.randn(B, args.latent, 1, 1)
                                .astype(np.float32))
            # -- D step: real→1, fake→0.  Fake is generated INSIDE
            # record() so G's BatchNorm runs in training mode (batch
            # stats) — the same distribution the G step optimizes —
            # then detached so no G grads flow.
            with mx.autograd.record():
                fake = gen(noise).detach()
                d_loss = (loss_fn(disc(real).reshape((-1,)), ones)
                          + loss_fn(disc(fake).reshape((-1,)), zeros))
            d_loss.backward()
            d_tr.step(B)
            # -- G step: fool D --------------------------------------------
            with mx.autograd.record():
                g_loss = loss_fn(disc(gen(noise)).reshape((-1,)), ones)
            g_loss.backward()
            g_tr.step(B)
            dl += d_loss.mean().asscalar()
            gl += g_loss.mean().asscalar()
        sample = gen(mx.nd.array(rng.randn(16, args.latent, 1, 1)
                                 .astype(np.float32)))
        spread = float(sample.asnumpy().std())
        logging.info("epoch %d: D=%.3f G=%.3f sample-std=%.3f", epoch,
                     dl / args.steps_per_epoch, gl / args.steps_per_epoch,
                     spread)
    print(f"final D={dl / args.steps_per_epoch:.3f} "
          f"G={gl / args.steps_per_epoch:.3f} sample-std={spread:.3f}")
    return dl / args.steps_per_epoch, gl / args.steps_per_epoch, spread


if __name__ == "__main__":
    main()
