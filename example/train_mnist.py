#!/usr/bin/env python
"""MNIST training — the [U:example/image-classification/train_mnist.py]
analog, runnable on CPU or TPU (swap --ctx).  Demonstrates both front
ends: the Gluon imperative loop (default) and the legacy Module API
(--module), with synthetic data when no MNIST files are present
(--benchmark, the reference's synthetic-data discipline).

    python example/train_mnist.py --benchmark --epochs 2
    python example/train_mnist.py --network lenet --module --benchmark
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

logging.basicConfig(level=logging.INFO)  # Module.fit reports through logging


def build_net(name):
    from incubator_mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    if name == "mlp":
        net.add(nn.Dense(128, activation="relu"),
                nn.Dense(64, activation="relu"), nn.Dense(10))
    else:
        net.add(nn.Conv2D(20, 5, activation="tanh"), nn.MaxPool2D(2, 2),
                nn.Conv2D(50, 5, activation="tanh"), nn.MaxPool2D(2, 2),
                nn.Flatten(), nn.Dense(500, activation="tanh"), nn.Dense(10))
    return net


def synthetic(n, flat):
    rng = np.random.RandomState(0)
    x = rng.rand(n, 784).astype(np.float32) if flat else \
        rng.rand(n, 1, 28, 28).astype(np.float32)
    # learnable structure: label = argmax of 10 fixed random projections
    w = np.random.RandomState(1).randn(x.reshape(n, -1).shape[1], 10)
    y = (x.reshape(n, -1) @ w).argmax(1).astype(np.float32)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", choices=("mlp", "lenet"), default="mlp")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--ctx", default="cpu", choices=("cpu", "tpu"))
    ap.add_argument("--module", action="store_true", help="legacy Module API")
    ap.add_argument("--benchmark", action="store_true", help="synthetic data")
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon

    ctx = mx.tpu() if args.ctx == "tpu" else mx.cpu()
    flat = args.network == "mlp"
    x, y = synthetic(4096, flat)
    n_train = 3584
    train = gluon.data.DataLoader(
        gluon.data.ArrayDataset(mx.nd.array(x[:n_train]), mx.nd.array(y[:n_train])),
        batch_size=args.batch_size, shuffle=True)
    val_x, val_y = mx.nd.array(x[n_train:], ctx=ctx), y[n_train:]

    if args.module:
        import incubator_mxnet_tpu.symbol as S

        data = S.var("data")
        sym = data
        if flat:
            for i, (h, act) in enumerate([(128, "relu"), (64, "relu")]):
                sym = S.Activation(S.FullyConnected(sym, num_hidden=h, name=f"fc{i}"),
                                   act_type=act, name=f"a{i}")
            sym = S.FullyConnected(sym, num_hidden=10, name="out")
        else:
            raise SystemExit("--module demo covers mlp")
        sym = S.SoftmaxOutput(sym, S.var("softmax_label"), name="softmax")
        mod = mx.mod.Module(sym, data_names=("data",), label_names=("softmax_label",))
        it = mx.io.NDArrayIter({"data": x[:n_train]}, {"softmax_label": y[:n_train]},
                               batch_size=args.batch_size, shuffle=True)
        mod.fit(it, num_epoch=args.epochs,
                optimizer="sgd", optimizer_params={"learning_rate": args.lr},
                eval_metric="acc")
        return

    net = build_net(args.network)
    net.initialize(ctx=ctx)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        metric.reset()
        for data, label in train:
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update([label], [out])
        acc = net(val_x).asnumpy().argmax(1)
        print(f"epoch {epoch}: train-acc {metric.get()[1]:.3f} "
              f"val-acc {(acc == val_y).mean():.3f}")


if __name__ == "__main__":
    main()
