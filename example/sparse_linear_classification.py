#!/usr/bin/env python
"""Sparse linear classification — the [U:example/sparse/linear_classification/]
analog: logistic regression over a high-dimensional sparse feature space
with a row-sparse embedding weight and LAZY optimizer updates (only the
rows a batch touches get momentum/weight-decay applied).

TPU-native notes: feature vectors are dense one-hot gathers (static
shapes), the weight's ``sparse_grad`` marking routes SGD through the
``*_lazy_update`` kernels, and the whole step jit-compiles after the
first batch.

    python example/sparse_linear_classification.py --epochs 3
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

logging.basicConfig(level=logging.INFO)


def synthetic_sparse(num_samples, num_features, nnz, seed=0):
    """Each sample activates ``nnz`` random feature ids; the label is the
    sign of the sum of a hidden per-feature weight over active ids (the
    criteo-style abstraction the reference example trains on)."""
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, num_features, size=(num_samples, nnz)).astype(np.float32)
    hidden = rng.randn(num_features).astype(np.float32)
    score = hidden[ids.astype(np.int64)].sum(axis=1)
    label = (score > 0).astype(np.float32)
    return ids, label


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-features", type=int, default=10000)
    ap.add_argument("--nnz", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import nn

    X, y = synthetic_sparse(16384, args.num_features, args.nnz)
    data = gluon.data.DataLoader(
        gluon.data.ArrayDataset(X, y), batch_size=args.batch_size, shuffle=True)

    # row-sparse weight: each step only the <=batch*nnz touched rows update.
    # The model IS the weight table — multi-hot logistic regression:
    # logit(x) = sum_{i in active(x)} w_i  (order-invariant, like the
    # reference's sparse dot(data, weight)).
    embed = nn.Embedding(args.num_features, 1, sparse_grad=True)
    embed.initialize()

    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    trainer = gluon.Trainer(embed.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})

    for epoch in range(args.epochs):
        total, correct, lsum, nb = 0, 0, 0.0, 0
        for xb, yb in data:
            with mx.autograd.record():
                per_id = embed(xb).reshape((xb.shape[0], -1))  # (B, nnz)
                logits = mx.nd.sum(per_id, axis=1)
                loss = loss_fn(logits, yb)
            loss.backward()
            trainer.step(xb.shape[0])
            lsum += loss.mean().asscalar()
            nb += 1
            pred = (logits.asnumpy() > 0).astype(np.float32)
            correct += (pred == yb.asnumpy()).sum()
            total += xb.shape[0]
        logging.info("epoch %d: loss=%.4f acc=%.3f", epoch, lsum / nb, correct / total)
    return correct / total


if __name__ == "__main__":
    acc = main()
    print(f"final-accuracy {acc:.3f}")
