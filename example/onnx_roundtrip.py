#!/usr/bin/env python
"""ONNX interchange — the [U:example/onnx/] analog: train a small Symbol
CNN with Module, export it to ONNX (no onnx package needed — the wire
codec is in-repo), re-import, verify prediction parity, and keep
finetuning the *imported* graph with Module.

This is the migration round-trip a reference-MXNet user relies on:
models leave for other runtimes via `export_model`, and foreign ONNX
models enter via `import_model` and train like any native Symbol.

    python example/onnx_roundtrip.py --epochs 2
"""
import argparse
import logging
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

logging.basicConfig(level=logging.INFO)
log = logging.getLogger("onnx_roundtrip")


def synthetic(n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 1, 16, 16).astype(np.float32)
    w = np.random.RandomState(1).randn(256, 10)
    y = (x.reshape(n, -1) @ w).argmax(1).astype(np.float32)
    return x, y


def lenet_sym():
    import incubator_mxnet_tpu.symbol as S

    S.symbol._reset_naming()
    data = S.var("data")
    x = S.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1), name="c1")
    x = S.Activation(x, act_type="relu", name="r1")
    x = S.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max", name="p1")
    x = S.Flatten(x, name="f1")
    x = S.FullyConnected(x, num_hidden=32, name="fc1")
    x = S.Activation(x, act_type="relu", name="r2")
    x = S.FullyConnected(x, num_hidden=10, name="fc2")
    return S.SoftmaxOutput(x, S.var("softmax_label"), name="softmax")


def fit(sym, X, y, epochs, batch_size, arg_params=None, aux_params=None):
    import incubator_mxnet_tpu as mx

    it = mx.io.NDArrayIter(X, y, batch_size, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=epochs, arg_params=arg_params,
            aux_params=aux_params, allow_missing=arg_params is not None,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            eval_metric="acc")
    return mod


def predict(mod, X, batch_size):
    import incubator_mxnet_tpu as mx

    it = mx.io.NDArrayIter(X, None, batch_size)
    return mod.predict(it).asnumpy()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--n", type=int, default=512)
    args = ap.parse_args()

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.contrib import onnx as onnx_mxnet

    X, y = synthetic(args.n)
    mod = fit(lenet_sym(), X, y, args.epochs, args.batch_size)
    ref = predict(mod, X[:64], args.batch_size)

    arg_params, aux_params = mod.get_params()
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "lenet.onnx")
        onnx_mxnet.export_model(mod.symbol, {**arg_params, **aux_params},
                                input_shape=(args.batch_size, 1, 16, 16),
                                onnx_file_path=path)
        log.info("exported %s (%d bytes)", path, os.path.getsize(path))
        meta = onnx_mxnet.get_model_metadata(path)
        log.info("metadata: %s", meta)
        sym2, arg2, aux2 = onnx_mxnet.import_model(path)

    # the imported graph predicts identically ...
    mod2 = mx.mod.Module(sym2, data_names=("data",), label_names=())
    it = mx.io.NDArrayIter(X[:64], None, args.batch_size)
    mod2.bind(data_shapes=it.provide_data, for_training=False)
    mod2.set_params(arg2, aux2, allow_missing=False)
    out = predict(mod2, X[:64], args.batch_size)
    err = float(np.abs(out - ref).max())
    log.info("roundtrip max |delta| = %.3g", err)
    assert err < 1e-4, "imported model diverged from the exported one"

    # ... and keeps training: the imported tip is already a Softmax node
    # (SoftmaxOutput exports as inference-form Softmax), so attach the new
    # loss head to the PRE-softmax internal output, as the reference ONNX
    # finetune flow does — stacking SoftmaxOutput on probabilities would
    # train a mis-specified double-softmax
    import incubator_mxnet_tpu.symbol as S
    internals = sym2.get_internals()
    logits = internals[internals.list_outputs()[-2]]
    ft_sym = S.SoftmaxOutput(logits, S.var("softmax_label"), name="softmax")
    fit(ft_sym, X, y, 1, args.batch_size, arg_params=arg2, aux_params=aux2)
    log.info("finetune on the imported graph: OK")
    print("ONNX_ROUNDTRIP_OK", err)


if __name__ == "__main__":
    main()
