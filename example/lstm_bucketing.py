#!/usr/bin/env python
"""LSTM bucketing language model — the [U:example/rnn/bucketing/
lstm_bucketing.py] analog: the fused ``sym.RNN`` mega-op (packed cuDNN-
layout parameter vector) under ``BucketingModule``, variable-length
sequences routed to per-bucket executors that SHARE one parameter set.

Synthetic Markov corpus (same generator family as word_language_model.py)
bucketed at lengths {8, 12, 16}; perplexity must fall.

    python example/lstm_bucketing.py --epochs 5
"""
import argparse
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import incubator_mxnet_tpu as mx
import incubator_mxnet_tpu.symbol as sym
from incubator_mxnet_tpu.io import DataBatch, DataDesc
from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size

VOCAB = 16
EMBED = 16
HIDDEN = 32
LAYERS = 2
BUCKETS = (8, 12, 16)


def synthetic_sequences(n=600, seed=0):
    """Token chains with strong bigram structure at mixed lengths."""
    rng = np.random.RandomState(seed)
    seqs = []
    for _ in range(n):
        L = int(rng.choice(BUCKETS))
        t = rng.randint(0, VOCAB)
        s = [t]
        for _ in range(L - 1):
            # each token strongly prefers (t*3+1) mod VOCAB
            t = (t * 3 + 1) % VOCAB if rng.rand() < 0.9 else rng.randint(0, VOCAB)
            s.append(t)
        seqs.append(s)
    return seqs


USE_CELL_API = False  # --cell-api: build with mx.rnn cells instead of sym.RNN


def sym_gen(seq_len):
    """Per-bucket symbol; every bucket reads the SAME parameter vars."""
    data = sym.Variable("data")            # [B, T] int tokens
    label = sym.Variable("softmax_label")  # [B, T] next tokens
    embed = sym.Embedding(data, sym.Variable("embed_weight"),
                          input_dim=VOCAB, output_dim=EMBED, name="embed")
    if USE_CELL_API:
        # the legacy mx.rnn path: unrolled LSTMCell stack, shared
        # parameters across buckets by name ([U:example/rnn/bucketing])
        stack = mx.rnn.SequentialRNNCell()
        for i in range(LAYERS):
            stack.add(mx.rnn.LSTMCell(num_hidden=HIDDEN,
                                      prefix=f"lstm_l{i}_"))
        outs, _ = stack.unroll(seq_len, inputs=embed, layout="NTC",
                               merge_outputs=True)        # [B, T, H]
        flat = sym.reshape(outs, shape=(-1, HIDDEN), name="flat")
        lab_t = sym.reshape(label, shape=(-1,), name="lab")
    else:
        tnc = sym.swapaxes(embed, dim1=0, dim2=1, name="to_tnc")  # [T, B, E]
        out = sym.RNN(tnc, sym.Variable("lstm_parameters"), mode="lstm",
                      state_size=HIDDEN, num_layers=LAYERS, name="lstm")
        flat = sym.reshape(out, shape=(-1, HIDDEN), name="flat")  # [T*B, H]
        lab_t = sym.reshape(sym.swapaxes(label, dim1=0, dim2=1), shape=(-1,),
                            name="lab")
    logits = sym.FullyConnected(flat, sym.Variable("pred_weight"),
                                sym.Variable("pred_bias"),
                                num_hidden=VOCAB, flatten=False, name="pred")
    net = sym.SoftmaxOutput(logits, label=lab_t, name="softmax")
    return net, ("data",), ("softmax_label",)


def make_batches(seqs, batch_size, rng):
    by_len = {b: [] for b in BUCKETS}
    for s in seqs:
        by_len[len(s)].append(s)
    batches = []
    for b, rows in by_len.items():
        rng.shuffle(rows)
        for i in range(0, len(rows) - batch_size + 1, batch_size):
            chunk = np.asarray(rows[i:i + batch_size], np.int32)
            data = chunk[:, :-1]
            label = chunk[:, 1:]
            T = b - 1
            batches.append(DataBatch(
                [mx.nd.array(data, dtype="int32")],
                [mx.nd.array(label.astype(np.float32))],
                bucket_key=T,
                provide_data=[DataDesc("data", (batch_size, T))],
                provide_label=[DataDesc("softmax_label", (batch_size, T))]))
    rng.shuffle(batches)
    return batches


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--cell-api", action="store_true",
                    help="build with mx.rnn cells instead of the fused sym.RNN")
    args = ap.parse_args()
    global USE_CELL_API
    USE_CELL_API = args.cell_api

    rng = np.random.RandomState(1)
    seqs = synthetic_sequences()
    default_key = max(BUCKETS) - 1

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=default_key)
    mod.bind([DataDesc("data", (args.batch_size, default_key))],
             [DataDesc("softmax_label", (args.batch_size, default_key))])
    # the packed RNN vector is 1-D — route it to Uniform (the reference's
    # bucketing example does the same via init patterns), Xavier elsewhere
    mod.init_params(initializer=mx.initializer.Mixed(
        [".*lstm_parameters", ".*"],
        [mx.initializer.Uniform(0.08), mx.initializer.Xavier()]))
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    n_params = (None if USE_CELL_API
                else rnn_param_size("lstm", EMBED, HIDDEN, LAYERS))
    first_ppl = None
    for epoch in range(args.epochs):
        total_nll, total_tok = 0.0, 0
        for batch in make_batches(seqs, args.batch_size, rng):
            mod.forward(batch, is_train=True)
            probs = mod.get_outputs()[0].asnumpy()  # [T*B, V]
            lab = np.asarray(batch.label[0].asnumpy(), np.int64)
            # fused path flattens T-major ([T, B]); cell path B-major
            lab_t = lab.reshape(-1) if USE_CELL_API else lab.T.reshape(-1)
            nll = -np.log(np.maximum(probs[np.arange(lab_t.size), lab_t], 1e-12))
            total_nll += float(nll.sum())
            total_tok += lab_t.size
            mod.backward()
            mod.update()
        ppl = math.exp(total_nll / total_tok)
        if first_ppl is None:
            first_ppl = ppl
        tag = ("cell-API" if USE_CELL_API
               else f"packed LSTM params: {n_params}")
        print(f"epoch {epoch}: perplexity {ppl:.3f} ({tag})")
    if args.epochs >= 2:
        assert ppl < first_ppl, "perplexity did not improve"
    # the shared-parameter contract: training through MIXED buckets left
    # ONE parameter set (the public view merges every bucket's executor)
    arg_params, _ = mod.get_params()
    if USE_CELL_API:
        assert "lstm_l0_i2h_weight" in arg_params  # shared across buckets
    else:
        assert "lstm_parameters" in arg_params
        assert arg_params["lstm_parameters"].shape == (n_params,)
    print(f"final-perplexity {ppl:.3f}")


if __name__ == "__main__":
    main()
