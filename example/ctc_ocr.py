#!/usr/bin/env python
"""Sequence recognition with CTC — the [U:example/ctc/] analog.

A toy line-OCR task, fully synthetic and download-free: each "image" is a
sequence of T column-feature vectors rendering a digit string of variable
length L ≤ max_len (distinct one-hot stripes + noise).  A BiLSTM over the
columns emits per-frame class scores; ``mx.nd.CTCLoss`` (the warp-ctc
analog, implemented as one ``lax.scan`` forward recursion with autodiff
backward) aligns frames to the unpadded label strings.  Greedy CTC
decoding (collapse repeats, drop blanks) reports sequence accuracy.

Run:  python example/ctc_ocr.py [--epochs 10] [--smoke]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu.gluon import nn, rnn

N_CLASSES = 11          # blank=0 + digits 1..10 (digit d encoded as d+1... 0->1)
FEAT = 16               # column-feature width
FRAMES_PER_CHAR = 3


def render_batch(rng, batch, max_len=5, t_frames=None):
    """Synthetic 'line images': each char paints FRAMES_PER_CHAR columns of
    a distinctive stripe pattern; labels are 1-based digit ids, 0-padded."""
    T = t_frames or (max_len * FRAMES_PER_CHAR + 2)
    x = rng.rand(T, batch, FEAT).astype(np.float32) * 0.1
    labels = np.zeros((batch, max_len), np.float32)
    for b in range(batch):
        L = rng.randint(1, max_len + 1)
        digits = rng.randint(0, 10, L)
        labels[b, :L] = digits + 1  # 1-based; 0 pads (= blank id)
        for i, d in enumerate(digits):
            lo = i * FRAMES_PER_CHAR
            # stripe: two hot rows per digit
            x[lo:lo + FRAMES_PER_CHAR, b, d] += 1.0
            x[lo:lo + FRAMES_PER_CHAR, b, 10 + (d % 6)] += 0.5
    return mx.nd.array(x), mx.nd.array(labels)


class OCRNet(gluon.HybridBlock):
    def __init__(self, hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.proj = nn.Dense(hidden, activation="relu", flatten=False)
            self.lstm = rnn.LSTM(hidden, bidirectional=True)
            self.head = nn.Dense(N_CLASSES, flatten=False)

    def forward(self, x):  # x: [T, B, FEAT]
        h = self.proj(x)
        h = self.lstm(h)       # [T, B, 2H]
        return self.head(h)    # [T, B, C]


def greedy_decode(logits):
    """argmax per frame → collapse repeats → drop blanks."""
    ids = logits.asnumpy().argmax(-1)  # [T, B]
    out = []
    for b in range(ids.shape[1]):
        seq, prev = [], -1
        for s in ids[:, b]:
            if s != prev and s != 0:
                seq.append(int(s))
            prev = s
        out.append(seq)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--smoke", action="store_true",
                    help="2 tiny epochs (CI smoke tier)")
    args = ap.parse_args()
    if args.smoke:
        args.epochs, args.batch = 2, 16

    rng = np.random.RandomState(0)
    mx.random.seed(0)
    net = OCRNet()
    net.initialize()
    x0, _ = render_batch(rng, 2)
    net(x0)  # materialize shapes
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    batches = 8 if args.smoke else 25
    for epoch in range(args.epochs):
        total = 0.0
        for _ in range(batches):
            x, y = render_batch(rng, args.batch)
            with autograd.record():
                logits = net(x)
                loss = mx.nd.CTCLoss(logits, y)
                mean_loss = loss.mean()
            mean_loss.backward()
            trainer.step(args.batch)
            total += float(mean_loss.asnumpy())
        # sequence accuracy on a fresh batch
        x, y = render_batch(rng, args.batch)
        decoded = greedy_decode(net(x))
        truth = [[int(v) for v in row if v != 0] for row in y.asnumpy()]
        acc = np.mean([d == t for d, t in zip(decoded, truth)])
        print(f"epoch {epoch}: ctc loss {total / batches:.3f}  "
              f"seq-acc {acc:.2f}")

    if args.smoke:
        assert total / batches < 20, "CTC loss failed to move"
        print("smoke ok")


if __name__ == "__main__":
    main()
