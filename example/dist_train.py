#!/usr/bin/env python
"""Multi-process data-parallel training ([U:example/image-classification/]
`--kv-store dist_sync` analog).  Launch with:

    python tools/launch_local.py -n 2 python example/dist_train.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray
    from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(10))
    net.initialize()
    net(mx.nd.zeros((2, 32)))

    def loss_fn(out, label):
        logits = out._data if hasattr(out, "_data") else out[0]._data
        return NDArray(streaming_softmax_ce(logits, label._data))

    trainer = SPMDTrainer(net, loss_fn, "sgd", {"learning_rate": 0.1},
                          mesh=make_mesh())
    rng = np.random.RandomState(100 + rank)  # each worker's LOCAL shard
    for step in range(20):
        x = rng.rand(64, 32).astype(np.float32)
        y = rng.randint(0, 10, (64,)).astype(np.int32)
        loss = trainer.step(*trainer.shard_batch(x, y))
    print(f"worker {rank}/{nw} final loss {float(np.asarray(loss._data)):.4f}")


if __name__ == "__main__":
    main()
