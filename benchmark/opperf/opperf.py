#!/usr/bin/env python
"""opperf — per-operator micro-benchmarks.

Parity: [U:benchmark/opperf/] (the reference's per-op latency suite run
across contexts).  Times a curated slice of the op registry on the
default backend: forward eager, forward jitted, and backward (via
jax.grad) where the op is differentiable; prints a table and optionally
JSON.

Usage:
    python benchmark/opperf/opperf.py [--ops dot,softmax] [--runs 50]
        [--warmup 5] [--json out.json]

On this sandbox the CPU backend is the default; run with the ambient env
(tunneled TPU) to profile real device dispatch:
    MXNET_OPPERF_CTX=tpu python benchmark/opperf/opperf.py
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
if os.environ.get("MXNET_OPPERF_CTX", "cpu") == "cpu":
    # force CPU even when the ambient env points at a tunneled device.
    # Env vars alone are NOT enough: sitecustomize registers the axon
    # plugin before this line runs, so deregister it in-process (the
    # tests/conftest.py pattern) or every per-op compile rides the tunnel.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

import numpy as np


def _cases(rng, large):
    """(op_name, args_factory, differentiable) — shapes follow the
    reference's default profiles (batched 2-D/4-D tensors)."""
    B = 32 if large else 8
    D = 512 if large else 64
    C, H, W = (64, 56, 56) if large else (8, 14, 14)
    f = np.float32

    def t(*shape):
        return rng.rand(*shape).astype(f)

    return [
        ("add", lambda: (t(B, D), t(B, D)), True, lambda a, b: a + b),
        ("mul", lambda: (t(B, D), t(B, D)), True, lambda a, b: a * b),
        ("dot", lambda: (t(D, D), t(D, D)), True, None),
        ("batch_dot", lambda: (t(B, D // 4, D // 4), t(B, D // 4, D // 4)), True, None),
        ("FullyConnected", lambda: (t(B, D), t(D, D), t(D)), True, None),
        ("Convolution", lambda: (t(B, C, H, W), t(C, C, 3, 3), t(C)), True, None),
        ("Pooling", lambda: (t(B, C, H, W),), True, None),
        ("BatchNorm", lambda: (t(B, C, H, W), t(C), t(C), t(C), t(C)), False, None),
        ("LayerNorm", lambda: (t(B, D), t(D), t(D)), True, None),
        ("softmax", lambda: (t(B, D),), True, None),
        ("log_softmax", lambda: (t(B, D),), True, None),
        ("relu", lambda: (t(B, D),), True, None),
        ("exp", lambda: (t(B, D),), True, None),
        ("sum", lambda: (t(B, D),), True, None),
        ("transpose", lambda: (t(B, D),), True, None),
        ("Embedding", lambda: (rng.randint(0, D, (B, 16)).astype(np.int32), t(D, 64)), False, None),
        ("Dropout", lambda: (t(B, D),), False, _dropout_fn),
        ("fused_attention", lambda: (t(B, 16, D), t(B, 16, D), t(B, 16, D)), True, None),
        # round-4 families
        ("linalg_potrf", lambda: (_gram(t(D // 4, D // 4)),), True, None),
        ("linalg_trsm", lambda: (np.tril(t(D // 4, D // 4)) + 2 * np.eye(D // 4, dtype=f), t(D // 4, D // 4)), True, None),
        ("CTCLoss", lambda: (t(16, B, 32), np.tile(np.arange(1, 6, dtype=f), (B, 1))), True, None),
        ("ROIPooling", lambda: (t(B, C, H, W), np.tile(np.array([0, 1, 1, H - 2, W - 2], f), (8, 1))), True, None),
        ("_contrib_ROIAlign", lambda: (t(B, C, H, W), np.tile(np.array([0, 1, 1, H - 2, W - 2], f), (8, 1))), True, None),
        ("_contrib_AdaptiveAvgPooling2D", lambda: (t(B, C, H, W),), True, None),
        ("im2col", lambda: (t(B, C, H, W),), True, None),
        ("masked_softmax", lambda: (t(B, D), rng.rand(B, D) > 0.2), True, None),
        ("_sample_normal", lambda: (t(B), t(B)), False, _sample_normal_fn),
        # round-5 families
        ("RNN", lambda: (t(16, B, 32), _rnn_params(rng, 32, 32)),
         True, None),
        ("_contrib_DeformableConvolution",
         lambda: (t(B, C, H, W), np.zeros((B, 18, H, W), f), t(C, C, 3, 3)),
         True, None),
        ("_contrib_DeformablePSROIPooling",
         lambda: (t(B, 2 * 4, H, W),
                  np.tile(np.array([0, 1, 1, H - 2, W - 2], f), (8, 1))),
         True, None),
        ("digamma", lambda: (t(B, D) + 0.5,), True, None),
        # round-5 tail
        ("Crop", lambda: (t(B, C, H, W),), True, None),
        ("quantize", lambda: (t(B, D), np.array([-1.0], f), np.array([1.0], f)),
         False, None),
        ("amp_multicast", lambda: (t(B, D).astype(np.float16), t(B, D)),
         False, None),
        ("choose_element_0index",
         lambda: (t(B, D), rng.randint(0, D, (B,)).astype(f)), True, None),
    ]


_KW = {"Convolution": {"kernel": (3, 3), "num_filter": 0, "pad": (1, 1)},
       "Pooling": {"kernel": (2, 2), "stride": (2, 2)},
       "fused_attention": {"num_heads": 4},
       "ROIPooling": {"pooled_size": (7, 7), "spatial_scale": 1.0},
       "_contrib_ROIAlign": {"pooled_size": (7, 7), "spatial_scale": 1.0,
                             "sample_ratio": 2},
       "_contrib_AdaptiveAvgPooling2D": {"output_size": (7, 7)},
       "im2col": {"kernel": (3, 3), "stride": (1, 1), "pad": (1, 1)},
       "RNN": {"mode": "lstm", "state_size": 32, "num_layers": 1},
       "_contrib_DeformableConvolution": {"kernel": (3, 3), "pad": (1, 1),
                                          "num_filter": 0, "no_bias": True},
       "_contrib_DeformablePSROIPooling": {"spatial_scale": 1.0,
                                           "output_dim": 2, "group_size": 2,
                                           "pooled_size": 7,
                                           "sample_per_part": 2,
                                           "no_trans": True},
       "Crop": {"h_w": (7, 7), "offset": (1, 1)},
       "quantize": {"out_type": "uint8"},
       "amp_multicast": {"num_outputs": 2}}


def _rnn_params(rng, C, H):
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size

    n = rnn_param_size("lstm", C, H)
    return rng.uniform(-0.1, 0.1, (n,)).astype(np.float32)


def _sample_normal_fn(mu, sigma):
    import jax

    from incubator_mxnet_tpu.ops.registry import get_op

    return get_op("_sample_normal").fn(mu, sigma, shape=(64,),
                                       key=jax.random.PRNGKey(0))


def _gram(x):
    """SPD input for the Cholesky benchmarks (A·Aᵀ + 4I)."""
    return (x @ x.T + 4 * np.eye(x.shape[0], dtype=x.dtype)).astype(x.dtype)


def _dropout_fn(x):
    import jax

    from incubator_mxnet_tpu.ops.registry import get_op

    # explicit key: the global key stack is for the framework's traced
    # paths, not plain jax.jit
    return get_op("Dropout").fn(x, training=True, key=jax.random.PRNGKey(0))


def bench_op(name, mk_args, diff, pyfn, runs, warmup):
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ops.registry import get_op

    kwargs = _KW.get(name, {})
    fn = pyfn or (lambda *a, _f=get_op(name).fn: _f(*a, **kwargs))
    args = tuple(jnp.asarray(a) for a in mk_args())

    def first(*a):
        out = fn(*a)
        return out[0] if isinstance(out, (list, tuple)) else out

    jfn = jax.jit(first)
    jax.block_until_ready(jfn(*args))

    def timed(g, n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = g(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / n * 1e3

    for _ in range(warmup):
        jax.block_until_ready(first(*args))
    eager_ms = timed(first, max(runs // 5, 3))
    jit_ms = timed(jfn, runs)

    bwd_ms = None
    if diff:
        gfn = jax.jit(jax.grad(lambda *a: first(*a).astype(jnp.float32).sum()))
        jax.block_until_ready(gfn(*args))
        bwd_ms = timed(gfn, runs)
    return eager_ms, jit_ms, bwd_ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None, help="comma-separated subset")
    ap.add_argument("--runs", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--large", action="store_true")
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    subset = set(args.ops.split(",")) if args.ops else None
    rows = []
    print(f"{'op':<22}{'eager(ms)':>12}{'jit(ms)':>12}{'bwd-jit(ms)':>14}")
    for name, mk, diff, pyfn in _cases(rng, args.large):
        if subset and name not in subset:
            continue
        try:
            eager, jit, bwd = bench_op(name, mk, diff, pyfn, args.runs, args.warmup)
        except Exception as e:  # keep going: the table is the product
            print(f"{name:<22}  FAILED: {type(e).__name__}: {str(e)[:60]}")
            continue
        print(f"{name:<22}{eager:>12.4f}{jit:>12.4f}"
              f"{(f'{bwd:.4f}' if bwd is not None else '-'):>14}")
        rows.append({"op": name, "eager_ms": eager, "jit_ms": jit, "bwd_ms": bwd})
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
