"""Trainer-step microbenchmark: fused whole-group update vs per-tensor loop.

Measures optimizer steps/sec of ``gluon.Trainer.step`` on a model with many
SMALL parameters — the regime the fused step exists for (docs/
optimizer_fusion.md): the per-tensor loop pays one jitted kernel launch,
one buffer swap, and fresh outputs per tensor per step, while the fused
path updates each parameter group in ONE donated-buffer jitted dispatch.

* ``per_tensor`` — ``Optimizer.aggregate_num = 0`` (the pre-fusion path,
  with the PR 2 dispatch machinery still active: the honest baseline)
* ``fused``      — the default fused whole-group step

Runs on any backend (CI smoke uses ``JAX_PLATFORMS=cpu``) and prints ONE
JSON line so CI and BENCH harvesting can grep it::

    python benchmark/opperf/trainer_step.py [--n-params 200] [--iters 10]

Acceptance floor (ISSUE 3): fused >= 2x per_tensor steps/sec on the
200-small-parameter model (CPU backend).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def _build(n_params, shape, seed, aggregate_num, optimizer, opt_args):
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import Parameter

    rs = np.random.RandomState(seed)
    params = []
    for k in range(n_params):
        p = Parameter(f"p{k}_weight", shape=shape, dtype="float32")
        p.initialize()
        p.set_data(mx.nd.array(rs.randn(*shape).astype(np.float32)))
        params.append(p)
    trainer = gluon.Trainer(params, optimizer, dict(opt_args), kvstore=None)
    trainer._optimizer.aggregate_num = aggregate_num
    grads = rs.randn(n_params, *shape).astype(np.float32)
    for p, g in zip(params, grads):
        p.grad()[:] = mx.nd.array(g)
    return trainer, params


def run(n_params=200, shape=(16, 4), iters=10, warmup=3, repeats=3,
        optimizer="sgd", opt_args=None):
    """Returns the result dict (also usable from tests as a smoke check).

    Measurement is PAIRED like benchmark/opperf/eager_dispatch.py: every
    timing round runs one ``step`` of each mode back-to-back and the
    per-mode score is the median round, so host drift hits both modes
    alike.  GC is paused during the timed rounds.  Both trainers share
    identical seeds/grads; their states advance in lockstep, so every
    round times the same mathematical step.
    """
    import gc

    import incubator_mxnet_tpu as mx

    opt_args = opt_args or {"learning_rate": 0.01, "momentum": 0.9, "wd": 1e-4}
    modes = {
        "per_tensor": _build(n_params, shape, 42, 0, optimizer, opt_args),
        "fused": _build(n_params, shape, 42, 1 << 20, optimizer, opt_args),
    }

    def one(mode):
        trainer, params = modes[mode]
        t0 = time.perf_counter()
        trainer.step(1)
        mx.nd.waitall()
        return time.perf_counter() - t0

    rounds = max(1, iters * repeats)
    for _ in range(max(1, warmup)):
        for m in modes:
            one(m)
    times = {m: [] for m in modes}
    gc.collect()
    gc_was_on = gc.isenabled()
    gc.disable()
    try:
        for _ in range(rounds):
            for m in modes:
                times[m].append(one(m))
    finally:
        if gc_was_on:
            gc.enable()

    medians = {m: _median(ts) for m, ts in times.items()}
    steps_per_sec = {m: 1.0 / v for m, v in medians.items()}
    return {
        "bench": "trainer_step",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "n_params": n_params,
        "shape": list(shape),
        "optimizer": optimizer,
        "iters": iters,
        "warmup": warmup,
        "repeats": repeats,
        "rounds": rounds,          # paired timing rounds behind each median
        "steps_per_sec": {m: round(v, 2) for m, v in steps_per_sec.items()},
        "median_s": medians,       # raw per-mode median round, seconds
        "speedup_fused": round(
            steps_per_sec["fused"] / steps_per_sec["per_tensor"], 2),
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n-params", type=int, default=200)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--side", type=int, default=16,
                   help="parameter tensor leading dim (small by design: the "
                        "bench isolates per-tensor dispatch overhead)")
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--repeats", type=int, default=3,
                   help="multiplier on --iters for the number of paired "
                        "timing rounds (median round wins)")
    p.add_argument("--optimizer", default="sgd")
    p.add_argument("--json", dest="json_path", default=None, metavar="PATH",
                   help="also write the result object to PATH — the "
                        "machine-readable record (medians, round counts, "
                        "config) bench trajectory harvesting reads instead "
                        "of hand-copied numbers")
    args = p.parse_args(argv)
    line = run(n_params=args.n_params, iters=args.iters,
               shape=(args.side, 4), warmup=args.warmup,
               repeats=args.repeats, optimizer=args.optimizer)
    print(json.dumps(line))
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(line, f, indent=2)
            f.write("\n")
    return line


if __name__ == "__main__":
    main()
