"""Weak/strong scaling harness: samples/sec vs device (and process) count,
with every point's time attribution on one merged timeline (ISSUE 20).

The ROADMAP's MLPerf item demands that "every scaling claim ships with
its curve".  This harness produces the curve AND its evidence:

* sweeps device count on a CPU virtual mesh (each point is a fresh
  subprocess with ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
  — device count is fixed per process) and/or process count (dist_sync
  ranks with the ``tools/launch_local.py`` DMLC environment),
* **weak** scaling holds per-device batch fixed (ideal: samples/sec
  grows linearly with N); **strong** scaling holds the global batch
  fixed,
* supports ``dp`` / ``fsdp`` / ``pipeline`` SPMD configs,
* every point runs under ``MXNET_COMPILE_GUARD=raise`` after warmup —
  a post-warmup recompile fails the point, not just a gate,
* every point's per-rank traces are fused by ``tools/trace_merge.py``
  and the goodput ledger recomputed from the merged dump must match the
  live-reported one (the attribution is PROVEN against the trace, not
  asserted), with straggler attribution (slowest rank by median step
  wall) and bubble/comm bucket splits per point,
* ``--json`` writes the machine-readable evidence
  ``tools/perf_history.py`` ingests; acceptance gates (efficiency
  floor, zero post-warmup recompiles, attribution match) set the exit
  code.

Usage::

    python benchmark/opperf/scaling.py [--mode weak|strong]
        [--config dp|fsdp|pipeline] [--devices 1,2,4,8] [--procs 1]
        [--steps 20] [--warmup 5] [--per-device-batch 8]
        [--efficiency-floor 0.05] [--json OUT] [--out-dir DIR] [--smoke]

``--smoke`` is the CI tier entry: the 2- and 4-device dp weak-scaling
points with small step counts.
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

RESULT_MARK = "SCALING_RESULT "
_CHILD_TIMEOUT_S = 600


# ---------------------------------------------------------------------------
# Child: one curve point in its own process (fixed device count)
# ---------------------------------------------------------------------------


def _drop_axon_backend():
    try:  # the tunneled-TPU factory registered by sitecustomize, if any
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def _build_net(gluon, seed):
    """4 Dense stages — splittable for the pipeline config."""
    import incubator_mxnet_tpu as mx

    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(8))
    net.initialize()
    net(mx.nd.zeros((2, 32)))
    return net


def child_spmd(args):
    """Single-process point: SPMD over the N-device CPU mesh."""
    _drop_axon_backend()
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, profiler
    from incubator_mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    from incubator_mxnet_tpu.parallel import SPMDTrainer, make_mesh
    from incubator_mxnet_tpu.parallel.sharding import fsdp_rules

    import jax

    n = jax.device_count()
    assert n == args.devices, (n, args.devices)
    batch = (args.per_device_batch * n if args.mode == "weak"
             else args.global_batch)
    batch = max(n, batch - batch % n)  # global batch must shard over dp

    net = _build_net(gluon, seed=7)
    loss_fn = SoftmaxCrossEntropyLoss()
    kw = {}
    if args.config == "fsdp":
        kw["mesh"] = make_mesh(fsdp=n)
        kw["rules"] = fsdp_rules()
    elif args.config == "pipeline":
        kw["mesh"] = make_mesh()
        kw["stages"] = net.split_stages([1, 1, 1, 1])
        kw["pipeline"] = {"schedule": "1f1b",
                          "n_microbatches": max(2, min(4, batch))}
    else:
        kw["mesh"] = make_mesh()
    trainer = SPMDTrainer(net, loss_fn, "sgd", {"learning_rate": 0.01},
                          **kw)

    rng = np.random.RandomState(0)
    x = rng.randn(batch, 32).astype(np.float32)
    y = rng.randint(0, 8, size=(batch,)).astype(np.float32)

    profiler.set_config(filename=args.trace)
    profiler.start()
    for _ in range(args.warmup):
        trainer.step(x, y)
    mx.nd.waitall()
    # the ledger measures ONLY the timed window: compile/warmup stays out
    # of the curve the same way it stays out of samples/sec
    profiler.reset_goodput()
    t0 = time.perf_counter()
    for _ in range(args.steps):
        trainer.step(x, y)
    mx.nd.waitall()
    elapsed = time.perf_counter() - t0
    snap = profiler.goodput_snapshot()
    counters = profiler.counters()
    profiler.dump()  # embeds the ledger + counters into the trace
    print(RESULT_MARK + json.dumps({
        "devices": n, "procs": 1, "rank": 0, "config": args.config,
        "mode": args.mode, "batch_global": batch, "steps": args.steps,
        "elapsed_s": round(elapsed, 6),
        "samples_per_sec": round(args.steps * batch / elapsed, 3),
        "goodput": snap,
        "recompile_steady_state": counters["recompile_steady_state"],
        "comms_ring_hops": counters["comms_ring_hops"],
        "pipeline_bubble_ms": counters["pipeline_bubble_ms"],
        "trace": args.trace,
    }), flush=True)


def child_dist(args):
    """One rank of a multi-process dist_sync point (bucketed pushpull
    gradient exchange — the measured ``comm`` bucket)."""
    _drop_axon_backend()
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, profiler

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    batch = (args.per_device_batch if args.mode == "weak"
             else max(1, args.global_batch // nw))

    net = _build_net(gluon, seed=7)
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01}, kvstore=kv)
    rng = np.random.RandomState(100 + rank)
    x = mx.nd.array(rng.randn(batch, 32).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 8, size=(batch,)).astype(np.float32))

    def one_step():
        with autograd.record():
            loss = L(net(x), y)
        loss.backward()
        trainer.step(batch)

    profiler.set_config(filename=args.trace)
    profiler.start()
    for _ in range(args.warmup):
        one_step()
    mx.nd.waitall()
    kv.barrier()
    profiler.reset_goodput()
    t0 = time.perf_counter()
    for _ in range(args.steps):
        one_step()
    mx.nd.waitall()
    kv.barrier()
    elapsed = time.perf_counter() - t0
    snap = profiler.goodput_snapshot()
    counters = profiler.counters()
    profiler.dump()
    if rank == 0:
        print(RESULT_MARK + json.dumps({
            "devices": 1, "procs": nw, "rank": 0, "config": "dist_sync",
            "mode": args.mode, "batch_global": batch * nw,
            "steps": args.steps, "elapsed_s": round(elapsed, 6),
            "samples_per_sec": round(args.steps * batch * nw / elapsed, 3),
            "goodput": snap,
            "recompile_steady_state": counters["recompile_steady_state"],
            "comms_ring_hops": counters["comms_ring_hops"],
            "pipeline_bubble_ms": counters["pipeline_bubble_ms"],
            "trace": args.trace,
        }), flush=True)


# ---------------------------------------------------------------------------
# Parent: sweep, merge, attribute, gate
# ---------------------------------------------------------------------------


def _reserve_port():
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    return s, s.getsockname()[1]


def _child_env(devices, extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("MXNET_FAULT_SPEC", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices}",
        "MXNET_COMPILE_GUARD": "raise",
    })
    env.update(extra or {})
    return env


def _parse_result(stdout, what):
    for line in stdout.splitlines():
        if line.startswith(RESULT_MARK):
            return json.loads(line[len(RESULT_MARK):])
    raise RuntimeError(f"{what}: no {RESULT_MARK.strip()} line in output:\n"
                       + stdout[-2000:])


def run_point_spmd(args, devices, out_dir):
    trace = os.path.join(out_dir, f"d{devices}_rank0.json")
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--devices", str(devices), "--config", args.config,
           "--mode", args.mode, "--steps", str(args.steps),
           "--warmup", str(args.warmup),
           "--per-device-batch", str(args.per_device_batch),
           "--global-batch", str(args.global_batch), "--trace", trace]
    # the guard arms itself after the warmup steps; warmup runs inside the
    # child BEFORE the timed window, so any post-warmup compile raises
    env = _child_env(devices,
                     {"MXNET_COMPILE_WARMUP_STEPS": str(args.warmup)})
    res = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         timeout=_CHILD_TIMEOUT_S)
    if res.returncode != 0:
        raise RuntimeError(
            f"point devices={devices} failed (rc {res.returncode}):\n"
            + (res.stderr or res.stdout)[-2000:])
    return _parse_result(res.stdout, f"devices={devices}"), [trace]


def run_point_dist(args, procs, out_dir):
    holder, port = _reserve_port()
    traces = [os.path.join(out_dir, f"p{procs}_rank{r}.json")
              for r in range(procs)]
    children = []
    for r in range(procs):
        cmd = [sys.executable, os.path.abspath(__file__), "--child",
               "--dist", "--mode", args.mode, "--steps", str(args.steps),
               "--warmup", str(args.warmup),
               "--per-device-batch", str(args.per_device_batch),
               "--global-batch", str(args.global_batch),
               "--trace", traces[r]]
        env = _child_env(1, {
            "MXNET_COMPILE_WARMUP_STEPS": str(args.warmup),
            "DMLC_ROLE": "worker", "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(procs), "DMLC_NUM_SERVER": "0",
            "DMLC_WORKER_ID": str(r),
        })
        children.append(subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    holder.close()
    outs = []
    for r, p in enumerate(children):
        try:
            out, err = p.communicate(timeout=_CHILD_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            for q in children:
                q.kill()
            raise
        if p.returncode != 0:
            for q in children:
                q.kill()
            raise RuntimeError(
                f"point procs={procs} rank {r} failed "
                f"(rc {p.returncode}):\n" + (err or out)[-2000:])
        outs.append(out)
    return _parse_result(outs[0], f"procs={procs}"), traces


def attribute_point(result, traces, out_dir, tag):
    """Merge the point's per-rank traces and pull the attribution the
    curve ships with: the merged-ledger goodput (cross-checked against
    the live-reported one), bubble/comm splits, and the straggler rank."""
    import trace_merge

    merged = trace_merge.merge_traces(traces)
    merged_path = os.path.join(out_dir, f"merged_{tag}.json")
    with open(merged_path, "w") as f:
        json.dump(merged, f)
    summ = trace_merge.goodput_summary(merged)
    live = result["goodput"]
    match = False
    if summ is not None and live.get("wall_s"):
        # rank 0's live snapshot vs the same rank's ledger as embedded in
        # the merged dump: taken one dump() apart, so equal to tolerance
        rank0 = summ["per_rank"].get(result.get("rank", 0)) or {}
        w0, w1 = live["wall_s"], rank0.get("wall_s") or 0.0
        match = w1 > 0 and abs(w0 - w1) / max(w0, w1) < 0.10
    ranks = (merged.get("otherData") or {}).get("ranks") or {}
    med_walls = {}
    for rk, entry in ranks.items():
        steps = (entry or {}).get("steps") or []
        walls = sorted(s.get("wall_ms", 0.0) for s in steps)
        if walls:
            med_walls[int(rk)] = walls[len(walls) // 2]
    straggler = None
    if len(med_walls) > 1:
        worst = max(med_walls, key=med_walls.get)
        straggler = {"rank": worst,
                     "median_step_wall_ms": round(med_walls[worst], 3),
                     "ranks_compared": len(med_walls)}
    buckets = live.get("buckets_s") or {}
    return {
        "merged_trace": merged_path,
        "merged_goodput": None if summ is None else
            {"wall_s": summ["wall_s"], "goodput": summ["goodput"],
             "buckets_s": summ["buckets_s"], "worst": summ["worst"]},
        "attribution_match": match,
        "bubble_s": buckets.get("bubble", 0.0),
        "comm_s": buckets.get("comm", 0.0),
        "straggler": straggler,
    }


def run_sweep(args):
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="mxnet_scaling_")
    os.makedirs(out_dir, exist_ok=True)
    points = []
    for devices in args.devices:
        result, traces = run_point_spmd(args, devices, out_dir)
        result.update(attribute_point(result, traces, out_dir,
                                      f"d{devices}"))
        points.append(result)
        print(f"[scaling] devices={devices}: "
              f"{result['samples_per_sec']:.1f} samples/s, goodput "
              f"{(result['goodput']['goodput'] or 0) * 100:.1f}%",
              file=sys.stderr, flush=True)
    for procs in args.procs_list:
        if procs < 2:
            continue
        result, traces = run_point_dist(args, procs, out_dir)
        result.update(attribute_point(result, traces, out_dir,
                                      f"p{procs}"))
        points.append(result)
        print(f"[scaling] procs={procs}: "
              f"{result['samples_per_sec']:.1f} samples/s",
              file=sys.stderr, flush=True)

    # per-point efficiency vs linear from the sweep's first point:
    # eff(N) = (T_N / T_base) / (N / base) — 1.0 is perfect scaling
    base = points[0]
    base_n = base["devices"] * base["procs"]
    base_t = base["samples_per_sec"]
    for pt in points:
        n = pt["devices"] * pt["procs"]
        ideal = base_t * n / base_n
        pt["efficiency"] = round(pt["samples_per_sec"] / ideal, 4)

    recomp_pass = all(pt["recompile_steady_state"] == 0 for pt in points)
    eff_pass = all(pt["efficiency"] >= args.efficiency_floor
                   for pt in points)
    attr_pass = all(pt["attribution_match"] for pt in points)
    evidence = {
        "schema": 1,
        "bench": "scaling",
        "mode": args.mode,
        "config": args.config,
        "per_device_batch": args.per_device_batch,
        "global_batch": args.global_batch,
        "steps": args.steps,
        "warmup": args.warmup,
        "points": points,
        "gates": {
            "efficiency_floor": args.efficiency_floor,
            "efficiency_pass": eff_pass,
            "recompile_pass": recomp_pass,
            "attribution_pass": attr_pass,
        },
        "pass": eff_pass and recomp_pass and attr_pass,
    }
    return evidence


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--mode", choices=("weak", "strong"), default="weak")
    ap.add_argument("--config", choices=("dp", "fsdp", "pipeline"),
                    default="dp")
    ap.add_argument("--devices", default="1,2,4,8",
                    help="comma-separated device counts (one subprocess "
                         "per point; CPU virtual mesh)")
    ap.add_argument("--procs", dest="procs_list", default="",
                    help="comma-separated dist_sync process counts to "
                         "sweep in addition to --devices")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--per-device-batch", type=int, default=8)
    ap.add_argument("--global-batch", type=int, default=64)
    ap.add_argument("--efficiency-floor", type=float, default=0.05,
                    help="minimum per-point efficiency-vs-linear "
                         "(CPU virtual meshes share one socket — the "
                         "floor proves the curve is a curve, not a wall)")
    ap.add_argument("--json", default=None,
                    help="write the evidence JSON here")
    ap.add_argument("--out-dir", default=None,
                    help="keep per-point traces/merges here")
    ap.add_argument("--smoke", action="store_true",
                    help="CI tier entry: 2- and 4-device dp weak points, "
                         "small step counts")
    # -- child-process plumbing (internal) --
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--dist", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--trace", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.smoke:
        args.devices = "2,4"
        args.steps = min(args.steps, 10)
        args.warmup = min(args.warmup, 3)

    args.devices = ([int(x) for x in str(args.devices).split(",") if x]
                    if not isinstance(args.devices, list) else args.devices)
    args.procs_list = [int(x) for x in str(args.procs_list).split(",") if x]

    if args.child:
        args.devices = args.devices[0] if args.devices else 1
        if args.trace is None:  # traceless smoke (bench.py outage evidence)
            import tempfile
            args.trace = os.path.join(
                tempfile.mkdtemp(prefix="scaling_child_"), "rank.json")
        if args.dist:
            child_dist(args)
        else:
            child_spmd(args)
        return 0

    evidence = run_sweep(args)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(evidence, f, indent=1)
        print(f"[scaling] evidence -> {args.json}", file=sys.stderr)
    print(json.dumps({
        "bench": "scaling", "mode": evidence["mode"],
        "config": evidence["config"],
        "curve": [[pt["devices"] * pt["procs"], pt["samples_per_sec"],
                   pt["efficiency"]] for pt in evidence["points"]],
        "pass": evidence["pass"],
        "gates": evidence["gates"],
    }))
    return 0 if evidence["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
