"""Serving-tier benchmark: throughput at a p99 latency SLO.

Open-loop harness in the Gemma-on-Cloud-TPU comparison shape (PAPERS.md):
requests arrive by a **Poisson process** (open loop — arrivals don't wait
for completions, so queueing delay is real) with **mixed lengths**, and
the headline metric is **throughput-at-SLO**: the highest sustained
arrival rate at which p99 end-to-end latency stays within ``--slo-ms``.

Two modes over the SAME workload:

* ``sequential`` — one request at a time through warmed single-request
  ``Predictor.forward`` (shape-bucketed, so it never recompiles either:
  the baseline isolates the BATCHING win, not compile overhead).
  Queueing is simulated exactly from measured service times (arrival
  order, M/D/1-style: start = max(arrival, previous completion)).
* ``served`` — through ``serving.InferenceServer`` (dynamic batching +
  (batch, length) bucketing), paced in real time by a feeder thread.

Acceptance (ISSUE 8): served throughput-at-SLO >= 3x sequential on CPU,
with ZERO recompiles after warmup — the harness exits non-zero if any
batch bound or compiled a new program once warmup finished (the CI
bucket-miss regression guard), so a bucketing regression cannot land
silently.

Prints ONE JSON line (like the other opperf harnesses)::

    python benchmark/opperf/serving.py [--smoke] [--json PATH]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as _np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

_perf = time.perf_counter


def build_model(layers=4, feat=64):
    """A padding-safe per-position MLP: ``layers`` blocks of
    FullyConnected(flatten=False) + tanh over (batch, length, feat).
    Parameter shapes are length-independent, so one weight copy serves
    every bucket."""
    import incubator_mxnet_tpu as mx
    import incubator_mxnet_tpu.symbol as S

    S.symbol._reset_naming()
    rng = _np.random.RandomState(0)
    x = S.var("data")
    params = {}
    for i in range(layers):
        name = f"fc{i}"
        x = S.FullyConnected(x, num_hidden=feat, flatten=False, name=name)
        x = S.Activation(x, act_type="tanh", name=f"act{i}")
        params[f"arg:{name}_weight"] = mx.nd.array(
            (rng.randn(feat, feat) / _np.sqrt(feat)).astype(_np.float32))
        params[f"arg:{name}_bias"] = mx.nd.array(
            _np.zeros(feat, _np.float32))
    return x, params


def make_workload(n, max_length, feat, seed):
    """(lengths, inputs): mixed request lengths uniform in
    [max_length//8, max_length] and the per-request sample arrays."""
    rng = _np.random.RandomState(seed)
    lo = max(1, max_length // 8)
    lengths = rng.randint(lo, max_length + 1, size=n)
    inputs = [rng.rand(int(L), feat).astype(_np.float32) for L in lengths]
    return lengths, inputs


def poisson_arrivals(n, rate, seed):
    rng = _np.random.RandomState(seed)
    gaps = rng.exponential(1.0 / rate, size=n)
    return _np.cumsum(gaps)


def _pct(xs, q):
    from incubator_mxnet_tpu import profiler

    return float(profiler.percentile(xs, q))


def _trial_line(n, rate, elapsed, lats, slo_ms):
    p99 = _pct(lats, 0.99)
    return {
        "rate": float(rate),
        "throughput": float(n / elapsed) if elapsed > 0 else 0.0,
        "p50_ms": _pct(lats, 0.50),
        "p99_ms": p99,
        "ok": bool(p99 <= slo_ms),
    }


# ---------------------------------------------------------------------------
# sequential baseline
# ---------------------------------------------------------------------------

class SequentialBaseline:
    """Warmed single-request predictor over the same length buckets."""

    def __init__(self, sym, params, feat, bucketer):
        from incubator_mxnet_tpu.predictor import Predictor

        self.feat = feat
        self.bucketer = bucketer
        self.pred = Predictor(
            sym, params, {"data": (1, bucketer.buckets[0], feat)})
        for lb in bucketer.buckets:  # warm every bucket
            self.pred.reshape({"data": (1, lb, self.feat)})
            self.pred.forward()

    def serve_one(self, sample):
        lb = self.bucketer.bucket_for(sample.shape[0])
        buf = _np.zeros((1, lb, self.feat), _np.float32)
        buf[0, :sample.shape[0]] = sample
        t0 = _perf()
        self.pred.reshape({"data": buf.shape})
        self.pred.predict(data=buf)
        return _perf() - t0

    def trial(self, inputs, rate, seed, slo_ms):
        """Simulated open-loop queueing from REAL measured service times."""
        arrivals = poisson_arrivals(len(inputs), rate, seed)
        done_prev = 0.0
        lats = []
        for arr, sample in zip(arrivals, inputs):
            svc = self.serve_one(sample)
            start = max(arr, done_prev)
            done_prev = start + svc
            lats.append((done_prev - arr) * 1e3)
        elapsed = done_prev - arrivals[0]
        return _trial_line(len(inputs), rate, elapsed, lats, slo_ms)


class ServedMode:
    """Real-time open loop against an InferenceServer."""

    def __init__(self, server):
        self.server = server

    def trial(self, inputs, rate, seed, slo_ms):
        arrivals = poisson_arrivals(len(inputs), rate, seed)
        pendings = [None] * len(inputs)
        submit_lag = [0.0] * len(inputs)
        t_start = _perf()

        def feeder():
            for i, (arr, sample) in enumerate(zip(arrivals, inputs)):
                now = _perf() - t_start
                if arr > now:
                    time.sleep(arr - now)
                # open-loop honesty: latency is measured from the
                # SCHEDULED Poisson arrival, so any backlog the feeder
                # itself accumulates at high rates counts against the
                # request instead of silently shifting the clock — the
                # rate search must be able to find a failing rate
                submit_lag[i] = max(0.0, (_perf() - t_start) - arr)
                pendings[i] = self.server.submit({"data": sample})

        th = threading.Thread(target=feeder, daemon=True)
        th.start()
        th.join()
        for p in pendings:
            p.result(timeout=60.0)
        elapsed = (_perf() - t_start) - arrivals[0]
        lats = [p.latency_ms + lag * 1e3
                for p, lag in zip(pendings, submit_lag)]
        return _trial_line(len(inputs), rate, elapsed, lats, slo_ms)


def max_rate_at_slo(trial_fn, inputs, base_rate, slo_ms, seed,
                    max_doublings=10, bisect_steps=2):
    """Highest Poisson arrival rate whose p99 meets the SLO: double from
    ``base_rate`` until the first failure, then bisect the last bracket.
    Returns (best_passing_trial, trials_run)."""
    trials = []
    best, lo, hi = None, None, None
    rate = base_rate
    for _ in range(max_doublings):
        t = trial_fn(inputs, rate, seed, slo_ms)
        trials.append(t)
        if t["ok"]:
            best, lo = t, rate
            rate *= 2.0
        else:
            hi = rate
            break
    if best is None:
        return None, trials
    for _ in range(bisect_steps if hi is not None else 0):
        mid = (lo + hi) / 2.0
        t = trial_fn(inputs, mid, seed, slo_ms)
        trials.append(t)
        if t["ok"]:
            best, lo = t, mid
        else:
            hi = mid
    return best, trials


# ---------------------------------------------------------------------------


def run(n_requests=400, layers=4, feat=64, max_length=128, max_batch=16,
        slo_ms=50.0, seed=0, smoke=False):
    import incubator_mxnet_tpu  # noqa: F401 — path check
    from incubator_mxnet_tpu import profiler
    from incubator_mxnet_tpu.serving import InferenceServer, ShapeBucketer

    sym, params = build_model(layers=layers, feat=feat)
    _, inputs = make_workload(n_requests, max_length, feat, seed)
    bucketer = ShapeBucketer(max_length=max_length,
                             min_bucket=max(8, max_length // 8))

    # -- sequential baseline ------------------------------------------
    seq = SequentialBaseline(sym, params, feat, bucketer)
    seq_compile0 = seq.pred.compile_stats()
    # capacity estimate anchors the rate ladder
    svc = sorted(seq.serve_one(inputs[i % len(inputs)]) for i in range(9))[4]
    base_rate = max(1.0, 0.25 / svc)
    seq_best, seq_trials = max_rate_at_slo(
        seq.trial, inputs, base_rate, slo_ms, seed)
    seq_recompiled = seq.pred.compile_stats() != seq_compile0

    # -- served mode ---------------------------------------------------
    server = InferenceServer(
        sym, params, {"data": (None, feat)},
        max_batch_size=max_batch,
        max_queue_ms=slo_ms / 5.0,
        slo_ms=slo_ms,
        length_buckets=bucketer.buckets,
        name="serving_bench")
    srv_compile0 = server.compile_stats()
    served = ServedMode(server)
    served_best, served_trials = max_rate_at_slo(
        served.trial, inputs, base_rate, slo_ms, seed)
    stats = server.stats()
    srv_recompiled = (server.compile_stats() != srv_compile0
                      or stats["bucket_miss_after_warmup"] > 0)
    server.close()

    speedup = None
    if seq_best and served_best:
        speedup = round(served_best["throughput"] / seq_best["throughput"], 2)
    line = {
        "bench": "serving",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "smoke": smoke,
        "slo_ms": slo_ms,
        "n_requests": n_requests,
        "layers": layers,
        "feat": feat,
        "max_length": max_length,
        "max_batch": max_batch,
        "length_buckets": list(bucketer.buckets),
        "single_service_ms": round(svc * 1e3, 3),
        "sequential": seq_best,
        "served": served_best,
        "trials": {"sequential": len(seq_trials),
                   "served": len(served_trials)},
        "throughput_at_slo": {
            "sequential": seq_best["throughput"] if seq_best else None,
            "served": served_best["throughput"] if served_best else None,
        },
        "speedup_at_slo": speedup,
        "recompiles_after_warmup": {
            "sequential": bool(seq_recompiled),
            "served": bool(srv_recompiled),
            "bucket_miss_after_warmup": stats["bucket_miss_after_warmup"],
        },
        "serving_counters": {k: v for k, v in profiler.counters().items()
                             if k.startswith("serving_")},
    }
    return line


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--requests", type=int, default=400)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--feat", type=int, default=64)
    p.add_argument("--max-length", type=int, default=128)
    p.add_argument("--max-batch", type=int, default=16)
    p.add_argument("--slo-ms", type=float, default=50.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="small fast configuration for the CI serving tier; "
                        "the zero-recompile guard still applies")
    p.add_argument("--json", dest="json_path", default=None, metavar="PATH",
                   help="also write the result object to PATH")
    args = p.parse_args(argv)
    if args.smoke:
        cfg = dict(n_requests=80, layers=2, feat=16, max_length=64,
                   max_batch=8, slo_ms=args.slo_ms, seed=args.seed,
                   smoke=True)
    else:
        cfg = dict(n_requests=args.requests, layers=args.layers,
                   feat=args.feat, max_length=args.max_length,
                   max_batch=args.max_batch, slo_ms=args.slo_ms,
                   seed=args.seed)
    line = run(**cfg)
    print(json.dumps(line))
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(line, f, indent=2)
            f.write("\n")
    rec = line["recompiles_after_warmup"]
    if rec["sequential"] or rec["served"]:
        print("FAIL: a batch recompiled after warmup "
              f"({rec})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
