"""Step-fold microbenchmark: one compiled program per training step.

Measures steps/sec of the SAME training step driven three ways on a
dispatch-bound model (many small Dense layers — the regime whole-program
folding exists for, docs/step_fold.md):

* ``eager``  — per-op dispatch: un-hybridized forward, tape backward, the
  (fused-group) ``Trainer.step``.  The honest pre-fold baseline.
* ``hybrid`` — the pre-fold BEST practice: hybridized forward (one
  CachedOp jit) + tape backward + fused ``Trainer.step`` — still several
  host dispatches per step.
* ``folded`` — ``Trainer.fold_step``: forward + loss + backward +
  optimizer tail as ONE donated-buffer compiled dispatch.

Measurement is PAIRED like the other opperf harnesses: every timing round
runs one step of each mode back-to-back, the per-mode score is the median
round, GC is off during rounds.  After warmup the harness ASSERTS the
fold's steady-state contract and exits non-zero on violation:

* exactly ONE host-issued device dispatch per folded step (the
  ``step_fold.DISPATCH_COUNTERS`` delta),
* zero steady-state recompiles (``recompile_steady_state`` delta — the
  fold arms the PR 9 compile guard after its first step).

``--dist`` adds the 2-process overlap experiment: workers launched via
``tools/launch_local.py`` train against a ``dist_sync`` store and time
``sequential`` (allreduce after backward: ``loss.backward()`` then
``Trainer.step``) vs ``overlap`` (``Trainer.backward``: each gradient
bucket's pushpull launches from the grad-readiness hook DURING backward),
with convergence parity between both modes asserted.  Paired medians ride
the evidence JSON (docs/STEP_FOLD_EVIDENCE_r15.json).

``--k [K ...]`` switches to the K-step fold sweep (``Trainer.fold_steps``,
docs/step_fold.md "Multi-step fold"): the same logical step timed at fold
widths K (default 1 vs 4 vs 16), paired per round, scored per LOGICAL
step.  After warmup it asserts dispatches/logical-step == 1/K exactly and
zero steady-state recompiles; non-smoke additionally requires the largest
K to beat K=1 by >= 1.3x (the ISSUE 17 acceptance floor).

Acceptance (ISSUE 15): folded >= 2x eager steps/sec on CPU; dist overlap
per-step wall < sequential.  (ISSUE 17): K=16 >= 1.3x the K=1 folded
step, dispatches per logical step exactly 1/K.

    python benchmark/opperf/step_fold.py [--smoke] [--dist] [--json PATH]
    python benchmark/opperf/step_fold.py --k            # 1 vs 4 vs 16
    python benchmark/opperf/step_fold.py --k 1 8 --smoke
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, ROOT)


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def _build(seed, hybrid, layers, width, batch, kvstore=None):
    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon

    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    for _ in range(layers):
        net.add(gluon.nn.Dense(width, activation="relu"))
    net.add(gluon.nn.Dense(8))
    net.initialize()
    if hybrid:
        net.hybridize()
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.rand(batch, 16).astype(np.float32))
    y = mx.nd.array(rs.rand(batch, 8).astype(np.float32))
    net(x)  # materialize deferred shapes
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9},
                            kvstore=kvstore)
    return net, trainer, x, y


def run(layers=12, width=32, batch=8, iters=10, warmup=4, repeats=3):
    """Local three-mode comparison + the steady-state assertions.
    Returns the result dict (smoke-checkable from tests)."""
    import gc

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, profiler
    from incubator_mxnet_tpu.gluon import step_fold

    L2 = gluon.loss.L2Loss()

    nets = {}
    for mode, hybrid in (("eager", False), ("hybrid", True),
                         ("folded", True)):
        nets[mode] = _build(42, hybrid, layers, width, batch)
    net_f, tr_f, x_f, y_f = nets["folded"]
    folded = tr_f.fold_step(lambda a, b: L2(net_f(a), b), block=net_f)

    def eager_like(mode):
        net, tr, x, y = nets[mode]
        with autograd.record():
            loss = L2(net(x), y)
        loss.backward()
        tr.step(batch)

    steps = {
        "eager": lambda: eager_like("eager"),
        "hybrid": lambda: eager_like("hybrid"),
        "folded": lambda: folded(x_f, y_f),
    }

    def one(mode):
        t0 = time.perf_counter()
        steps[mode]()
        mx.nd.waitall()
        return time.perf_counter() - t0

    for _ in range(max(1, warmup)):
        for m in steps:
            one(m)
    if not folded.folded:
        print(f"FOLD FELL BACK: {folded.fallback_reason}", file=sys.stderr)
        raise SystemExit(3)

    # steady-state contract, asserted BEFORE timing so a violation can't
    # hide behind a fast median
    c0 = profiler.counters()
    check_steps = 3
    for _ in range(check_steps):
        folded(x_f, y_f)
    mx.nd.waitall()
    c1 = profiler.counters()
    dispatches = (step_fold.host_dispatch_total(c1)
                  - step_fold.host_dispatch_total(c0)) / check_steps
    recompiles = c1["recompile_steady_state"] - c0["recompile_steady_state"]

    rounds = max(1, iters * repeats)
    times = {m: [] for m in steps}
    gc.collect()
    gc_was_on = gc.isenabled()
    gc.disable()
    try:
        for _ in range(rounds):
            for m in steps:
                times[m].append(one(m))
    finally:
        if gc_was_on:
            gc.enable()
    medians = {m: _median(ts) for m, ts in times.items()}
    steps_per_sec = {m: 1.0 / v for m, v in medians.items()}
    return {
        "bench": "step_fold",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "layers": layers, "width": width, "batch": batch,
        "rounds": rounds,
        "steps_per_sec": {m: round(v, 2) for m, v in steps_per_sec.items()},
        "median_s": medians,
        "speedup_folded_vs_eager": round(
            steps_per_sec["folded"] / steps_per_sec["eager"], 2),
        "speedup_folded_vs_hybrid": round(
            steps_per_sec["folded"] / steps_per_sec["hybrid"], 2),
        "folded_dispatches_per_step": dispatches,
        "recompiles_steady_state": recompiles,
    }


def run_k_sweep(ks=(1, 4, 16), layers=12, width=32, batch=8, iters=10,
                warmup=3, repeats=3):
    """K-step fold sweep (``Trainer.fold_steps``): time the SAME logical
    training step at several fold widths K and assert the dispatch
    contract — exactly one host dispatch per K logical steps (1/K per
    logical step) and zero steady-state recompiles.  K=1 is the PR 15
    single-step fold; larger K amortises the per-dispatch host cost over
    the in-program ``lax.scan``.  Measurement is paired per round (one
    window of each K back-to-back), score = median wall / K (per LOGICAL
    step).  Returns the result dict."""
    import gc

    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, profiler
    from incubator_mxnet_tpu.gluon import step_fold

    L2 = gluon.loss.L2Loss()
    folds = {}
    for k in ks:
        net, tr, x, y = _build(42, True, layers, width, batch)
        fold = tr.fold_steps(lambda a, b, n=net: L2(n(a), b), k=k,
                             block=net)
        if k == 1:
            folds[k] = (fold, (x, y))
        else:
            # [K, batch, ...] stacked window, the stage_window layout
            xw = mx.nd.array(np.repeat(np.asarray(x._data)[None],
                                       k, axis=0))
            yw = mx.nd.array(np.repeat(np.asarray(y._data)[None],
                                       k, axis=0))
            folds[k] = (fold, (xw, yw))

    def one(k):
        fold, nds = folds[k]
        t0 = time.perf_counter()
        fold(*nds)
        mx.nd.waitall()
        return (time.perf_counter() - t0) / k   # per LOGICAL step

    for _ in range(max(1, warmup)):
        for k in ks:
            one(k)
    for k in ks:
        fold, _ = folds[k]
        if not fold.folded:
            print(f"K={k} FOLD FELL BACK: {fold.fallback_reason}",
                  file=sys.stderr)
            raise SystemExit(3)

    # dispatch contract AFTER warmup: one window dispatch covers K logical
    # steps, so dispatches / logical step must be exactly 1/K
    c_base = profiler.counters()["recompile_steady_state"]
    dispatch_ratio = {}
    check_windows = 3
    for k in ks:
        c0 = profiler.counters()
        for _ in range(check_windows):
            one(k)
        c1 = profiler.counters()
        d = (step_fold.host_dispatch_total(c1)
             - step_fold.host_dispatch_total(c0))
        dispatch_ratio[k] = d / (check_windows * k)

    rounds = max(1, iters * repeats)
    times = {k: [] for k in ks}
    gc.collect()
    gc_was_on = gc.isenabled()
    gc.disable()
    try:
        for _ in range(rounds):
            for k in ks:
                times[k].append(one(k))
    finally:
        if gc_was_on:
            gc.enable()
    recompiles = (profiler.counters()["recompile_steady_state"] - c_base)
    medians = {k: _median(ts) for k, ts in times.items()}
    kmax, kmin = max(ks), min(ks)
    return {
        "bench": "step_fold_k_sweep",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "layers": layers, "width": width, "batch": batch,
        "rounds": rounds, "ks": list(ks),
        "logical_steps_per_sec": {str(k): round(1.0 / m, 2)
                                  for k, m in medians.items()},
        "median_logical_step_s": {str(k): m for k, m in medians.items()},
        "dispatches_per_logical_step": {str(k): round(r, 6)
                                        for k, r in dispatch_ratio.items()},
        "speedup_kmax_vs_k1": round(medians[kmin] / medians[kmax], 2),
        "k_max": kmax,
        "recompiles_steady_state": recompiles,
    }


# ---------------------------------------------------------------------------
# dist overlap experiment (2 processes over launch_local)
# ---------------------------------------------------------------------------


def dist_worker(layers, width, batch, iters, warmup, bucket_kb):
    """Worker body (run under tools/launch_local.py at n=2): time
    sequential allreduce-after-backward vs grad-readiness-hooked overlap
    on the SAME model against a dist_sync store, then assert convergence
    parity between the two modes.  Rank 0 prints one JSON marker line."""
    os.environ["MXNET_KVSTORE_BUCKET_BYTES"] = str(bucket_kb * 1024)
    import gc

    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon

    L2 = gluon.loss.L2Loss()
    kv = mx.kv.create("dist_sync")
    rank = kv.rank

    # NON-hybridized on purpose: a hybridized block's backward is ONE tape
    # node, so every grad finalizes at once and there is nothing for the
    # readiness hook to overlap.  The per-op tape finalizes grads in
    # reverse-layer order — bucket k's pushpull rides the wire while the
    # earlier layers' VJPs still run.
    net, trainer, x, y = _build(7, False, layers, width, batch, kvstore=kv)

    def sequential():
        with autograd.record():
            loss = L2(net(x), y)
        loss.backward()          # full backward first ...
        trainer.step(batch)      # ... then every bucket's allreduce
        return loss

    def overlap():
        with autograd.record():
            loss = L2(net(x), y)
        trainer.backward(loss)   # buckets pushpull DURING backward
        trainer.step(batch)
        return loss

    modes = {"sequential": sequential, "overlap": overlap}

    def one(mode):
        kv.barrier()
        t0 = time.perf_counter()
        modes[mode]()
        mx.nd.waitall()
        return time.perf_counter() - t0

    for _ in range(max(1, warmup)):
        for m in modes:
            one(m)
    times = {m: [] for m in modes}
    gc.collect()
    gc.disable()
    for _ in range(iters):
        for m in modes:
            times[m].append(one(m))
    gc.enable()
    medians = {m: _median(ts) for m, ts in times.items()}

    # convergence parity: two fresh same-seeded models, N steps each mode
    net_a, tr_a, xa, ya = _build(13, True, layers, width, batch, kvstore=kv)
    net_b, tr_b, xb, yb = _build(13, True, layers, width, batch, kvstore=kv)
    la = lb = None
    for _ in range(10):
        with autograd.record():
            la = L2(net_a(xa), ya)
        la.backward()
        tr_a.step(batch)
        with autograd.record():
            lb = L2(net_b(xb), yb)
        tr_b.backward(lb)
        tr_b.step(batch)
    mx.nd.waitall()
    fa = float(la.mean().asscalar())
    fb = float(lb.mean().asscalar())
    conv_ok = bool(np.isfinite(fa) and np.isfinite(fb)
                   and abs(fa - fb) <= 1e-5 + 1e-3 * abs(fa))

    from incubator_mxnet_tpu import profiler as _p
    launched = _p.counters()["allreduce_overlap_launched"]
    if rank == 0:
        print("STEP_FOLD_DIST_JSON: " + json.dumps({
            "workers": kv.num_workers,
            "bucket_kb": bucket_kb,
            "median_s": medians,
            "overlap_speedup": round(
                medians["sequential"] / medians["overlap"], 3),
            "overlap_buckets_launched": launched,
            "convergence": {"sequential": fa, "overlap": fb,
                            "parity": conv_ok},
        }), flush=True)
    kv.barrier()
    if not conv_ok:
        raise SystemExit(4)


def run_dist(layers=12, width=256, batch=32, iters=8, warmup=3,
             bucket_kb=64):
    """Launch the 2-process overlap experiment; returns its JSON dict."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)     # workers boot their own CPU backend
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, os.path.join(ROOT, "tools", "launch_local.py"),
           "-n", "2", sys.executable, os.path.abspath(__file__),
           "--dist-worker", "--layers", str(layers), "--width", str(width),
           "--batch", str(batch), "--iters", str(iters),
           "--warmup", str(warmup), "--bucket-kb", str(bucket_kb)]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)
    sys.stderr.write(proc.stderr[-2000:])
    for line in proc.stdout.splitlines():
        if line.startswith("STEP_FOLD_DIST_JSON: "):
            out = json.loads(line[len("STEP_FOLD_DIST_JSON: "):])
            out["returncode"] = proc.returncode
            return out
    sys.stderr.write(proc.stdout[-2000:])
    raise RuntimeError(
        f"dist workers produced no result (rc={proc.returncode})")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--layers", type=int, default=None)
    p.add_argument("--width", type=int, default=None)
    p.add_argument("--batch", type=int, default=None)
    p.add_argument("--iters", type=int, default=None)
    p.add_argument("--warmup", type=int, default=None)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--smoke", action="store_true",
                   help="tiny config; the steady-state assertions ARE the "
                        "regression guard (non-zero exit on any violation)")
    p.add_argument("--k", dest="k_sweep", nargs="*", type=int, default=None,
                   metavar="K",
                   help="run the K-step fold sweep instead (default sweep "
                        "1 4 16, or the listed K values): times the same "
                        "logical step at each fold width and asserts "
                        "dispatches/logical-step == 1/K after warmup")
    p.add_argument("--dist", action="store_true",
                   help="also run the 2-process overlap experiment")
    p.add_argument("--bucket-kb", type=int, default=64)
    p.add_argument("--dist-worker", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--json", dest="json_path", default=None, metavar="PATH")
    args = p.parse_args(argv)

    if args.dist_worker:
        dist_worker(args.layers or 12, args.width or 256, args.batch or 32,
                    args.iters or 8, args.warmup or 3, args.bucket_kb)
        return None

    if args.smoke:
        defaults = dict(layers=6, width=32, batch=8, iters=3, warmup=2,
                        repeats=1)
    else:
        defaults = dict(layers=12, width=32, batch=8, iters=10, warmup=4,
                        repeats=args.repeats)
    for k in ("layers", "width", "batch", "iters", "warmup"):
        if getattr(args, k) is not None:
            defaults[k] = getattr(args, k)
        defaults.setdefault(k, None)

    if args.k_sweep is not None:
        ks = tuple(sorted(set(args.k_sweep))) or (
            (1, 4) if args.smoke else (1, 4, 16))
        result = run_k_sweep(ks=ks, **defaults)
        print(json.dumps(result))
        if args.json_path:
            with open(args.json_path, "w") as f:
                json.dump(result, f, indent=2)
                f.write("\n")
        rc = 0
        for k_str, ratio in result["dispatches_per_logical_step"].items():
            want = 1.0 / int(k_str)
            if abs(ratio - want) > 1e-9:
                print(f"FAIL: K={k_str}: {ratio} dispatches per logical "
                      f"step (want exactly {want:.6f})", file=sys.stderr)
                rc = 1
        if result["recompiles_steady_state"]:
            print(f"FAIL: {result['recompiles_steady_state']} steady-state "
                  "recompiles during the sweep", file=sys.stderr)
            rc = 1
        # smoke asserts the dispatch contract only — paired-median timing
        # on a 3-iter tiny config is noise, not signal
        if not args.smoke and len(ks) > 1 \
                and result["speedup_kmax_vs_k1"] < 1.3:
            print(f"FAIL: K={result['k_max']} only "
                  f"{result['speedup_kmax_vs_k1']}x the K={min(ks)} folded "
                  "step (acceptance floor 1.3x)", file=sys.stderr)
            rc = 1
        if rc:
            raise SystemExit(rc)
        return result

    result = run(**defaults)

    if args.dist:
        result["dist"] = run_dist(bucket_kb=args.bucket_kb)

    print(json.dumps(result))
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")

    rc = 0
    if result["folded_dispatches_per_step"] != 1:
        print(f"FAIL: {result['folded_dispatches_per_step']} dispatches "
              "per folded step (want exactly 1)", file=sys.stderr)
        rc = 1
    if result["recompiles_steady_state"]:
        print(f"FAIL: {result['recompiles_steady_state']} steady-state "
              "recompiles after warmup", file=sys.stderr)
        rc = 1
    if not args.smoke and result["speedup_folded_vs_eager"] < 2.0:
        print(f"FAIL: folded only {result['speedup_folded_vs_eager']}x "
              "eager (acceptance floor 2x)", file=sys.stderr)
        rc = 1
    if args.dist:
        d = result["dist"]
        if d.get("returncode"):
            print("FAIL: dist workers exited non-zero", file=sys.stderr)
            rc = 1
        if not d["convergence"]["parity"]:
            print("FAIL: overlap/sequential convergence parity",
                  file=sys.stderr)
            rc = 1
        if d["overlap_speedup"] <= 1.0:
            print(f"FAIL: overlap {d['overlap_speedup']}x sequential "
                  "(want > 1)", file=sys.stderr)
            rc = 1
    if rc:
        raise SystemExit(rc)
    return result


if __name__ == "__main__":
    main()
