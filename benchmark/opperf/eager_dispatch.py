"""Eager-dispatch microbenchmark: uncached vs cached-jit vs bulked.

Measures ops/sec on an N-op eager elementwise chain and an SGD-style
optimizer-update chain through ``ndarray.invoke`` under the three dispatch
regimes of docs/eager_dispatch.md:

* ``uncached``   — level-1 cache disabled (the pre-accelerator hot path:
                   raw Python tracing + per-primitive XLA dispatch per op)
* ``cached_jit`` — level-1 dispatch cache (ops/registry.py)
* ``bulked``     — level-2 op-bulking (engine.bulk): whole chain flushed
                   as one compiled program per iteration

Runs on any backend (CI smoke uses ``JAX_PLATFORMS=cpu``) and prints ONE
JSON line so CI and BENCH harvesting can grep it::

    python benchmark/opperf/eager_dispatch.py [--n-ops 64] [--iters 30]

Acceptance floor (ISSUE 2): cached_jit >= 2x uncached and
bulked >= cached_jit on the 64-op elementwise chain (CPU backend).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def _elemwise_chain(nd, x, n_ops):
    """n_ops elementwise ops (one ``invoke`` dispatch each), cycling
    scale / softsign / shift / hard_sigmoid — the small arithmetic +
    activation mix the motivation targets.  softsign (abs+add+div) and
    hard_sigmoid (mul+add+clip) lower to several XLA primitives, so the
    uncached path pays one dispatch per *primitive* while a cached entry
    replays one fused executable per *op* — exactly the gap the level-1
    cache exists to close.  Outputs stay in [0, 1]: numerically safe at
    any chain length."""
    steps = (lambda y: y * 1.0001,
             lambda y: nd.softsign(y),
             lambda y: y + 0.0001,
             lambda y: nd.hard_sigmoid(y))
    y = x
    for i in range(n_ops):
        y = steps[i % 4](y)
    return y


def _sgd_chain(nd, w, g, n_steps):
    """Manual SGD idiom (`w = w - lr * g` outside record): 2 dispatches
    per step, the optimizer/metric-update shape of eager traffic."""
    for _ in range(n_steps):
        w = w - (g * 0.01)
    return w


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def run(n_ops=64, iters=30, shape=(8, 8), warmup=5, repeats=5):
    """Returns the result dict (also usable from tests as a smoke check).

    Measurement is PAIRED: every timing round runs one iteration of each
    mode back-to-back and the per-mode score is the median round.  Dispatch
    overhead is tens of us/op — well inside the drift of a shared or
    virtualized CPU host over the seconds a blocked per-mode loop takes —
    and pairing at iteration granularity makes that drift hit all modes
    alike instead of whichever mode owned the slow window.  GC is paused
    during the timed rounds (standard microbenchmark hygiene: collection
    pauses land between rounds, not inside a random mode's timing).
    """
    import gc

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import engine
    from incubator_mxnet_tpu.ops import registry

    nd = mx.nd
    x = nd.ones(shape)
    g = nd.ones(shape)

    modes = ("uncached", "cached_jit", "bulked")
    results = {m: {} for m in modes}
    medians = {m: {} for m in modes}
    rounds = max(1, iters * repeats)
    prev = registry.set_dispatch_cache(enabled=True, warmup=0)
    try:
        out = {}

        def elem():
            out["y"] = _elemwise_chain(nd, x, n_ops)

        def sgd():
            out["y"] = _sgd_chain(nd, x, g, n_ops // 2)

        for name, body in (("elemwise", elem), ("sgd_update", sgd)):
            def bulked(_b=body):
                with engine.bulk(n_ops + 1):
                    _b()

            def one(mode, _body=body, _bulked=bulked):
                registry.set_dispatch_cache(enabled=(mode != "uncached"),
                                            warmup=0)
                t0 = time.perf_counter()
                (_bulked if mode == "bulked" else _body)()
                out["y"].wait_to_read()
                return time.perf_counter() - t0

            times = {m: [] for m in modes}
            for _ in range(max(1, warmup)):
                for m in modes:
                    one(m)
            gc.collect()
            gc_was_on = gc.isenabled()
            gc.disable()
            try:
                for r in range(rounds):
                    for m in modes:
                        times[m].append(one(m))
                    if r % 50 == 49:
                        gc.enable()
                        gc.collect()
                        gc.disable()
            finally:
                if gc_was_on:
                    gc.enable()
            for m in modes:
                med = _median(times[m])
                results[m][name] = n_ops / med
                medians[m][name] = med
    finally:
        registry.set_dispatch_cache(enabled=prev[0], max_entries=prev[1],
                                    warmup=prev[2])
        registry.clear_dispatch_cache()

    line = {
        "bench": "eager_dispatch",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "n_ops": n_ops,
        "iters": iters,
        "warmup": warmup,
        "repeats": repeats,
        "rounds": rounds,          # paired timing rounds behind each median
        "shape": list(shape),
        "ops_per_sec": results,
        "median_s": medians,       # raw per-mode median round, seconds
        "speedup_cached": round(
            results["cached_jit"]["elemwise"] / results["uncached"]["elemwise"], 2),
        "speedup_bulked": round(
            results["bulked"]["elemwise"] / results["uncached"]["elemwise"], 2),
    }
    return line


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n-ops", type=int, default=64)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--side", type=int, default=8,
                   help="square tensor side (small by design: the bench "
                        "isolates dispatch overhead, not kernel FLOPs)")
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--repeats", type=int, default=5,
                   help="multiplier on --iters for the number of paired "
                        "timing rounds (median round wins)")
    p.add_argument("--json", dest="json_path", default=None, metavar="PATH",
                   help="also write the result object to PATH — the "
                        "machine-readable record (medians, round counts, "
                        "config) bench trajectory harvesting reads instead "
                        "of hand-copied numbers")
    args = p.parse_args(argv)
    line = run(n_ops=args.n_ops, iters=args.iters,
               shape=(args.side, args.side), warmup=args.warmup,
               repeats=args.repeats)
    print(json.dumps(line))
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(line, f, indent=2)
            f.write("\n")
    return line


if __name__ == "__main__":
    main()
