"""Quantized-collectives microbenchmark: fp32 vs bf16 vs int8 gradient
exchange on BOTH cross-host paths (docs/gradient_compression.md).

* ``pushpull`` — ``kvstore.bucketed_pushpull`` against a dist store: the
  same gradient set allreduced under each codec tier, bytes-on-wire read
  back from the ``comms_bytes_raw``/``comms_bytes_wire`` counters (the
  acceptance evidence is counter-verified, not computed by the harness).
* ``spmd`` — one ``SPMDTrainer`` per tier on the virtual 8-device CPU
  mesh: the int8 tier's in-program quantize → integer psum → dequantize
  runs inside the same donated-buffer compiled step, so the comparison
  also guards the zero-steady-state-recompile contract
  (``MXNET_COMPILE_GUARD=raise`` armed after warmup; non-zero exit on
  any post-warmup compile).

Measurement is PAIRED like the other opperf harnesses: each timing round
runs one step of every tier back-to-back, median round wins, GC paused.

Acceptance (ISSUE 14): the int8 tier moves >= 3.5x fewer gradient bytes
than fp32 on BOTH paths (counters), with the opt-out groups still
travelling exact.  ``--algo ring|psum|both`` (ISSUE 19) A/Bs the SPMD
exchange algorithm over the SAME buckets — for the ring, the evidence is
per-HOP: every ppermute payload is one encoded chunk, and the harness
exits non-zero if the int8 per-hop byte ratio falls below 3.5x fp32 or
any tier recompiles after warmup.

    python benchmark/opperf/collectives.py [--json PATH] [--smoke]
                                           [--algo ring|psum|both]
"""
from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

# the SPMD half needs a multi-device dp axis; default to the suite's
# virtual 8-device CPU mesh when run bare (before any jax import)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

TIERS = ("fp32", "bf16", "int8")
# the SPMD half also runs the int4 packed tier: its nibble wire rides the
# ring hops / int4 psum grid, while the host bucket path rejects it (no
# linear sum for packed nibbles) — so it never joins the pushpull tiers
SPMD_TIERS = TIERS + ("int4",)


@contextlib.contextmanager
def _armed_guard():
    """Arm the steady-state compile guard for the harness WITHOUT leaking
    process state: the CI smoke imports ``run()`` in-process, and a bare
    ``os.environ.setdefault`` here would leave the whole remaining test
    suite in raise mode (armed by whichever trainer stepped last)."""
    from incubator_mxnet_tpu import profiler

    unset = "MXNET_COMPILE_GUARD" not in os.environ
    if unset:
        os.environ["MXNET_COMPILE_GUARD"] = "raise"
    try:
        yield
    finally:
        if unset:
            os.environ.pop("MXNET_COMPILE_GUARD", None)
        profiler.disarm_compile_guard()


def _guarded(fn):
    def wrapper(*args, **kwargs):
        with _armed_guard():
            return fn(*args, **kwargs)
    wrapper.__doc__ = fn.__doc__
    wrapper.__name__ = fn.__name__
    return wrapper


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def _policy(tier, algo=None):
    from incubator_mxnet_tpu import comm

    # "off", not None: None re-resolves MXNET_GRAD_COMPRESS downstream,
    # and an exported tier in the caller's env would silently compress
    # the fp32 BASELINE, making every ratio in the evidence meaningless
    if tier == "fp32":
        return "off"
    pol = comm.resolve_policy(tier)
    if algo is not None:
        # pin the exchange algorithm for the A/B regardless of the
        # caller's MXNET_GRAD_COMPRESS_ALGO
        pol = comm.CompressionPolicy(pol.codec,
                                     error_feedback=pol.error_feedback,
                                     algo=algo)
    return pol


def _counter_delta(fn):
    """Run ``fn`` and return (result, raw_bytes, wire_bytes) counted."""
    from incubator_mxnet_tpu import profiler

    c0 = profiler.counters()
    out = fn()
    c1 = profiler.counters()
    return (out, c1["comms_bytes_raw"] - c0["comms_bytes_raw"],
            c1["comms_bytes_wire"] - c0["comms_bytes_wire"])


def run_pushpull(n_params=64, shape=(64, 32), iters=10, warmup=2, repeats=3):
    """Paired bucketed-pushpull timing: one gradient set, three wire
    tiers, per-tier error feedback carried across rounds like a real
    training loop."""
    import gc

    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import comm, kvstore as kv_mod
    from incubator_mxnet_tpu.gluon import Parameter

    rs = np.random.RandomState(7)
    params = []
    for k in range(n_params):
        p = Parameter(f"c{k}_weight", shape=shape, dtype="float32")
        p.initialize()
        p.set_data(mx.nd.array(rs.randn(*shape).astype(np.float32)))
        params.append(p)
    grads = [rs.randn(*shape).astype(np.float32) for _ in params]
    kv = kv_mod.create("dist_sync")
    feedbacks = {t: comm.ErrorFeedback() for t in TIERS}

    def one(tier):
        for p, g in zip(params, grads):
            p.grad()[:] = mx.nd.array(g)
        items = [(i, p.grad()) for i, p in enumerate(params)]
        names = [p.name for p in params]
        pol = _policy(tier)
        t0 = time.perf_counter()
        kv_mod.bucketed_pushpull(kv, items, names=names, compression=pol,
                                 feedback=feedbacks[tier])
        mx.nd.waitall()
        return time.perf_counter() - t0

    byte_ratio = {}
    for tier in TIERS:
        for _ in range(max(1, warmup)):
            one(tier)
        _, raw, wire = _counter_delta(lambda: one(tier))
        byte_ratio[tier] = {"bytes_raw": raw, "bytes_wire": wire,
                            "ratio": round(raw / wire, 3) if wire else 0.0}
    rounds = max(1, iters * repeats)
    times = {t: [] for t in TIERS}
    gc.collect()
    gc_was_on = gc.isenabled()
    gc.disable()
    try:
        for _ in range(rounds):
            for t in TIERS:
                times[t].append(one(t))
    finally:
        if gc_was_on:
            gc.enable()
    medians = {t: _median(v) for t, v in times.items()}
    return {
        "rounds": rounds,
        "median_s": medians,
        "steps_per_sec": {t: round(1.0 / v, 2) for t, v in medians.items()},
        "bytes": byte_ratio,
    }


@_guarded
def run_spmd(batch=32, features=64, hidden=256, classes=8, iters=10,
             warmup=2, repeats=3, algo="psum"):
    """Paired SPMD-step timing, one trainer per tier, under the
    steady-state compile guard.  ``algo`` picks the exchange form for the
    compressed tiers: ``psum`` (quantize -> integer psum -> dequantize)
    or ``ring`` (explicit encoded ppermute hops, comm/ring.py) — same
    buckets either way, so the A/B isolates the algorithm."""
    import gc

    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, profiler
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.parallel import SPMDTrainer, make_mesh

    def build():
        mx.random.seed(11)
        net = nn.HybridSequential()
        net.add(nn.Dense(hidden, activation="relu"),
                nn.Dense(hidden, activation="relu"), nn.Dense(classes))
        net.initialize()
        net(mx.nd.zeros((2, features)))
        return net

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(1)
    x = rng.randn(batch, features).astype(np.float32)
    y = rng.randint(0, classes, (batch,)).astype(np.float32)

    trainers = {}

    def one(tier):
        tr = trainers[tier]
        t0 = time.perf_counter()
        loss = tr.step(mx.nd.array(x), mx.nd.array(y))
        loss.asnumpy()  # sync: time the whole compiled step
        return time.perf_counter() - t0

    with profiler.compile_guard_paused():
        for tier in SPMD_TIERS:
            trainers[tier] = SPMDTrainer(
                build(), loss_fn, "sgd", {"learning_rate": 0.05},
                mesh=make_mesh(),
                compression=_policy(tier, algo=algo))
        for _ in range(max(1, warmup)):
            for t in SPMD_TIERS:
                one(t)
    base_recompiles = profiler.counters()["recompile_steady_state"]

    byte_ratio = {}
    for tier in SPMD_TIERS:
        _, raw, wire = _counter_delta(lambda: one(tier))
        if tier == "fp32":
            # the fp32 trainer has no comm accounting: its dp exchange IS
            # the raw payload — derive it from the int8 trainer's layout
            continue
        entry = {"bytes_raw": raw, "bytes_wire": wire,
                 "ratio": round(raw / wire, 3) if wire else 0.0}
        cfg_t = trainers[tier]._comm_cfg
        if algo == "ring" and cfg_t["hops"]:
            # per-HOP wire accounting (the acceptance evidence is
            # hop-granular for the ring: every ppermute payload is the
            # encoded chunk, so the per-hop ratio IS the codec's)
            from incubator_mxnet_tpu.comm import ring as ring_mod

            chunk = ring_mod._ring_chunk(cfg_t["codec"], cfg_t["n"],
                                         cfg_t["shards"])
            entry.update(
                hops=cfg_t["hops"], bytes_per_hop=cfg_t["bytes_hop"],
                fp32_bytes_per_hop=4 * chunk,
                hop_ratio_vs_fp32=round(4 * chunk / cfg_t["bytes_hop"], 3)
                if cfg_t["bytes_hop"] else 0.0)
        byte_ratio[tier] = entry
    cfg = trainers["int8"]._comm_cfg
    byte_ratio["fp32"] = {"bytes_raw": cfg["bytes_raw"],
                          "bytes_wire": cfg["bytes_raw"], "ratio": 1.0}

    rounds = max(1, iters * repeats)
    times = {t: [] for t in SPMD_TIERS}
    gc.collect()
    gc_was_on = gc.isenabled()
    gc.disable()
    try:
        for _ in range(rounds):
            for t in SPMD_TIERS:
                times[t].append(one(t))
    finally:
        if gc_was_on:
            gc.enable()
    recompiles = profiler.counters()["recompile_steady_state"] - base_recompiles
    medians = {t: _median(v) for t, v in times.items()}
    return {
        "algo": algo,
        "rounds": rounds,
        "median_s": medians,
        "steps_per_sec": {t: round(1.0 / v, 2) for t, v in medians.items()},
        "bytes": byte_ratio,
        "post_warmup_recompiles": int(recompiles),
    }


def run(n_params=64, shape=(64, 32), batch=32, hidden=256, iters=10,
        warmup=2, repeats=3, algo="both"):
    pushpull = run_pushpull(n_params=n_params, shape=shape, iters=iters,
                            warmup=warmup, repeats=repeats)
    algos = ("psum", "ring") if algo == "both" else (algo,)
    spmd_ab = {}
    for a in algos:
        spmd_ab[a] = run_spmd(batch=batch, hidden=hidden, iters=iters,
                              warmup=warmup, repeats=repeats, algo=a)
    primary = "ring" if "ring" in spmd_ab else algos[0]
    spmd = spmd_ab[primary]
    ratios = {
        "pushpull_int8": pushpull["bytes"]["int8"]["ratio"],
        "spmd_int8": spmd["bytes"]["int8"]["ratio"],
    }
    ok = all(v >= 3.5 for v in ratios.values())
    if "ring" in spmd_ab:
        # hop-granular acceptance: the ring's per-ppermute payload must
        # be >= 3.5x narrower than the fp32 chunk it replaces (>= 6x for
        # the packed int4 nibbles)
        ratios["spmd_ring_int8_per_hop"] = \
            spmd_ab["ring"]["bytes"]["int8"]["hop_ratio_vs_fp32"]
        ratios["spmd_ring_int4_per_hop"] = \
            spmd_ab["ring"]["bytes"]["int4"]["hop_ratio_vs_fp32"]
        ok = (ok and ratios["spmd_ring_int8_per_hop"] >= 3.5
              and ratios["spmd_ring_int4_per_hop"] >= 6.0)
    recompiles = sum(r["post_warmup_recompiles"] for r in spmd_ab.values())
    return {
        "bench": "collectives",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "n_params": n_params,
        "shape": list(shape),
        "batch": batch,
        "hidden": hidden,
        "algo": algo,
        "pushpull": pushpull,
        "spmd": spmd,
        "spmd_ab": spmd_ab,
        "int8_byte_ratio": ratios,
        "bytes_acceptance": bool(ok),   # int8 >= 3.5x on BOTH paths
        "post_warmup_recompiles": int(recompiles),
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n-params", type=int, default=64)
    p.add_argument("--side", type=int, default=64)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--algo", choices=("psum", "ring", "both"),
                   default="both",
                   help="gradient-exchange algorithm for the SPMD half: "
                        "the quantized psum sandwich, the explicit "
                        "encoded-ppermute ring, or an A/B of both over "
                        "the same buckets (default)")
    p.add_argument("--smoke", action="store_true",
                   help="tiny config + 1 round: the CI regression guard "
                        "(non-zero exit on post-warmup recompiles or an "
                        "int8 byte-ratio below 3.5x on either path, "
                        "per-hop for the ring)")
    p.add_argument("--json", dest="json_path", default=None, metavar="PATH")
    args = p.parse_args(argv)
    kw = dict(n_params=args.n_params, shape=(args.side, 32),
              batch=args.batch, hidden=args.hidden, iters=args.iters,
              warmup=args.warmup, repeats=args.repeats, algo=args.algo)
    if args.smoke:
        kw.update(n_params=16, iters=1, repeats=1, warmup=1, hidden=128)
    line = run(**kw)
    print(json.dumps(line))
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(line, f, indent=2)
            f.write("\n")
    if line["post_warmup_recompiles"]:
        print(f"FAIL: {line['post_warmup_recompiles']} post-warmup "
              "recompile(s) in the compressed SPMD step", file=sys.stderr)
        return 2
    if not line["bytes_acceptance"]:
        print(f"FAIL: int8 byte ratio below 3.5x: {line['int8_byte_ratio']}",
              file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    rc = main()
    sys.exit(rc if isinstance(rc, int) else 0)
