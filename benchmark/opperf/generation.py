"""Generation-tier benchmark: tokens/sec at a TTFT + per-token SLO.

Open-loop harness in the Gemma-on-Cloud-TPU serving shape (PAPERS.md):
prompts arrive by a **Poisson process** (open loop — arrivals don't wait
for completions, so queueing delay is real) with **mixed prompt lengths
and mixed token budgets**, and the headline metric is **tokens/sec at
SLO**: generated-token throughput at the highest sustained arrival rate
whose p99 time-to-first-token AND p99 per-output-token latency both meet
their SLOs.

Two modes over the SAME workload and the SAME engine:

* ``static`` — drain-and-refill batching (``batching="static"``):
  admissions only into an EMPTY decode batch, so utilization drains as
  each wave finishes — the pre-continuous-batching baseline.
* ``continuous`` — iteration-level continuous batching: finished
  sequences leave and queued prefills join BETWEEN decode steps.

Acceptance (ISSUE 11): continuous beats static on tokens/sec-at-SLO,
with ZERO compiles after warmup under ``MXNET_COMPILE_GUARD=raise`` —
the harness arms raise mode itself and exits non-zero if any program
compiled once warmup finished (the CI regression guard for the
slot-cache discipline).

Prints ONE JSON line (like the other opperf harnesses)::

    python benchmark/opperf/generation.py [--smoke] [--json PATH]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as _np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

_perf = time.perf_counter

VOCAB, BOS, EOS = 17, 1, 2


def build_model(units=24, layers=1, heads=2, seed=0):
    """Tiny pre-norm encoder-decoder transformer with materialized
    (seeded, untrained) weights — the harness measures the scheduler and
    the compiled decode loop, not model quality; request lifetimes vary
    through each request's sampled token budget."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon.model_zoo.transformer import Transformer

    mx.random.seed(seed)
    net = Transformer(VOCAB, units=units, hidden_size=2 * units,
                      num_heads=heads, num_encoder_layers=layers,
                      num_decoder_layers=layers, dropout=0.0, max_length=256)
    net.initialize()
    net(mx.nd.array(_np.ones((1, 8), _np.int32), dtype="int32"),
        mx.nd.array(_np.ones((1, 1), _np.int32), dtype="int32"))
    return net


def make_workload(n, max_prompt, max_new, seed):
    rng = _np.random.RandomState(seed)
    prompts = [rng.randint(3, VOCAB, int(L)).astype(_np.int32)
               for L in rng.randint(2, max_prompt + 1, size=n)]
    budgets = rng.randint(2, max_new + 1, size=n).tolist()
    return prompts, budgets


def poisson_arrivals(n, rate, seed):
    rng = _np.random.RandomState(seed)
    return _np.cumsum(rng.exponential(1.0 / rate, size=n))


def _pct(xs, q):
    from incubator_mxnet_tpu import profiler

    return float(profiler.percentile(xs, q))


def run_trial(server, prompts, budgets, rate, seed, ttft_slo_ms,
              tpot_slo_ms):
    """One open-loop trial at ``rate`` req/s.  Latency is charged from
    the SCHEDULED Poisson arrival (feeder backlog counts against the
    request — the serving.py honesty rule), so the rate search can find
    the real SLO edge."""
    n = len(prompts)
    arrivals = poisson_arrivals(n, rate, seed)
    results = [None] * n
    lag = [0.0] * n
    t0 = _perf()

    def feeder():
        for i, (arr, p, b) in enumerate(zip(arrivals, prompts, budgets)):
            now = _perf() - t0
            if arr > now:
                time.sleep(arr - now)
            lag[i] = max(0.0, (_perf() - t0) - arr)
            results[i] = server.submit(p, max_new_tokens=int(b))

    th = threading.Thread(target=feeder, daemon=True)
    th.start()
    th.join()
    tokens = 0
    ttfts, tpots = [], []
    for r, lg in zip(results, lag):
        toks = r.result(timeout=300.0)
        tokens += len(toks)
        ttfts.append((r.ttft_ms or 0.0) + lg * 1e3)
        if r.tpot_ms is not None:
            tpots.append(r.tpot_ms)
    elapsed = (_perf() - t0) - float(arrivals[0])
    p99_ttft = _pct(ttfts, 0.99)
    p99_tpot = _pct(tpots, 0.99) if tpots else 0.0
    return {
        "rate": float(rate),
        "tokens": int(tokens),
        "tokens_per_s": float(tokens / elapsed) if elapsed > 0 else 0.0,
        "ttft_ms_p50": _pct(ttfts, 0.50),
        "ttft_ms_p99": p99_ttft,
        "tpot_ms_p50": _pct(tpots, 0.50) if tpots else 0.0,
        "tpot_ms_p99": p99_tpot,
        "ok": bool(p99_ttft <= ttft_slo_ms and p99_tpot <= tpot_slo_ms),
    }


def max_rate_at_slo(server, prompts, budgets, base_rate, seed, ttft_slo_ms,
                    tpot_slo_ms, max_doublings=8, bisect_steps=2):
    trials = []
    best, lo, hi = None, None, None
    rate = base_rate
    for _ in range(max_doublings):
        t = run_trial(server, prompts, budgets, rate, seed, ttft_slo_ms,
                      tpot_slo_ms)
        trials.append(t)
        if t["ok"]:
            best, lo = t, rate
            rate *= 2.0
        else:
            hi = rate
            break
    if best is None:
        return None, trials
    for _ in range(bisect_steps if hi is not None else 0):
        mid = (lo + hi) / 2.0
        t = run_trial(server, prompts, budgets, mid, seed, ttft_slo_ms,
                      tpot_slo_ms)
        trials.append(t)
        if t["ok"]:
            best, lo = t, mid
        else:
            hi = mid
    return best, trials


def run(n_requests=120, units=24, layers=1, max_prompt=16, max_new=24,
        slots=4, ttft_slo_ms=250.0, tpot_slo_ms=50.0, seed=0, smoke=False):
    from incubator_mxnet_tpu import profiler
    from incubator_mxnet_tpu.serving import GenerationServer

    # the acceptance contract IS raise mode: one stray compile after
    # warmup fails every in-flight request, which fails the harness
    profiler.set_config(compile_guard="raise")
    net = build_model(units=units, layers=layers, seed=seed)
    prompts, budgets = make_workload(n_requests, max_prompt, max_new, seed)

    line = {
        "bench": "generation",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "smoke": smoke,
        "n_requests": n_requests,
        "units": units,
        "layers": layers,
        "max_prompt": max_prompt,
        "max_new": max_new,
        "slots_per_bucket": slots,
        "ttft_slo_ms": ttft_slo_ms,
        "tpot_slo_ms": tpot_slo_ms,
        "modes": {},
        "recompiles_after_warmup": {},
    }
    base_rate = None
    for mode in ("static", "continuous"):
        server = GenerationServer(
            net, bos=BOS, eos=EOS, max_prompt_length=max_prompt,
            max_new_tokens=max_new, slots_per_bucket=slots,
            tenants={"default": {"max_queue": 100000}},
            batching=mode, name=f"gen_bench_{mode}")
        try:
            if base_rate is None:
                # capacity anchor: one request alone, steady state
                t0 = _perf()
                toks = server.submit(prompts[0],
                                     max_new_tokens=int(budgets[0])) \
                    .result(120.0)
                svc = max(1e-4, _perf() - t0)
                base_rate = max(0.5, 0.25 * slots * len(toks)
                                / (svc * float(_np.mean(budgets))))
            steady0 = profiler.counters()["recompile_steady_state"]
            comp0 = server.compile_stats()["compiles"]
            best, trials = max_rate_at_slo(
                server, prompts, budgets, base_rate, seed, ttft_slo_ms,
                tpot_slo_ms)
            recompiled = (
                profiler.counters()["recompile_steady_state"] != steady0
                or server.compile_stats()["compiles"] != comp0)
            line["modes"][mode] = {"best": best, "trials": len(trials)}
            line["recompiles_after_warmup"][mode] = bool(recompiled)
        finally:
            server.close()
            profiler.disarm_compile_guard()
    cont = line["modes"]["continuous"]["best"]
    stat = line["modes"]["static"]["best"]
    line["tokens_per_s_at_slo"] = {
        "continuous": cont["tokens_per_s"] if cont else None,
        "static": stat["tokens_per_s"] if stat else None,
    }
    line["speedup_at_slo"] = (
        round(cont["tokens_per_s"] / stat["tokens_per_s"], 2)
        if cont and stat and stat["tokens_per_s"] > 0 else None)
    profiler.set_config(compile_guard=None)
    return line


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--requests", type=int, default=120)
    p.add_argument("--units", type=int, default=24)
    p.add_argument("--layers", type=int, default=1)
    p.add_argument("--max-prompt", type=int, default=16)
    p.add_argument("--max-new", type=int, default=24)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--ttft-slo-ms", type=float, default=250.0)
    p.add_argument("--tpot-slo-ms", type=float, default=50.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="small fast configuration for the CI serving tier; "
                        "the zero-recompile guard still applies")
    p.add_argument("--json", dest="json_path", default=None, metavar="PATH",
                   help="also write the result object to PATH")
    args = p.parse_args(argv)
    if args.smoke:
        cfg = dict(n_requests=40, units=16, layers=1, max_prompt=8,
                   max_new=12, slots=4, ttft_slo_ms=args.ttft_slo_ms,
                   tpot_slo_ms=args.tpot_slo_ms, seed=args.seed, smoke=True)
    else:
        cfg = dict(n_requests=args.requests, units=args.units,
                   layers=args.layers, max_prompt=args.max_prompt,
                   max_new=args.max_new, slots=args.slots,
                   ttft_slo_ms=args.ttft_slo_ms,
                   tpot_slo_ms=args.tpot_slo_ms, seed=args.seed)
    line = run(**cfg)
    print(json.dumps(line))
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(line, f, indent=2)
            f.write("\n")
    if any(line["recompiles_after_warmup"].values()):
        print(f"FAIL: a program compiled after warmup "
              f"({line['recompiles_after_warmup']})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
