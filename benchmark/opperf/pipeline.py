"""Pipeline-schedule microbenchmark: single-stage vs GPipe vs 1F1B.

Trains the SAME stage-split transformer encoder three ways through
``SPMDTrainer`` — unpipelined single program, GPipe schedule (paper
configuration: full rematerialization), 1F1B schedule (remat off; at most
P microbatches in flight) — and reports steps/sec plus each schedule's
measured bubble fraction.

Bubble measurement (docs/pipeline_parallelism.md): on the virtual CPU
mesh every "stage" runs on the same host serially, so a wall-clock bubble
would measure the box, not the schedule.  Instead the harness CALIBRATES
per-slot costs from real timed slot programs — a jitted single-stage
microbatch forward (tf) and forward+backward (tf+tb) — and feeds the
measured tf/tb into the deterministic schedule simulator
(``parallel.simulate_schedule``).  The reported fraction is exact for the
executed slot sequence under those measured costs; recompute slots count
as bubble (overhead the schedule demanded).

Measurement is PAIRED like the other opperf harnesses: each timing round
runs one step of every mode back-to-back, median round wins, GC paused.
The harness arms ``MXNET_COMPILE_GUARD=raise`` through the trainers'
auto-arm and exits non-zero if ANY mode recompiled after warmup.

Acceptance (ISSUE 13): on >=4 stages x >=8 microbatches, 1F1B's measured
bubble < GPipe's, and 1F1B within 1.5x of the analytic (P-1)/(M+P-1)
bound.  Evidence: docs/PIPELINE_EVIDENCE_r13.json.

    python benchmark/opperf/pipeline.py [--stages 4] [--microbatches 8]
        [--json PATH] [--smoke]
"""
from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def _build_net(n_layers, units, hidden, heads, seed):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.gluon import nn

    mx.random.seed(seed)
    net = nn.HybridSequential()
    for _ in range(n_layers):
        net.add(nn.TransformerEncoderCell(units, hidden, heads))
    net.add(nn.Dense(8, flatten=False))
    net.initialize()
    net(mx.nd.zeros((2, 4, units)))
    return net


def _calibrate_slot_costs(units, hidden, heads, micro_batch, seq, iters=5):
    """Median wall of a jitted one-stage microbatch forward (tf) and
    forward+backward (tf+tb) — the per-slot costs the simulator scales."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    w1 = jnp.asarray(rng.randn(units, hidden).astype(np.float32) * 0.05)
    w2 = jnp.asarray(rng.randn(hidden, units).astype(np.float32) * 0.05)
    x = jnp.asarray(rng.randn(micro_batch, seq, units).astype(np.float32))

    def stage(w, h):
        # FFN-shaped stand-in with the microbatch's real GEMM volume
        return jnp.tanh(jnp.maximum(h @ w[0], 0.0) @ w[1]) + h

    fwd = jax.jit(stage)
    bwd = jax.jit(jax.value_and_grad(
        lambda w, h: jnp.sum(stage(w, h) ** 2)))
    fwd((w1, w2), x).block_until_ready()
    _, g = bwd((w1, w2), x)
    jax.block_until_ready(g)
    tfs, tbs = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        fwd((w1, w2), x).block_until_ready()
        tfs.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _, g = bwd((w1, w2), x)
        jax.block_until_ready(g)
        tbs.append(time.perf_counter() - t0)
    tf = _median(tfs)
    tb = max(_median(tbs) - tf, 0.25 * tf)  # backward-only slot cost
    return tf, tb


@contextlib.contextmanager
def _armed_guard():
    """Arm the steady-state compile guard for the harness WITHOUT leaking
    process state: the CI smoke imports ``run()`` in-process, and a bare
    ``os.environ.setdefault`` here would leave the whole remaining test
    suite in raise mode (armed by whichever trainer stepped last)."""
    from incubator_mxnet_tpu import profiler

    unset = "MXNET_COMPILE_GUARD" not in os.environ
    if unset:
        os.environ["MXNET_COMPILE_GUARD"] = "raise"
    try:
        yield
    finally:
        if unset:
            os.environ.pop("MXNET_COMPILE_GUARD", None)
        profiler.disarm_compile_guard()


def _guarded(fn):
    def wrapper(*args, **kwargs):
        with _armed_guard():
            return fn(*args, **kwargs)
    wrapper.__doc__ = fn.__doc__
    wrapper.__name__ = fn.__name__
    return wrapper


@_guarded
def run(n_stages=4, layers_per_stage=1, n_microbatches=8, batch=16, seq=8,
        units=32, hidden=64, heads=4, iters=8, warmup=2, repeats=3):
    import gc

    import numpy as np

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, profiler
    from incubator_mxnet_tpu.parallel import (
        SPMDTrainer, analytic_bubble_fraction, make_mesh, simulate_schedule)

    n_layers = n_stages * layers_per_stage
    rng = np.random.RandomState(1)
    x = rng.randn(batch, seq, units).astype(np.float32)
    y = rng.randint(0, 8, (batch,)).astype(np.float32)

    def loss_fn(out, label):
        return gluon.loss.SoftmaxCrossEntropyLoss()(out.mean(axis=1), label)

    def _merge(a, b):
        from incubator_mxnet_tpu.gluon import nn

        m = nn.HybridSequential()
        m.add(*list(a), *list(b))
        return m

    def make_trainer(mode):
        net = _build_net(n_layers, units, hidden, heads, seed=11)
        if mode == "single":
            return SPMDTrainer(net, loss_fn, "adam", {"learning_rate": 1e-3},
                               mesh=make_mesh())
        stages = net.split_stages([layers_per_stage] * n_stages + [1])
        # fold the classifier into the last stage
        merged = stages[:-2] + [_merge(stages[-2], stages[-1])]
        return SPMDTrainer(
            net, loss_fn, "adam", {"learning_rate": 1e-3},
            mesh=make_mesh(), stages=merged,
            pipeline={"schedule": mode, "n_microbatches": n_microbatches})

    modes = {}

    def one(mode):
        tr = modes[mode]
        t0 = time.perf_counter()
        loss = tr.step(mx.nd.array(x), mx.nd.array(y))
        loss.asnumpy()  # sync: time the whole compiled step
        return time.perf_counter() - t0

    # setup + warmup under a paused guard (the serving-warmup idiom):
    # each trainer's FIRST compile is expected; anything after this block
    # is a steady-state recompile and fails the run
    with profiler.compile_guard_paused():
        for mode in ("single", "gpipe", "1f1b"):
            modes[mode] = make_trainer(mode)
        for _ in range(max(1, warmup)):
            for m in modes:
                one(m)
    base_recompiles = profiler.counters()["recompile_steady_state"]

    rounds = max(1, iters * repeats)
    times = {m: [] for m in modes}
    gc.collect()
    gc_was_on = gc.isenabled()
    gc.disable()
    try:
        for _ in range(rounds):
            for m in modes:
                times[m].append(one(m))
    finally:
        if gc_was_on:
            gc.enable()

    recompiles = profiler.counters()["recompile_steady_state"] - base_recompiles
    medians = {m: _median(ts) for m, ts in times.items()}
    steps_per_sec = {m: 1.0 / v for m, v in medians.items()}

    tf, tb = _calibrate_slot_costs(units, hidden, heads,
                                   batch // n_microbatches, seq)
    P = n_stages  # classifier folded into the last stage
    bubbles = {}
    for mode, remat in (("gpipe", True), ("1f1b", False)):
        sim = simulate_schedule(P, n_microbatches, mode,
                                tf=tf, tb=tb, remat=remat)
        bubbles[mode] = {
            "bubble_fraction": round(sim["bubble_fraction"], 4),
            "idle_fraction": round(sim["idle_fraction"], 4),
            "remat": remat,
        }
    analytic = analytic_bubble_fraction(P, n_microbatches)

    ok = (bubbles["1f1b"]["bubble_fraction"]
          < bubbles["gpipe"]["bubble_fraction"]
          and bubbles["1f1b"]["bubble_fraction"] <= 1.5 * analytic)
    return {
        "bench": "pipeline",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "stages": P,
        "layers_per_stage": layers_per_stage,
        "microbatches": n_microbatches,
        "batch": batch,
        "seq": seq,
        "units": units,
        "rounds": rounds,
        "steps_per_sec": {m: round(v, 2) for m, v in steps_per_sec.items()},
        "median_s": medians,
        "slot_costs_ms": {"tf": round(tf * 1e3, 4), "tb": round(tb * 1e3, 4)},
        "bubble": bubbles,
        "analytic_bound": round(analytic, 4),
        "bubble_acceptance": bool(ok),
        "post_warmup_recompiles": int(recompiles),
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--layers-per-stage", type=int, default=1)
    p.add_argument("--microbatches", type=int, default=8)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=8)
    p.add_argument("--units", type=int, default=32)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--smoke", action="store_true",
                   help="tiny config + 1 round: the CI regression guard "
                        "(non-zero exit on post-warmup recompiles or a "
                        "bubble-acceptance failure)")
    p.add_argument("--json", dest="json_path", default=None, metavar="PATH")
    args = p.parse_args(argv)
    kw = dict(n_stages=args.stages, layers_per_stage=args.layers_per_stage,
              n_microbatches=args.microbatches, batch=args.batch,
              seq=args.seq, units=args.units, hidden=args.hidden,
              iters=args.iters, warmup=args.warmup, repeats=args.repeats)
    if args.smoke:
        kw.update(iters=1, repeats=1, warmup=1)
    line = run(**kw)
    print(json.dumps(line))
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(line, f, indent=2)
            f.write("\n")
    if line["post_warmup_recompiles"]:
        print(f"FAIL: {line['post_warmup_recompiles']} post-warmup "
              "recompile(s) in the scheduled step", file=sys.stderr)
        return 2
    if not line["bubble_acceptance"]:
        print("FAIL: bubble acceptance (1f1b < gpipe and within 1.5x "
              "analytic) not met", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    rc = main()
    sys.exit(rc if isinstance(rc, int) else 0)
