"""Input-pipeline microbenchmark: async sharded infeed vs serial host loop.

Measures steps/sec of a **synthetic host-heavy training loop** — the
regime MLPerf-0.6-on-TPU-v3 (PAPERS.md) names the first wall at pod
scale: every batch pays real host-side input latency (modeled as a
``time.sleep`` I/O stall plus a numpy decode pass — disk/network wait
plus CPU work, the standard record-iterator shape) before a jitted
device step can run.

* ``off``      — the serial baseline: prep → ``device_put`` →  step,
  one batch at a time on the consumer thread (what any loop without the
  pipeline pays).
* ``pipeline`` — ``io.DataPipeline``: worker-pool prep + double-buffered
  async transfer deliver device-resident mesh-sharded batches while the
  previous step computes; depth autotunes from the stall/step feedback.

Both modes run the SAME prep work, transfer, and compiled step; the only
difference is overlap.  Per mode: fresh source, ``warmup`` steps, then
``steps`` timed steps, repeated ``trials`` times — the per-mode score is
the median trial (one continuous run per trial, NOT per-step pairs: an
epoch boundary would refill the buffer and bill phantom stalls).
Consumer stalls and the autotuned depth are sampled over the TIMED
window only, so ``stalls_after_warmup == 0`` is the steady-state
acceptance evidence (ISSUE 9: >= 1.5x steps/sec AND zero post-warmup
stalls at the autotuned depth, CPU backend).

Prints ONE JSON line so CI and BENCH harvesting can grep it::

    python benchmark/opperf/input_pipeline.py [--steps 40] [--host-ms 12]
        [--json PATH] [--smoke]

``--smoke`` shrinks the run and exits non-zero if the pipeline path
recorded a consumer stall after warmup — the CI ``io`` tier's
host-starvation regression guard.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def _make_source(n, batch, feat, host_ms, seed=0):
    """Raw record stream + the host-side decode it needs: ``prep`` sleeps
    ``host_ms`` (I/O wait) then runs a numpy normalize pass (CPU work)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    raw = [rng.randn(batch, feat).astype(np.float32) for _ in range(n)]

    def source():
        return iter(raw)

    def prep(b):
        time.sleep(host_ms / 1e3)
        b = b - b.mean(axis=1, keepdims=True)
        return b / (b.std(axis=1, keepdims=True) + 1e-6)

    return source, prep


def _make_step(mesh, feat, layers, hidden, seed=1):
    """A jitted forward/backward-shaped compute: enough matmul to give
    the pipeline something to overlap with."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from incubator_mxnet_tpu.parallel import batch_pspec
    from jax.sharding import NamedSharding

    rng = np.random.RandomState(seed)
    ws = [jax.device_put(
        jnp.asarray(rng.randn(feat if i == 0 else hidden, hidden)
                    .astype(np.float32) / np.sqrt(feat)),
        NamedSharding(mesh, jax.sharding.PartitionSpec()))  # replicated
        for i in range(layers)]

    @jax.jit
    def step(x, *weights):
        h = x
        for w in weights:
            h = jnp.tanh(h @ w)
        return jnp.sum(h * h)

    sharding = NamedSharding(mesh, batch_pspec(2))
    return step, ws, sharding


def run(steps=40, warmup=8, trials=3, batch=256, feat=512, hidden=1024,
        layers=8, host_ms=12.0, num_workers=4, depth=2, max_depth=8):
    """Returns the result dict (also the tests' smoke check entry)."""
    import gc

    import jax

    from incubator_mxnet_tpu.io import DataPipeline
    from incubator_mxnet_tpu.parallel import make_mesh

    mesh = make_mesh()
    step, ws, sharding = _make_step(mesh, feat, layers, hidden)
    n_batches = (warmup + steps) * trials + 8

    def run_off():
        """Serial: prep -> device_put -> step on one thread per batch."""
        source, prep = _make_source(n_batches, batch, feat, host_ms)
        it = source()

        def one():
            b = prep(next(it))
            x = jax.device_put(b, sharding)
            return step(x, *ws)

        for _ in range(warmup):
            one().block_until_ready()
        t0 = time.perf_counter()
        for _ in range(steps):
            # per-step loss read (block) in BOTH modes: an async dispatch
            # loop would measure dispatch throughput, and its consumer
            # would drain the infeed at dispatch speed — billing phantom
            # stalls while the device is the actual bottleneck
            one().block_until_ready()
        return time.perf_counter() - t0, {}

    def run_pipe():
        source, prep = _make_source(n_batches, batch, feat, host_ms)
        pipe = DataPipeline(source, prep_fn=prep, mesh=mesh,
                            num_workers=num_workers, depth=depth,
                            max_depth=max_depth, num_parts=1, part_index=0,
                            name="io_bench")
        try:
            it = iter(pipe)
            for _ in range(warmup):
                step(next(it), *ws).block_until_ready()
            stalls0 = pipe.stats()["stalls"]
            t0 = time.perf_counter()
            for _ in range(steps):
                step(next(it), *ws).block_until_ready()
            dt = time.perf_counter() - t0
            st = pipe.stats()
            return dt, {"stalls_after_warmup": st["stalls"] - stalls0,
                        "autotuned_depth": st["depth"],
                        "depth_changes": st["depth_changes"]}
        finally:
            pipe.close()

    modes = {"off": run_off, "pipeline": run_pipe}
    times = {m: [] for m in modes}
    extras = {}
    gc.collect()
    for _ in range(trials):
        for m, fn in modes.items():
            dt, extra = fn()
            times[m].append(dt)
            if extra:
                extras = extra  # last trial's steady-state evidence
    medians = {m: _median(ts) for m, ts in times.items()}
    steps_per_sec = {m: steps / v for m, v in medians.items()}
    return {
        "bench": "input_pipeline",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "devices": len(jax.devices()),
        "steps": steps,
        "warmup": warmup,
        "trials": trials,
        "batch": batch,
        "feat": feat,
        "hidden": hidden,
        "layers": layers,
        "host_ms": host_ms,
        "num_workers": num_workers,
        "initial_depth": depth,
        "max_depth": max_depth,
        "steps_per_sec": {m: round(v, 2) for m, v in steps_per_sec.items()},
        "median_s": medians,
        "speedup_pipeline": round(
            steps_per_sec["pipeline"] / steps_per_sec["off"], 2),
        **extras,
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--warmup", type=int, default=8)
    p.add_argument("--trials", type=int, default=3,
                   help="independent runs per mode; the median trial wins")
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--feat", type=int, default=512)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--host-ms", type=float, default=12.0,
                   help="per-batch host input latency the prep stage "
                        "models (I/O wait + decode)")
    p.add_argument("--workers", type=int, default=4,
                   help="prep worker threads; per-batch producer latency "
                        "is host_ms/workers, sized well under the device "
                        "step so steady state has zero consumer stalls")
    p.add_argument("--smoke", action="store_true",
                   help="tiny run; non-zero exit if the pipeline stalled "
                        "after warmup (CI regression guard)")
    p.add_argument("--json", dest="json_path", default=None, metavar="PATH",
                   help="also write the result object to PATH — the "
                        "machine-readable record evidence harvesting reads")
    args = p.parse_args(argv)
    kw = dict(steps=args.steps, warmup=args.warmup, trials=args.trials,
              batch=args.batch, feat=args.feat, layers=args.layers,
              host_ms=args.host_ms, num_workers=args.workers)
    if args.smoke:
        kw.update(steps=12, warmup=6, trials=1, batch=128, feat=512,
                  layers=6, host_ms=6.0, num_workers=4)
    line = run(**kw)
    print(json.dumps(line))
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(line, f, indent=2)
            f.write("\n")
    if args.smoke and line.get("stalls_after_warmup", 0) > 0:
        print("input_pipeline smoke: consumer stalled after warmup "
              f"({line['stalls_after_warmup']} stalls at depth "
              f"{line.get('autotuned_depth')})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
