"""``mx.npx`` — the numpy-extension operator namespace.

Parity: [U:python/mxnet/_numpy_op_doc.py] / the deep-numpy ``npx``
namespace (1.6+): neural-network and framework ops that have no NumPy
equivalent, exposed alongside ``mx.np`` — ``npx.relu``, ``npx.softmax``,
``npx.batch_norm``, ``npx.convolution``, ``npx.pick``, ``npx.reshape_like``
etc., plus ``set_np()``/``reset_np()`` re-exported.  Names resolve through
the SAME op registry as ``mx.nd`` (one kernel set, two calling
conventions), so everything registered is reachable here.
"""
from __future__ import annotations

from .ops.registry import get_op
from .util import is_np_array, is_np_shape, reset_np, set_np  # noqa: F401

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "seed", "waitall", "save", "load"]


def seed(seed_state):
    """Parity: ``npx.seed`` — re-exported ``mx.random.seed``."""
    from . import random as _random

    _random.seed(seed_state)


def waitall():
    """Parity: ``npx.waitall`` — engine drain."""
    from . import engine as _engine

    _engine.waitall()


def save(fname, data):
    """Parity: ``npx.save`` — the ndarray container format."""
    from .ndarray.utils import save as _save

    _save(fname, data)


def load(fname):
    """Parity: ``npx.load``."""
    from .ndarray.utils import load as _load

    return _load(fname)

# npx spells several ops in snake_case where the legacy registry uses
# CamelCase (the reference keeps both registries; here it's one table
# with aliases)
_ALIASES = {
    "activation": "Activation",
    "batch_norm": "BatchNorm",
    "layer_norm": "LayerNorm",
    "group_norm": "GroupNorm",
    "instance_norm": "InstanceNorm",
    "convolution": "Convolution",
    "deconvolution": "Deconvolution",
    "pooling": "Pooling",
    "fully_connected": "FullyConnected",
    "dropout": "Dropout",
    "embedding": "Embedding",
    "leaky_relu": "LeakyReLU",
    "one_hot": "one_hot",
    "pick": "pick",
    "topk": "topk",
    "relu": "relu",
    "sigmoid": "sigmoid",
    "softmax": "softmax",
    "log_softmax": "log_softmax",
    "multibox_detection": "contrib_MultiBoxDetection",
    "multibox_prior": "contrib_MultiBoxPrior",
    "multibox_target": "contrib_MultiBoxTarget",
    "sequence_mask": "SequenceMask",
    "reshape_like": "reshape_like",
    "gamma": "gamma",
}

def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    target = _ALIASES.get(name, name)
    try:
        get_op(target)
    except KeyError:
        raise AttributeError(f"npx has no op {name!r}") from None
    # delegate to the nd wrapper: one factory, shared cache, out= support
    from . import ndarray as nd_ns

    return getattr(nd_ns, target)
