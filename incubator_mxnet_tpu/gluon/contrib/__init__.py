"""``gluon.contrib`` (parity: [U:python/mxnet/gluon/contrib/])."""
from . import estimator
from .estimator import Estimator

__all__ = ["estimator", "Estimator"]
