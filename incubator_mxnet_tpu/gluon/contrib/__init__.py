"""``gluon.contrib`` (parity: [U:python/mxnet/gluon/contrib/])."""
from . import estimator
from .estimator import Estimator
from . import nn
from . import rnn

__all__ = ["estimator", "Estimator", "nn", "rnn"]
