"""``gluon.contrib.rnn`` — experimental recurrent-cell extras.

Parity target: [U:python/mxnet/gluon/contrib/rnn/rnn_cell.py] —
``VariationalDropoutCell`` (one dropout mask shared across every time
step, Gal & Ghahramani 2016) and ``LSTMPCell`` (LSTM with a hidden-state
projection, the LSTMP of Sak et al. 2014).

The reference's Conv{1,2,3}D{RNN,LSTM,GRU}Cell family is not ported
(documented divergence: no baseline workload exercises convolutional
recurrence; the cells compose from Convolution + the RecurrentCell
contract here if needed).

TPU-native note: the variational masks are drawn once per sequence with
the framework RNG and then reused — under trace the mask is a plain
captured tensor, so every step's multiply fuses into the cell matmuls.
"""
from __future__ import annotations

from ..rnn.rnn_cell import RecurrentCell, _ModifierCell

__all__ = ["VariationalDropoutCell", "LSTMPCell", "Conv2DRNNCell",
           "Conv2DLSTMCell", "Conv2DGRUCell"]


class VariationalDropoutCell(_ModifierCell):
    """Apply fixed dropout masks to inputs/states/outputs across all time
    steps of a sequence (parity: ``contrib.rnn.VariationalDropoutCell``).

    Masks are (re)drawn on the first call after ``reset()`` — one mask per
    role, shared by every subsequent step, so the same units are dropped
    for the whole sequence."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0, drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    @staticmethod
    def _mask(p, like):
        from ... import ndarray as nd

        # Dropout of ones == scaled keep-mask (values 0 or 1/(1-p)); reusing
        # it IS the variational trick.
        return nd.Dropout(nd.ones_like(like), p=p, training=True)

    def __call__(self, inputs, states):
        from ... import autograd

        if not autograd.is_training():
            return self.base_cell(inputs, states)
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(self.drop_inputs, inputs)
            inputs = inputs * self._input_mask
        if self.drop_states:
            if self._state_mask is None:
                self._state_mask = self._mask(self.drop_states, states[0])
            states = [states[0] * self._state_mask] + list(states[1:])
        output, next_states = self.base_cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(self.drop_outputs, output)
            output = output * self._output_mask
        return output, next_states

    def _alias(self):
        return "vardrop"

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError  # stateful masks: dispatch is in __call__

    def __repr__(self):
        return (f"VariationalDropoutCell(p_in={self.drop_inputs}, "
                f"p_state={self.drop_states}, p_out={self.drop_outputs})")


class LSTMPCell(RecurrentCell):
    """LSTM cell with hidden-state projection (parity:
    ``contrib.rnn.LSTMPCell``).  Gate math matches :class:`LSTMCell`
    (order [i, f, g, o]); the output hidden state is ``r = W_r h`` with
    ``W_r`` of shape (projection_size, hidden_size), shrinking the
    recurrent matmul to (4h × p) — the Sak et al. LSTMP."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [
            {"shape": (batch_size, self._projection_size), "__layout__": "NC"},
            {"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
        ]

    def _alias(self):
        return "lstmp"

    def _shape_inference(self, x, *args):
        self.i2h_weight._finish_deferred_init((4 * self._hidden_size, x.shape[-1]))
        self.h2h_weight._finish_deferred_init((4 * self._hidden_size, self._projection_size))
        self.h2r_weight._finish_deferred_init((self._projection_size, self._hidden_size))
        self.i2h_bias._finish_deferred_init((4 * self._hidden_size,))
        self.h2h_bias._finish_deferred_init((4 * self._hidden_size,))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight, h2r_weight,
                       i2h_bias, h2h_bias):
        prev_r, prev_c = states
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(prev_r, h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = F.tanh(slices[2])
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * prev_c + in_gate * in_transform
        hidden = out_gate * F.tanh(next_c)
        next_r = F.FullyConnected(hidden, h2r_weight, no_bias=True,
                                  num_hidden=self._projection_size)
        return next_r, [next_r, next_c]

    def __repr__(self):
        return (f"LSTMPCell({self._hidden_size} -> {self._projection_size})")


class _ConvRNNBase(RecurrentCell):
    """Shared machinery for the convolutional recurrent cells (parity:
    [U:python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py]).  2-D variants
    (the Conv2D*Cell family): inputs [B, C, H, W].  Upstream conventions:
    ``i2h_pad`` defaults to VALID (0, 0) padding — the state's H/W is the
    i2h conv's output size — while the h2h conv is auto-'same'-padded over
    the state (odd h2h kernels required, as upstream's auto-pad assumes)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 n_gates, i2h_pad=(0, 0), activation="tanh", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)  # (C, H, W)
        self._hc = hidden_channels
        self._gates = n_gates
        self._activation = activation

        def _pair(k):
            return (k, k) if isinstance(k, int) else tuple(k)

        self._i2h_kernel = _pair(i2h_kernel)
        self._h2h_kernel = _pair(h2h_kernel)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise ValueError(
                    f"Conv cells need odd h2h kernels for same-padding, got {k}")
        self._i2h_pad = _pair(i2h_pad)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        # state spatial dims = i2h conv output dims (upstream convention)
        self._state_hw = tuple(
            d + 2 * p - k + 1 for d, p, k in zip(
                self._input_shape[1:], self._i2h_pad, self._i2h_kernel))
        c = self._input_shape[0]
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(n_gates * hidden_channels, c) + self._i2h_kernel)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(n_gates * hidden_channels, hidden_channels) + self._h2h_kernel)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(n_gates * hidden_channels,), init="zeros")
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(n_gates * hidden_channels,), init="zeros")

    def state_info(self, batch_size=0):
        h, w = self._state_hw
        return [{"shape": (batch_size, self._hc, h, w), "__layout__": "NCHW"}
                for _ in range(self._n_states)]

    def _convs(self, F, inputs, state, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=self._gates * self._hc)
        h2h = F.Convolution(state, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=self._gates * self._hc)
        return i2h, h2h


class Conv2DRNNCell(_ConvRNNBase):
    """Convolutional vanilla RNN cell (parity: ``contrib.rnn.Conv2DRNNCell``)."""

    _n_states = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_pad=(0, 0), activation="tanh",
                 prefix=None, params=None):
        super().__init__(input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                         n_gates=1, i2h_pad=i2h_pad,
                         activation=activation, prefix=prefix, params=params)

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._convs(F, inputs, states[0], i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        out = self._get_activation(F, i2h + h2h, self._activation)
        return out, [out]


class Conv2DLSTMCell(_ConvRNNBase):
    """ConvLSTM (Shi et al. 2015; parity: ``contrib.rnn.Conv2DLSTMCell``);
    gate order [i, f, g, o] like :class:`LSTMCell`."""

    _n_states = 2

    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_pad=(0, 0), activation="tanh",
                 prefix=None, params=None):
        super().__init__(input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                         n_gates=4, i2h_pad=i2h_pad,
                         activation=activation, prefix=prefix, params=params)

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h, prev_c = states
        i2h, h2h = self._convs(F, inputs, prev_h, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        gates = i2h + h2h
        sl = F.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(sl[0])
        f = F.sigmoid(sl[1])
        g = self._get_activation(F, sl[2], self._activation)
        o = F.sigmoid(sl[3])
        next_c = f * prev_c + i * g
        next_h = o * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class Conv2DGRUCell(_ConvRNNBase):
    """ConvGRU (parity: ``contrib.rnn.Conv2DGRUCell``); gate order
    [r, z, n] like :class:`GRUCell`."""

    _n_states = 1

    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_pad=(0, 0), activation="tanh",
                 prefix=None, params=None):
        super().__init__(input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                         n_gates=3, i2h_pad=i2h_pad,
                         activation=activation, prefix=prefix, params=params)

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h, h2h = self._convs(F, inputs, prev_h, i2h_weight, h2h_weight,
                               i2h_bias, h2h_bias)
        i_sl = F.split(i2h, num_outputs=3, axis=1)
        h_sl = F.split(h2h, num_outputs=3, axis=1)
        r = F.sigmoid(i_sl[0] + h_sl[0])
        z = F.sigmoid(i_sl[1] + h_sl[1])
        n = self._get_activation(F, i_sl[2] + r * h_sl[2], self._activation)
        next_h = (1 - z) * n + z * prev_h
        return next_h, [next_h]
