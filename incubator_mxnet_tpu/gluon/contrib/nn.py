"""``gluon.contrib.nn`` — experimental layer extras.

Parity target: [U:python/mxnet/gluon/contrib/nn/basic_layers.py] —
Concurrent, HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm,
PixelShuffle1D/2D/3D.

TPU-native notes:
* ``SyncBatchNorm``: the reference implements cross-GPU stat sync with a
  dedicated NCCL kernel ([U:src/operator/contrib/sync_batch_norm.cc]).
  Under this framework's SPMD design the batch axis is *sharded over the
  mesh inside one jitted program*, so the plain BatchNorm reduction over
  the batch axis is already a global reduction — XLA inserts the
  cross-device collective automatically.  SyncBatchNorm is therefore
  BatchNorm (the subsumption is the feature); ``num_devices`` is accepted
  and ignored.
* ``SparseEmbedding``: the reference stores the gradient row_sparse so the
  PS only moves touched rows.  Here the dense-storage/lazy-update
  equivalent is ``grad_stype='row_sparse'`` (optimizer applies
  ``*_lazy_update`` row-wise semantics; see ndarray/sparse.py divergence
  note).
* ``PixelShuffle*D``: pure reshape/transpose — XLA fuses them into
  neighbouring ops; shapes are static under trace so ``x.shape`` is free.
"""
from __future__ import annotations

from ..nn.basic_layers import (
    BatchNorm,
    Concatenate,
    Embedding,
    HybridConcatenate,
    Identity,
)
from ..block import HybridBlock

__all__ = [
    "Concurrent",
    "HybridConcurrent",
    "Identity",
    "SparseEmbedding",
    "SyncBatchNorm",
    "PixelShuffle1D",
    "PixelShuffle2D",
    "PixelShuffle3D",
]


class Concurrent(Concatenate):
    """Run children on the same input, concat outputs (parity:
    ``contrib.nn.Concurrent``; the 2.x name is Concatenate)."""


class HybridConcurrent(HybridConcatenate):
    """Hybridizable :class:`Concurrent` (parity:
    ``contrib.nn.HybridConcurrent``)."""


class SparseEmbedding(Embedding):
    """Embedding whose gradient is row-sparse (parity:
    ``contrib.nn.SparseEmbedding``).  Storage is dense on TPU; the
    row-sparse contract survives as lazy per-row optimizer updates."""

    def __init__(self, input_dim, output_dim, dtype="float32", weight_initializer=None,
                 prefix=None, params=None):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, prefix=prefix, params=params)


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (parity:
    ``contrib.nn.SyncBatchNorm``).  See module docstring: under SPMD the
    batch-axis reduction is already global, so this IS BatchNorm."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9, epsilon=1e-5,
                 center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros", running_variance_initializer="ones",
                 **kwargs):
        del num_devices  # subsumed: stats reduce over the full sharded batch
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)


class PixelShuffle1D(HybridBlock):
    """Rearrange ``(N, C*f, W)`` → ``(N, C, W*f)`` (parity:
    ``contrib.nn.PixelShuffle1D``)."""

    def __init__(self, factor, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._factor = int(factor)

    def hybrid_forward(self, F, x):
        f = self._factor
        n, cf, w = x.shape
        x = F.reshape(x, shape=(n, cf // f, f, w))
        x = F.transpose(x, axes=(0, 1, 3, 2))        # (N, C, W, f)
        return F.reshape(x, shape=(n, cf // f, w * f))

    def __repr__(self):
        return f"{type(self).__name__}({self._factor})"


class PixelShuffle2D(HybridBlock):
    """Rearrange ``(N, C*f1*f2, H, W)`` → ``(N, C, H*f1, W*f2)`` (parity:
    ``contrib.nn.PixelShuffle2D`` — the sub-pixel conv upsampler)."""

    def __init__(self, factor, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        try:
            f1, f2 = factor
        except TypeError:
            f1 = f2 = factor
        self._factors = (int(f1), int(f2))

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        n, c, h, w = x.shape
        c //= f1 * f2
        x = F.reshape(x, shape=(n, c, f1, f2, h, w))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))  # (N, C, H, f1, W, f2)
        return F.reshape(x, shape=(n, c, h * f1, w * f2))

    def __repr__(self):
        return f"{type(self).__name__}({self._factors})"


class PixelShuffle3D(HybridBlock):
    """Rearrange ``(N, C*f1*f2*f3, D, H, W)`` → ``(N, C, D*f1, H*f2, W*f3)``
    (parity: ``contrib.nn.PixelShuffle3D``)."""

    def __init__(self, factor, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        try:
            f1, f2, f3 = factor
        except TypeError:
            f1 = f2 = f3 = factor
        self._factors = (int(f1), int(f2), int(f3))

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors
        n, c, d, h, w = x.shape
        c //= f1 * f2 * f3
        x = F.reshape(x, shape=(n, c, f1, f2, f3, d, h, w))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return F.reshape(x, shape=(n, c, d * f1, h * f2, w * f3))

    def __repr__(self):
        return f"{type(self).__name__}({self._factors})"
