"""Keras-style Estimator fit loop
(parity: [U:python/mxnet/gluon/contrib/estimator/]).

``Estimator.fit(train_data, epochs)`` with event handlers: checkpointing,
logging, early stopping — same handler hook points as the reference
(train_begin/epoch_begin/batch_begin/batch_end/epoch_end/train_end).
"""
from __future__ import annotations

import logging
import time

from ... import metric as metric_mod
from .. import loss as loss_mod
from ..trainer import Trainer

__all__ = [
    "Estimator",
    "MetricHandler",
    "ValidationHandler",
    "StoppingHandler",
    "TrainBegin",
    "TrainEnd",
    "EpochBegin",
    "EpochEnd",
    "BatchBegin",
    "BatchEnd",
    "CheckpointHandler",
    "EarlyStoppingHandler",
    "LoggingHandler",
]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    def __init__(self, log_interval="epoch"):
        self.log_interval = log_interval
        self._batches = 0

    def train_begin(self, estimator, *args, **kwargs):
        self._start = time.time()
        logging.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        logging.info("Training finished in %.1fs", time.time() - self._start)

    def epoch_end(self, estimator, *args, **kwargs):
        msgs = []
        for m in estimator.train_metrics:
            name, value = m.get()
            msgs.append(f"{name}={value:.6f}")
        logging.info("Epoch %d: %s", estimator.current_epoch, " ".join(msgs))

    def batch_end(self, estimator, *args, **kwargs):
        self._batches += 1
        if self.log_interval != "epoch" and self._batches % self.log_interval == 0:
            msgs = []
            for m in estimator.train_metrics:
                name, value = m.get()
                msgs.append(f"{name}={value:.6f}")
            logging.info("Batch %d: %s", self._batches, " ".join(msgs))


class CheckpointHandler(EpochEnd):
    def __init__(self, model_dir, model_prefix="model", save_best=False, monitor=None):
        self.model_dir = model_dir
        self.model_prefix = model_prefix

    def epoch_end(self, estimator, *args, **kwargs):
        import os

        os.makedirs(self.model_dir, exist_ok=True)
        path = os.path.join(self.model_dir, f"{self.model_prefix}-epoch{estimator.current_epoch}.params")
        estimator.net.save_parameters(path)


class EarlyStoppingHandler(EpochEnd):
    def __init__(self, monitor, min_delta=0, patience=0, mode="auto"):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.wait = 0
        self.best = None
        self.mode = mode

    def epoch_end(self, estimator, *args, **kwargs):
        name, value = self.monitor.get()
        decreasing = "loss" in name or self.mode == "min"
        improved = (
            self.best is None
            or (decreasing and value < self.best - self.min_delta)
            or (not decreasing and value > self.best + self.min_delta)
        )
        if improved:
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                estimator.stop_training = True
                logging.info("Early stopping: %s did not improve for %d epochs", name, self.wait)


class MetricHandler(EpochBegin, BatchEnd):
    """Owns an INDEPENDENT train-metric list (parity:
    estimator.MetricHandler): resets at epoch begin, updates from the
    batch the estimator just processed (``estimator._last_batch``)."""

    def __init__(self, train_metrics):
        self.train_metrics = _as_metrics(train_metrics)

    def epoch_begin(self, estimator):
        for m in self.train_metrics:
            m.reset()

    def batch_end(self, estimator):
        label, pred, loss = estimator._last_batch
        for m in self.train_metrics:
            if isinstance(m, metric_mod.Loss):
                m.update([], [loss])
            else:
                m.update([label], [pred])


class ValidationHandler(EpochEnd):
    """Runs validation every ``epoch_period`` epochs (parity:
    estimator.ValidationHandler)."""

    def __init__(self, val_data, eval_fn=None, epoch_period=1,
                 val_metrics=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = max(1, int(epoch_period))
        self.val_metrics = _as_metrics(val_metrics)

    def epoch_end(self, estimator):
        if (estimator.current_epoch + 1) % self.epoch_period:
            return
        if self.eval_fn is not None:
            self.eval_fn(self.val_data)
        else:
            estimator.evaluate(self.val_data, self.val_metrics)


class StoppingHandler(TrainBegin, EpochEnd, BatchEnd):
    """Stop at ``max_epoch`` epochs or ``max_batch`` total batches
    (parity: estimator.StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self._batches = 0

    def train_begin(self, estimator):
        self._batches = 0

    def batch_end(self, estimator):
        self._batches += 1
        if self.max_batch is not None and self._batches >= self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator):
        if (self.max_epoch is not None
                and estimator.current_epoch + 1 >= self.max_epoch):
            estimator.stop_training = True


class Estimator:
    """Parity: ``gluon.contrib.estimator.Estimator``."""

    def __init__(self, net, loss=None, train_metrics=None, val_metrics=None, trainer=None, context=None):
        self.net = net
        self.loss = loss or loss_mod.SoftmaxCrossEntropyLoss()
        self.train_metrics = _as_metrics(train_metrics) or [metric_mod.Accuracy()]
        self.val_metrics = _as_metrics(val_metrics) or [metric_mod.Accuracy()]
        self.trainer = trainer or Trainer(net.collect_params(), "adam")
        self.stop_training = False
        self.current_epoch = 0

    def evaluate(self, val_data, val_metrics=None):
        from ... import autograd

        metrics = _as_metrics(val_metrics) or self.val_metrics
        for m in metrics:
            m.reset()
        for batch in val_data:
            data, label = batch[0], batch[1]
            with autograd.predict_mode():
                pred = self.net(data)
            for m in metrics:
                m.update([label], [pred])
        return metrics

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None, batches=None):
        from ... import autograd

        handlers = event_handlers or [LoggingHandler()]
        self.stop_training = False  # a reused Estimator/handler starts clean
        for h in handlers:
            if isinstance(h, TrainBegin):
                h.train_begin(self)
        n_batches = 0
        for epoch in range(epochs):
            if self.stop_training:
                break
            self.current_epoch = epoch
            for m in self.train_metrics:
                m.reset()
            for h in handlers:
                if isinstance(h, EpochBegin):
                    h.epoch_begin(self)
            for batch in train_data:
                data, label = batch[0], batch[1]
                for h in handlers:
                    if isinstance(h, BatchBegin):
                        h.batch_begin(self)
                with autograd.record():
                    pred = self.net(data)
                    loss = self.loss(pred, label)
                loss.backward()
                self.trainer.step(data.shape[0])
                self._last_batch = (label, pred, loss)
                for m in self.train_metrics:
                    if isinstance(m, metric_mod.Loss):
                        m.update([], [loss])
                    else:
                        m.update([label], [pred])
                for h in handlers:
                    if isinstance(h, BatchEnd):
                        h.batch_end(self)
                n_batches += 1
                if batches is not None and n_batches >= batches:
                    break
                if self.stop_training:
                    break
            if self.stop_training:
                # mid-epoch stop (max_batch): no end-of-epoch validation,
                # checkpointing or logging over a truncated epoch
                break
            if val_data is not None:
                self.evaluate(val_data)
            for h in handlers:
                if isinstance(h, EpochEnd):
                    h.epoch_end(self)
        for h in handlers:
            if isinstance(h, TrainEnd):
                h.train_end(self)


def _as_metrics(m):
    if m is None:
        return None
    if isinstance(m, (list, tuple)):
        return list(m)
    return [m]
