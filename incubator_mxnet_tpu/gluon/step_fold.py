"""One compiled program per training step — the Gluon step fold.

A classic Gluon training step is several host dispatches: the hybridized
forward (CachedOp jit), the autograd backward (one jitted vjp per tape
node), the bucketed ``allreduce_grads`` pushpulls, and one fused
``group_apply`` per optimizer group.  ``SPMDTrainer`` has lowered its whole
step to ONE donated-buffer program since PR 3 — this module brings the same
whole-program compilation to the imperative ``gluon.Trainer`` contract
(the Julia-to-TPU full-compilation result in PAPERS.md: XLA's fusion pays
off at program granularity, not op granularity):

* :class:`StepProgram` (``Trainer.fold_step(loss_fn)``) traces Block
  forward + loss + backward + the fused optimizer tail into one jitted,
  donated-buffer program per (batch signature, optimizer-group-set).  The
  capture enters the SAME ``gluon.block.trace_scope`` ceremony as the
  CachedOp build and the SPMDTrainer step builders (the unification of the
  repo's partial graph capturers), and the optimizer tail composes the
  SAME per-tensor step adapters ``optimizer/fused.py`` groups with
  (``plan_groups``), so folded numerics cannot drift from the unfused
  kernels they inline.  Weights, optimizer state (and under error
  feedback, compression residuals) are donated; the fresh outputs are
  swapped back into the live ``Parameter``/state NDArrays, so folded and
  unfused steps stay interchangeable mid-training and
  ``save_states``/``load_states`` keep working.

* Multi-process runs against a ``dist_sync`` store fold the gradient
  exchange IN-PROGRAM: forward/backward runs per worker shard inside one
  ``shard_map`` over the kvstore's worker mesh, and each size-capped
  gradient bucket becomes an explicit ``psum`` (or the PR 14 codec's
  quantize → integer psum → dequantize, ``comm.traced_allreduce``) graph
  node that depends only on its own bucket's grads — XLA's scheduler is
  free to start a bucket's collective while the remaining backward still
  computes, which is where MLPerf-on-TPU-pods finds most pod-scale
  headroom.

* :func:`fold_update` is the ``MXNET_STEP_FOLD=1`` fast path inside
  ``Trainer.step``: the whole optimizer tail — every fused group — folds
  into ONE donated jitted dispatch instead of one ``group_apply`` per
  group (forward/backward already ran eagerly by the time ``step()`` is
  called, so this is the part of the step ``Trainer.step`` can fold).

* The K-step fold (``Trainer.fold_steps(loss_fn, k)``, K from
  ``MXNET_STEP_FOLD_K``) wraps the SAME per-step body in a ``lax.scan``
  over K pre-staged batches: params, optimizer state (and under error
  feedback, compression residuals) ride the loop carry, per-step
  lr/wd/t and PRNG keys ride as stacked ``[K]`` device arrays, and the
  K per-step losses accumulate in-program — host dispatch cost drops to
  1/K with numerics exactly equal to K unfolded steps.  The input side
  folds too: ``pipeline.stage_window(k)`` hands the program a
  device-resident ``[K, ...]`` stacked batch window the transfer thread
  built ahead of the scan.  ``K=1`` IS the PR 15 program (same site,
  same signature).  Checkpoints land on K boundaries only
  (``save_states`` refuses mid-window; the window cursor rides the
  snapshot payload).  Compile sites ``gluon.step_fold_k`` and (for
  :class:`EvalProgram`, ``Trainer.fold_eval``) ``gluon.fold_eval``.

Escape hatches (docs/step_fold.md): ``MXNET_STEP_FOLD=0`` disables both
entries, a block opts out with ``block._step_fold_opt_out = True``, and
any capture failure or unsupported optimizer falls back to the eager
record/backward/step path (counted in ``step_fold_fallback``), never
erroring.  ``NaiveEngine`` bypasses folding entirely.
"""
from __future__ import annotations

import os as _os
import warnings as _warnings
from time import perf_counter as _perf

import jax
import jax.numpy as jnp
import numpy as _np

from .. import autograd
from .. import engine as _engine
from .. import profiler as _profiler
from ..ndarray.ndarray import NDArray
from ..optimizer import fused as _fused
from ..optimizer.optimizer import _swap
from ..random import get_key
from .block import trace_scope

__all__ = ["StepProgram", "EvalProgram", "fold_update", "fold_enabled",
           "step_fast_path", "fold_k", "host_dispatch_total",
           "DISPATCH_COUNTERS", "FALLBACK_LABELS"]


def fold_enabled():
    """Whether ``Trainer.fold_step`` folds (default yes;
    ``MXNET_STEP_FOLD=0`` is the escape hatch — the returned StepProgram
    still works, running the eager record/backward/step path)."""
    return _os.environ.get("MXNET_STEP_FOLD", "1") != "0"


def fold_k(default=1):
    """The configured fold width K (``MXNET_STEP_FOLD_K``, default 1):
    how many logical training steps ``Trainer.fold_steps`` /
    ``Trainer.fold_eval`` fold into one compiled dispatch when the
    caller does not pass ``k`` explicitly.  K=1 reduces exactly to the
    single-step fold."""
    try:
        k = int(_os.environ.get("MXNET_STEP_FOLD_K", "") or default)
    except ValueError:
        k = default
    return max(1, k)


# Canonical per-reason labels for the ``step_fold_fallback`` counter
# (``profiler.incr_labeled`` — surfaced in ``dumps()``, the metrics
# snapshot and the Prometheus counters): one scrape says WHY a fold ran
# eager, not just how often.
FALLBACK_LABELS = ("env-off", "naive-engine", "block-opt-out",
                   "grad-req-add", "unsupported-optimizer", "async-PS",
                   "capture-failure", "deferred-init")


def step_fast_path():
    """Whether ``Trainer.step`` routes its optimizer tail through
    :func:`fold_update` (opt-in: ``MXNET_STEP_FOLD=1`` exactly — the
    default keeps the established per-group ``group_apply`` path)."""
    return _os.environ.get("MXNET_STEP_FOLD") == "1"


# Counters that each tick once per HOST-ISSUED device dispatch.  The
# steady-state folded step must move this total by exactly 1 (its own
# ``step_fold_call``) — the opperf harness and tests assert the delta.
DISPATCH_COUNTERS = (
    "dispatch_cache_hit", "dispatch_cache_miss", "dispatch_cache_bypass",
    "dispatch_cache_fallback", "bulk_flush", "fused_step_call",
    "allreduce_bucket", "step_fold_call", "fold_eval_call",
)


def host_dispatch_total(counters=None):
    """Sum of the per-dispatch counters (see ``DISPATCH_COUNTERS``)."""
    c = counters if counters is not None else _profiler.counters()
    return sum(c[k] for k in DISPATCH_COUNTERS)


# concrete jax array of an NDArray, flushing a pending bulk deferred in
# place — THE shared flush-before-donation rule (optimizer/fused.py)
_raw = _fused._concrete


def _opted_out(block):
    """Per-block opt-out: ``block._step_fold_opt_out = True`` anywhere in
    the tree keeps the fold off (docs/step_fold.md)."""
    if block is None:
        return False
    if getattr(block, "_step_fold_opt_out", False):
        return True
    return any(_opted_out(c) for c in getattr(block, "_children", {}).values())


class StepProgram:
    """The folded training step for one ``(Trainer, loss_fn)`` pair.

    ``loss_fn(*batch_ndarrays) -> loss NDArray`` computes the loss from
    the batch (calling the Block(s) whose Parameters the Trainer owns);
    calling the program runs forward + backward + allreduce + optimizer
    update as ONE compiled dispatch and returns the loss NDArray.

    Built via ``Trainer.fold_step(loss_fn)``; see docs/step_fold.md for
    the capture contract (what may run inside ``loss_fn``) and the escape
    hatches.
    """

    def __init__(self, trainer, loss_fn, block=None, keep_grads=False,
                 k=None, donate_window=False):
        self._trainer = trainer
        self._loss_fn = loss_fn
        self._block = block
        self._keep_grads = bool(keep_grads)
        self._k = max(1, int(k if k is not None else fold_k()))
        self._donate_window = bool(donate_window)
        self._cache = {}            # (batch sig, group sig, ...) -> entry
        self._fallback_reason = None
        self._fallback_label = None
        self._warned = False
        self._guard_armed = False
        self._dist = None           # _DistRegisters when folding over a mesh
        self._logical_steps = 0     # logical training steps run (any path)
        self._window_pos = 0        # steps since the last window boundary:
                                    # always 0 for K=1 and after any whole-
                                    # window dispatch; step_one moves it —
                                    # save_states refuses while it is != 0
        if not fold_enabled():
            self._fallback_reason = "MXNET_STEP_FOLD=0"
            self._fallback_label = "env-off"
        elif _engine.is_naive():
            self._fallback_reason = "NaiveEngine"
            self._fallback_label = "naive-engine"
        elif _opted_out(block):
            self._fallback_reason = "block opt-out (_step_fold_opt_out)"
            self._fallback_label = "block-opt-out"

    # -- public surface --------------------------------------------------
    @property
    def folded(self):
        """False once the program has fallen back to the eager path for
        good (reason in ``fallback_reason``)."""
        return self._fallback_reason is None

    @property
    def fallback_reason(self):
        return self._fallback_reason

    @property
    def k(self):
        """Configured fold width: logical steps per full window."""
        return self._k

    @property
    def logical_steps(self):
        """Logical training steps this program has run (folded or eager)."""
        return self._logical_steps

    @property
    def window_pos(self):
        """Logical steps since the last window boundary (``0 <= pos < k``).
        Whole-window dispatches — full or epoch-tail — always land back on
        a boundary; only the ``step_one`` escape moves the cursor.  The
        K-boundary checkpoint rule: ``Trainer.save_states`` refuses while
        this is non-zero (docs/step_fold.md#multi-step-fold)."""
        return self._window_pos

    def __call__(self, *batch, batch_size=None):
        tr = self._trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        nds = [b if isinstance(b, NDArray) else NDArray(jnp.asarray(b))
               for b in batch]
        if self._k > 1:
            return self._window_call(nds, batch_size)
        if batch_size is None:
            batch_size = nds[0].shape[0]
        out = self._call_one(nds, batch_size)
        self._logical_steps += 1
        return out

    def _call_one(self, nds, batch_size):
        tr = self._trainer
        if self._fallback_reason is not None:
            return self._eager_step(nds, batch_size)
        # deferred-init params can only materialize through a real eager
        # forward — run ONE unfused step, then fold from the next call
        # (mirrors HybridBlock.__call__'s DeferredInit retry)
        if any(p._deferred_init is not None or p._data is None
               for p in tr._params):
            return self._eager_step(nds, batch_size)
        # the folded program embeds the gradient collectives — arm the
        # collective watchdog around the whole dispatch (import at call
        # time: gluon must not import the parallel package at load)
        from ..parallel import elastic as _elastic
        _elastic.watchdog_arm("step_fold.call")
        try:
            return self._folded_step(nds, batch_size)
        finally:
            _elastic.watchdog_disarm()

    def _window_call(self, nds, batch_size):
        """One K-window dispatch: ``nds`` are ``[k_window, batch, ...]``
        stacked arrays (``pipeline.stage_window(k)`` hands them over
        device-resident; an epoch tail may carry ``k_window < k``).  Any
        whole-window dispatch lands the program back on a window
        boundary.  Returns the ``[k_window, ...]`` per-step losses."""
        tr = self._trainer
        if nds[0].ndim < 2:
            raise ValueError(
                f"fold_steps(k={self._k}) expects stacked [k, batch, ...] "
                "windows (pipeline.stage_window(k)); got shape "
                f"{tuple(nds[0].shape)} — use step_one() for a single "
                "unstacked batch")
        kw = int(nds[0].shape[0])
        if any(int(nd.shape[0]) != kw for nd in nds):
            raise ValueError(
                "window leading dims disagree: "
                f"{[tuple(nd.shape) for nd in nds]}")
        if batch_size is None:
            batch_size = nds[0].shape[1]
        if self._fallback_reason is not None or any(
                p._deferred_init is not None or p._data is None
                for p in tr._params):
            out = self._eager_window(nds, batch_size)
        else:
            from ..parallel import elastic as _elastic
            _elastic.watchdog_arm("step_fold.call")
            try:
                out = self._folded_step(nds, batch_size, k_window=kw)
            finally:
                _elastic.watchdog_disarm()
        self._logical_steps += kw
        self._window_pos = 0
        return out

    def step_one(self, *batch, batch_size=None):
        """Single-logical-step escape on a K>1 program: runs ONE step as
        a ``k_window=1`` window (its own compiled entry, registered as a
        declared warmup — never a steady-state guard violation) and moves
        the window cursor off the K boundary; ``Trainer.save_states``
        refuses until further ``step_one`` calls complete a whole window.
        On a K=1 program this is exactly ``__call__``."""
        if self._k == 1:
            return self(*batch, batch_size=batch_size)
        nds = [b if isinstance(b, NDArray) else NDArray(jnp.asarray(b))
               for b in batch]
        if batch_size is None:
            batch_size = nds[0].shape[0]
        window = [NDArray(nd._data[None]) for nd in nds]
        pos = self._window_pos
        out = self(*window, batch_size=batch_size)
        self._window_pos = (pos + 1) % self._k
        return NDArray(out._data[0])

    def sync(self):
        """Write fold-held state back into the live Parameters/Trainer
        (no-op for the local fold, which swaps buffers every step; the
        multi-process fold keeps donated global registers and syncs
        lazily — ``Trainer.save_states`` calls this)."""
        if self._dist is not None:
            self._dist.sync_out()

    def invalidate(self):
        """Drop compiled programs and (dist) registers so the next call
        re-stages from the live Parameters — required after
        ``load_states`` or direct ``set_data`` on a multi-process fold."""
        self._cache.clear()
        self._dist = None

    # -- fallback path ---------------------------------------------------
    def _note_fallback(self, reason, label="capture-failure"):
        if self._dist is not None:
            # the registers hold the live trajectory; the eager path reads
            # the Parameters — refresh them before switching over
            self._dist.sync_out()
            self._dist = None
        self._fallback_reason = reason
        self._fallback_label = label
        if not self._warned:
            self._warned = True
            _warnings.warn(
                f"step fold disabled ({reason}); running the eager "
                "record/backward/step path instead — see docs/step_fold.md",
                UserWarning, stacklevel=3)

    def _run_eager(self, nds, batch_size):
        """Route a fallback to the right eager shape: stacked windows for
        a K>1 program, the single-batch path otherwise.  ``nds`` must
        match the shape the caller was dispatched with."""
        if self._k > 1 and nds and nds[0].ndim >= 2:
            return self._eager_window(nds, batch_size)
        return self._eager_step(nds, batch_size)

    def _eager_step(self, nds, batch_size):
        """The unfused reference path: record forward+loss, tape backward,
        ``Trainer.step`` (allreduce + fused optimizer groups).  EVERY
        eager execution through the program counts in
        ``step_fold_fallback`` (with a per-reason label) — the counter
        quantifies how much of a run escaped the fold, not how many
        distinct reasons there were."""
        _profiler.incr_labeled("step_fold_fallback",
                               self._fallback_label or "deferred-init")
        with autograd.record():
            loss = self._loss_fn(*nds)
        autograd.backward([loss])
        self._trainer.step(batch_size)
        return loss

    def _eager_window(self, nds, batch_size):
        """Eager reference for a stacked ``[k_window, ...]`` window: one
        unfused step per row, losses restacked to the folded program's
        ``[k_window, ...]`` output shape."""
        losses = []
        for j in range(int(nds[0].shape[0])):
            row = [NDArray(nd._data[j]) for nd in nds]
            losses.append(self._eager_step(row, batch_size))
        return NDArray(jnp.stack([l._data for l in losses]))

    # -- the folded step -------------------------------------------------
    def _folded_step(self, nds, batch_size, k_window=None):
        tr = self._trainer
        opt = tr._optimizer
        kw = k_window if self._k > 1 else None
        tr._check_and_rescale_grad(tr._scale / batch_size)
        touched = []
        for i, p in enumerate(tr._params):
            if p.grad_req == "null" or p._data is None:
                continue
            if p._data._grad is None:
                raise UserWarning(
                    f"Gradient of Parameter `{p.name}` has no grad buffer")
            if p.grad_req != "write":
                # grad_req='add' accumulates across backwards — a folded
                # step would overwrite the running sum
                self._note_fallback(f"{p.name} has grad_req="
                                    f"{p.grad_req!r} (fold needs 'write')",
                                    label="grad-req-add")
                return self._run_eager(nds, batch_size)
            if i not in tr._states:
                tr._states[i] = opt.create_state_multi_precision(i, p.data())
            touched.append((i, p))
        tr._account_memory(touched)
        groups, rest = _fused.plan_groups(
            opt, [(i, p.data(), None) for i, p in touched], tr._states)
        if rest or not groups:
            names = [tr._params[i].name for i, _, _ in rest][:3]
            self._note_fallback(
                f"no fused kernels for {type(opt).__name__} on "
                f"{names or 'these params'} (lazy/sparse or unsupported)",
                label="unsupported-optimizer")
            return self._run_eager(nds, batch_size)

        # kvstore routing: a dist store either folds in-program (SPMD
        # collectives available) or forces the eager path (async PS —
        # server-side optimizer, host TCP wire)
        kv = tr._kvstore
        dist = kv is not None and kv.num_workers > 1
        if dist and not (hasattr(kv, "_worker_mesh")
                         and kv.supports_grad_bucketing()):
            self._note_fallback(
                f"kvstore {getattr(kv, 'type', kv)!r} cannot fold "
                "(server-side optimizer / async tier)", label="async-PS")
            return self._run_eager(nds, batch_size)

        tpos_of = {i: t for t, (i, _) in enumerate(touched)}
        group_sig = tuple(
            (step.__name__, dt, cx,
             tuple(i for i, _, _, _ in members),
             tuple(len(flat) for _, _, _, flat in members))
            for (step, dt, cx), members in groups.items())
        raws = [_raw(nd) for nd in nds]
        batch_sig = tuple((tuple(a.shape), str(a.dtype)) for a in raws)
        key_sig = (batch_sig, group_sig, bool(dist), kw)

        entry = self._cache.get(key_sig)
        fresh = entry is None
        if fresh:
            try:
                entry = self._build(raws, touched, groups, tpos_of, dist,
                                    kv, kw=kw)
            except Exception as e:  # capture failure: loud sticky fallback
                self._note_fallback(f"capture failed: {e!r:.200}")
                return self._run_eager(nds, batch_size)
            self._cache[key_sig] = entry

        # per-step dynamic hypers: bump ALL counts first, then read lr/wd
        # (the fused_update discipline — synchronized params all see the
        # same num_update).  For a K-window, repeat the discipline once
        # per LOGICAL step so the stacked [K, n] rows are exactly what K
        # unfolded steps would have staged — and draw K keys from the
        # ambient stream in step order so dropout parity is bit-exact.
        if kw is None:
            for i, _ in touched:
                opt._update_count(i)
            lrs = jnp.asarray([opt._get_lr(i) for i, _ in touched],
                              jnp.float32)
            wds = jnp.asarray([opt._get_wd(i) for i, _ in touched],
                              jnp.float32)
            ts = jnp.asarray([opt._index_update_count[i]
                              for i, _ in touched], jnp.float32)
            key = get_key()
        else:
            lr_rows, wd_rows, t_rows, keys = [], [], [], []
            for _ in range(kw):
                for i, _p in touched:
                    opt._update_count(i)
                lr_rows.append([opt._get_lr(i) for i, _p in touched])
                wd_rows.append([opt._get_wd(i) for i, _p in touched])
                t_rows.append([opt._index_update_count[i]
                               for i, _p in touched])
                keys.append(get_key())
            lrs = jnp.asarray(lr_rows, jnp.float32)
            wds = jnp.asarray(wd_rows, jnp.float32)
            ts = jnp.asarray(t_rows, jnp.float32)
            key = jnp.stack(keys)
        scalars = {k: jnp.asarray(v, jnp.float32)
                   for k, v in _fused._scalars(opt).items()}

        return self._dispatch(entry, touched, key, lrs, wds, ts, scalars,
                              raws, fresh, kw)

    def _dispatch(self, entry, touched, key, lrs, wds, ts, scalars, raws,
                  fresh, kw=None):
        tr = self._trainer
        site = "gluon.step_fold" if kw is None else "gluon.step_fold_k"
        if self._dist is not None:
            call_args = self._dist.stage_call(key, lrs, wds, ts, scalars,
                                              raws, window=kw is not None)
        else:
            param_arrs = [_raw(p._data) for p in entry["params"]]
            state_arrs = [tuple(_raw(s) for s in flat)
                          for flat in entry["state_flats"]]
            call_args = (key, lrs, wds, ts, scalars, param_arrs, state_arrs,
                         *raws)
        tc = _perf() if fresh else None
        t0 = _perf() if _profiler._active else None
        try:
            try:
                out = entry["fn"](*call_args)
            except Exception as e:
                # the donated whole-step dispatch is an OOM choke point
                _profiler.maybe_oom_postmortem(e, site)
                raise
            loss_local = self._wire_outputs(entry, touched, out)
            if tc is not None:
                # AFTER output wiring: a guard in raise mode must never
                # leave Parameters pointing at donated-and-deleted buffers.
                # Tail-window / step_one entries (k_window != k) are a
                # DECLARED warmup: each distinct window width is its own
                # program, built once — register the compile but don't let
                # an armed guard judge it (the serving re-warm convention).
                if entry.get("declared_warmup"):
                    with _profiler.compile_guard_paused():
                        _profiler.record_compile(
                            site, self._compile_sig(entry, raws),
                            (_perf() - tc) * 1e3)
                else:
                    _profiler.record_compile(
                        site, self._compile_sig(entry, raws),
                        (_perf() - tc) * 1e3)
            ca = entry.get("comm_args")
            kk = int(kw) if kw is not None else 1
            if ca is not None:
                from ..comm import compression as _comp

                _comp.account(ca["bytes_raw"] * kk, ca["bytes_wire"] * kk)
                if ca["hops"]:
                    _profiler.incr("comms_ring_hops", ca["hops"] * kk)
            if t0 is not None:
                span_args = {"params": len(touched),
                             "dist": self._dist is not None}
                if kw is not None:
                    span_args["k"] = int(kw)
                if ca is not None:
                    span_args.update(ca,
                                     bytes_raw=ca["bytes_raw"] * kk,
                                     bytes_wire=ca["bytes_wire"] * kk)
                _profiler.record_span("trainer.step_fold", "trainer", t0,
                                      args=span_args)
            _profiler.incr("step_fold_call")
            # freshness snapshot (Trainer._update parity): only a future
            # backward/user write may flip a param back to fresh
            for i, p in touched:
                tr._grad_versions[i] = p.grad_version
        finally:
            _profiler.step_boundary()
        if not self._guard_armed:
            self._guard_armed = True
            _profiler.arm_compile_guard(site)
        return loss_local

    def _compile_sig(self, entry, raws):
        kw = entry.get("k")
        program = "step_fold" if not kw else f"step_fold_k[{kw}]"
        if entry["dist"]:
            program += ":dist"
            ca = entry.get("comm_args")
            if ca:
                # a wire-policy change (codec tier or exchange algorithm)
                # is a DISTINCT program, not a recompile of the old one —
                # the same reason bucket keys are codec-namespaced
                program += f":{ca.get('codec')}:{ca.get('algo')}"
        sig = {"__program__": program,
               "params": _profiler.sig_static(len(entry["params"])),
               "groups": _profiler.sig_static(
                   [g[0] for g in entry["plan_names"]])}
        for i, a in enumerate(raws):
            sig[f"in{i}"] = {"k": "array", "shape": tuple(a.shape),
                             "dtype": str(a.dtype)}
        return sig

    def _warn_foreign_aux(self, aux_cell):
        """One loud warning when the capture saw aux updates for params
        the trainer doesn't own: their OLD value is a baked trace
        constant, so they stay FROZEN in-fold (pass the block's full
        ``collect_params()`` to the Trainer to fold them)."""
        foreign = aux_cell[0][1] if aux_cell else []
        if foreign:
            _warnings.warn(
                "step fold: aux updates for parameters the Trainer does "
                f"not own stay FROZEN inside the fold ({foreign[:3]}...); "
                "construct the Trainer with the block's full "
                "collect_params() to fold their running stats — "
                "docs/step_fold.md", UserWarning, stacklevel=4)

    def _wire_outputs(self, entry, touched, out):
        """Swap the program's fresh buffers into the live NDArrays (local
        fold) or registers (dist fold).  Returns the loss NDArray."""
        if self._dist is not None:
            return self._dist.wire(entry, touched, out, self._keep_grads)
        it = iter(out)
        new_params, new_states, loss_data = next(it), next(it), next(it)
        grads = next(it) if self._keep_grads else None
        for p, arr in zip(entry["params"], new_params):
            _swap(p._data, arr)
        for flat, new in zip(entry["state_flats"], new_states):
            for s_nd, s_new in zip(flat, new):
                _swap(s_nd, s_new)
        if grads is not None:
            for (_, p), g in zip(touched, grads):
                _swap(p._data._grad, g)
        return NDArray(loss_data)

    # -- capture ---------------------------------------------------------
    def _build(self, raws, touched, groups, tpos_of, dist, kv, kw=None):
        """Trace + jit the whole step.  Returns the cache entry dict.  The
        capture is validated with ``jax.eval_shape`` (no device work), so
        a loss_fn the tracer cannot swallow fails HERE — cleanly — and the
        caller falls back to the eager path.

        With ``kw`` (the K-step fold), the SAME per-step body — forward,
        backward, bucket collectives, optimizer tail, aux write-back —
        becomes the body of a ``jax.lax.scan`` over the ``[kw, ...]``
        stacked batch window: params and optimizer state (and, dist, EF
        residuals) ride the loop carry; per-step lr/wd/t rows and PRNG
        keys ride as stacked ``[kw, ...]`` scan inputs; the per-step
        losses stack as the scan output."""
        tr = self._trainer
        params = [p for p in tr._params if p._data is not None]
        slot_of = {id(p): s for s, p in enumerate(params)}
        trainable_slots = [slot_of[id(p)] for _, p in touched]
        state_flats = [None] * len(touched)
        plan = []        # (step_fn, [(tpos, slot)])
        plan_names = []
        for (step, dt, cx), members in groups.items():
            rows = []
            for i, w, _, flat in members:
                t = tpos_of[i]
                state_flats[t] = tuple(flat)
                rows.append((t, slot_of[id(tr._params[i])]))
            plan.append((step, tuple(rows)))
            plan_names.append((step.__name__, dt, len(members)))
        loss_fn = self._loss_fn
        keep_grads = self._keep_grads
        aux_cell = []     # [(in_slots, out_params)] discovered on trace 1
        loss_meta = []    # [ndim] of the user loss

        def forward_loss(train_arrs, full_arrs, key, batch):
            full = list(full_arrs)
            for s, arr in zip(trainable_slots, train_arrs):
                full[s] = arr
            with trace_scope(params, full, key, True) as collector:
                loss = loss_fn(*[NDArray(b) for b in batch])
            loss_data = loss._data
            if not loss_meta:
                loss_meta.append(loss_data.ndim)
            if not aux_cell:
                # per-POSITION ownership (slot index, or None for a param
                # the trainer doesn't hold): owned and foreign aux may
                # interleave in forward order.  Foreign aux updates are
                # DROPPED, not written back — the old value is baked into
                # the trace as a constant, so a write-back would keep
                # re-deriving the update from the original stats forever
                # (frozen is honest; a warning surfaces it at build).
                kinds, foreign = [], []
                for p, _ in collector:
                    s = slot_of.get(id(p))
                    kinds.append(s)
                    if s is None:
                        foreign.append(p.name)
                aux_cell.append((kinds, foreign))
            aux_vals = tuple(v._data if isinstance(v, NDArray) else v
                             for _, v in collector)
            # differentiate the SUM in the loss's own dtype — exact parity
            # with loss.backward()'s implicit ones head-grads
            return jnp.sum(loss_data), (aux_vals, loss_data)

        def optimizer_tail(param_arrs, state_arrs, grads, lrs, wds, ts,
                           scalars):
            new_full = list(param_arrs)
            new_states = list(state_arrs)
            for step, rows in plan:
                for t, s in rows:
                    nw, ns = step(param_arrs[s], grads[t], state_arrs[t],
                                  lrs[t], wds[t], ts[t], scalars)
                    new_full[s] = nw
                    new_states[t] = tuple(ns)
            return new_full, new_states

        def apply_aux(new_full, param_arrs, aux_vals):
            kinds, _ = aux_cell[0]
            for s, v in zip(kinds, aux_vals):
                if s is not None:
                    new_full[s] = v.astype(param_arrs[s].dtype)

        if dist:
            return self._build_dist(raws, touched, params, state_flats,
                                    plan, plan_names, trainable_slots,
                                    forward_loss, optimizer_tail, apply_aux,
                                    aux_cell, loss_meta, kv, kw=kw)

        def one_step(key, lr, wd, t, scalars, param_arrs, state_arrs,
                     batch):
            """ONE logical step — shared verbatim by the K=1 program and
            the scan body, so folded numerics cannot depend on K."""
            train_arrs = [param_arrs[s] for s in trainable_slots]
            (_, (aux_vals, loss_data)), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(train_arrs, list(param_arrs),
                                            key, batch)
            new_full, new_states = optimizer_tail(
                param_arrs, state_arrs, grads, lr, wd, t, scalars)
            apply_aux(new_full, param_arrs, aux_vals)
            return new_full, new_states, loss_data, grads

        if kw is None:
            def pure_step(key, lrs, wds, ts, scalars, param_arrs,
                          state_arrs, *batch):
                new_full, new_states, loss_data, grads = one_step(
                    key, lrs, wds, ts, scalars, param_arrs, state_arrs,
                    batch)
                out = (new_full, new_states, loss_data)
                if keep_grads:
                    out += (list(grads),)
                return out
        else:
            def pure_step(keys, lrs, wds, ts, scalars, param_arrs,
                          state_arrs, *windows):
                def body(carry, xs):
                    p_arrs, s_arrs = carry[0], carry[1]
                    key, lr, wd, t = xs[0], xs[1], xs[2], xs[3]
                    batch = xs[4:]
                    new_full, new_states, loss_data, grads = one_step(
                        key, lr, wd, t, scalars, list(p_arrs),
                        [tuple(s) for s in s_arrs], batch)
                    new_carry = (tuple(new_full),
                                 tuple(tuple(s) for s in new_states))
                    if keep_grads:
                        new_carry += (tuple(grads),)
                    return new_carry, loss_data

                init = (tuple(param_arrs),
                        tuple(tuple(s) for s in state_arrs))
                if keep_grads:
                    init += (tuple(jnp.zeros_like(param_arrs[s])
                                   for s in trainable_slots),)
                xs = (keys, lrs, wds, ts) + tuple(windows)
                carry, losses = jax.lax.scan(body, init, xs)
                out = (list(carry[0]),
                       [tuple(s) for s in carry[1]], losses)
                if keep_grads:
                    out += (list(carry[2]),)
                return out

        # abstract validation pass — populates aux_cell/loss_meta and
        # surfaces capture failures without any device work.  The key aval
        # comes from a FRESH PRNGKey(0), never get_key(): splitting the
        # ambient stream at build time would desync fold-vs-unfused
        # dropout parity by one key.
        ex_key = jax.random.PRNGKey(0)
        hyp = ((len(touched),) if kw is None else (kw, len(touched)))
        key_shape = ex_key.shape if kw is None else (kw,) + ex_key.shape
        abstract = (
            jax.ShapeDtypeStruct(key_shape, ex_key.dtype),
            jax.ShapeDtypeStruct(hyp, jnp.float32),
            jax.ShapeDtypeStruct(hyp, jnp.float32),
            jax.ShapeDtypeStruct(hyp, jnp.float32),
            {k: jax.ShapeDtypeStruct((), jnp.float32)
             for k in _fused._scalars(tr._optimizer)},
            [jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
             for p in params],
            [tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                   for s in flat) for flat in state_flats],
            *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in raws],
        )
        jax.eval_shape(pure_step, *abstract)
        self._warn_foreign_aux(aux_cell)
        donate = (5, 6) if _fused.donation_enabled() else ()
        if kw is not None and self._donate_window and \
                _fused.donation_enabled():
            # the staged [K, ...] window is single-use — donating it back
            # to the allocator covers the stacked copy's footprint
            donate += tuple(range(7, 7 + len(raws)))
        fn = jax.jit(pure_step, donate_argnums=donate)
        return {"fn": fn, "params": params, "state_flats": state_flats,
                "plan_names": plan_names, "dist": False, "k": kw,
                "declared_warmup": kw is not None and kw != self._k,
                "abstract": abstract}

    # -- the multi-process (in-fold collectives) build -------------------
    def _build_dist(self, raws, touched, params, state_flats, plan,
                    plan_names, trainable_slots, forward_loss,
                    optimizer_tail, apply_aux, aux_cell, loss_meta, kv,
                    kw=None):
        """Fold the gradient exchange into the program: forward/backward
        per worker shard under ONE ``shard_map`` over the kvstore's worker
        mesh, with each size-capped gradient bucket an explicit allreduce
        node (fp32 ``psum``, or the PR 14 codec's in-program quantized
        exchange) that XLA may schedule as soon as that bucket's grads
        exist — comms overlapped against the remaining backward.  The
        optimizer tail then runs on the replicated reduced grads."""
        from jax.sharding import PartitionSpec as P

        from .. import kvstore as kv_mod
        from ..comm import compression as comp_mod
        from ..parallel.mesh import get_shard_map

        tr = self._trainer
        mesh = kv._worker_mesh()
        keep_grads = self._keep_grads
        policy = comp_mod.resolve_policy()
        ef = policy is not None and policy.error_feedback

        # THE deterministic bucket rule (kvstore.plan_buckets — shared
        # with bucketed_pushpull and the overlap hook, so in-fold and
        # out-of-fold paths can never draw different bucket boundaries);
        # positions index ``touched`` order = the grads list
        _, kv_buckets = kv_mod.plan_buckets(
            [(i, p.grad()) for i, p in touched],
            names=[p.name for _, p in touched], compression=policy)
        buckets = []   # (codec|None, [(tpos, off, n, shape)])
        for bk in kv_buckets:
            rows, off = [], 0
            for t in bk["positions"]:
                a = touched[t][1]._data._data
                rows.append((t, off, int(a.size), tuple(a.shape)))
                off += int(a.size)
            buckets.append((bk["codec"], tuple(rows)))
        n_train = len(touched)
        # ring outputs are replicated by explicit ppermute relay, which
        # the static replication checker cannot infer through
        algo = policy.algo if policy is not None else "psum"
        smap = get_shard_map(check_rep=(algo != "ring"))
        P0 = P()
        PW = P("w")
        # per-LOGICAL-step batch spec: inside a K-window the scan body
        # sees one [global_batch, ...] slice per iteration (the stacked
        # window itself is sharded on axis 1, its batch axis)
        batch_specs = tuple(
            P(*(("w",) + (None,) * ((a.ndim - (2 if kw else 1)))))
            for a in raws)

        def shard_body(train_arrs, full_arrs, key, residuals, *batch):
            # distinct PRNG stream per worker — the documented dist-fold
            # convention (matches the SPMD quantized-collective build)
            key = jax.random.fold_in(key, jax.lax.axis_index("w"))
            (_, (aux_vals, loss_data)), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(train_arrs, full_arrs, key,
                                            batch)
            new_grads = [None] * n_train
            new_resid = []
            ri = 0
            for codec, rows in buckets:
                flat = jnp.concatenate(
                    [grads[t].reshape(-1) for t, _, _, _ in rows])
                if codec is None:
                    red = jax.lax.psum(flat, "w")
                else:
                    red, resid = comp_mod.traced_allreduce(
                        codec, flat, residuals[ri][0] if ef else None,
                        ("w",), algo=algo)
                    if ef:
                        new_resid.append(resid[None, :])
                        ri += 1
                for t, off, n, shape in rows:
                    new_grads[t] = red[off:off + n].reshape(shape)
            # local loss leaves sharded over 'w' (each worker reads its
            # own shard — parity with the per-worker eager loss); aux
            # stats pmean so every worker applies the same running stats
            loss_out = loss_data if loss_data.ndim >= 1 \
                else loss_data[None]
            aux_vals = tuple(jax.lax.pmean(a, "w") for a in aux_vals)
            return (tuple(new_grads), tuple(new_resid), loss_out, aux_vals)

        mapped = smap(
            shard_body, mesh=mesh,
            in_specs=(P0, P0, P0, PW) + batch_specs,
            out_specs=(P0, PW, PW, P0))

        def dist_step(key, lr, wd, t, scalars, param_arrs, state_arrs,
                      residuals, batch):
            """ONE logical dist step (shard_map'd collectives inside) —
            shared verbatim by the K=1 program and the scan body."""
            train_arrs = [param_arrs[s] for s in trainable_slots]
            grads_t, new_resid, loss_out, aux_vals = mapped(
                train_arrs, list(param_arrs), key, tuple(residuals), *batch)
            new_full, new_states = optimizer_tail(
                param_arrs, state_arrs, list(grads_t), lr, wd, t, scalars)
            apply_aux(new_full, param_arrs, aux_vals)
            return new_full, new_states, list(new_resid), loss_out, grads_t

        if kw is None:
            def pure_step(key, lrs, wds, ts, scalars, param_arrs,
                          state_arrs, residuals, *batch):
                new_full, new_states, new_resid, loss_out, grads_t = \
                    dist_step(key, lrs, wds, ts, scalars, param_arrs,
                              state_arrs, residuals, batch)
                out = (new_full, new_states, new_resid, loss_out)
                if keep_grads:
                    out += (list(grads_t),)
                return out
        else:
            def pure_step(keys, lrs, wds, ts, scalars, param_arrs,
                          state_arrs, residuals, *windows):
                def body(carry, xs):
                    p_arrs, s_arrs, resid = carry
                    key, lr, wd, t = xs[0], xs[1], xs[2], xs[3]
                    batch = xs[4:]
                    new_full, new_states, new_resid, loss_out, grads_t = \
                        dist_step(key, lr, wd, t, scalars, list(p_arrs),
                                  [tuple(s) for s in s_arrs], list(resid),
                                  batch)
                    new_carry = (tuple(new_full),
                                 tuple(tuple(s) for s in new_states),
                                 tuple(new_resid))
                    ys = (loss_out,)
                    if keep_grads:
                        ys += (tuple(grads_t),)
                    return new_carry, ys

                init = (tuple(param_arrs),
                        tuple(tuple(s) for s in state_arrs),
                        tuple(residuals))
                xs = (keys, lrs, wds, ts) + tuple(windows)
                carry, ys = jax.lax.scan(body, init, xs)
                out = (list(carry[0]), [tuple(s) for s in carry[1]],
                       list(carry[2]), ys[0])
                if keep_grads:
                    # last logical step's grads — the window-boundary
                    # grads, same contract as K=1's post-step grads
                    out += ([g[-1] for g in ys[1]],)
                return out

        if self._dist is not None:
            # a rebuild (new batch signature): the live Parameters are
            # stale — refresh them from the old registers before re-staging
            self._dist.sync_out()
        regs = _DistRegisters(tr, params, state_flats, mesh,
                              buckets if ef else [], loss_meta)
        self._dist = regs
        donate = (5, 6, 7) if _fused.donation_enabled() else ()
        if kw is not None and self._donate_window and \
                _fused.donation_enabled():
            donate += tuple(range(8, 8 + len(raws)))
        with mesh:
            fn = jax.jit(pure_step, donate_argnums=donate)
        # validation trace (abstract; global shapes)
        ex_key = jax.random.PRNGKey(0)
        nw = mesh.devices.size
        hyp = ((n_train,) if kw is None else (kw, n_train))
        key_shape = ex_key.shape if kw is None else (kw,) + ex_key.shape
        if kw is None:
            batch_avals = [jax.ShapeDtypeStruct(
                (a.shape[0] * nw,) + tuple(a.shape[1:]), a.dtype)
                for a in raws]
        else:
            batch_avals = [jax.ShapeDtypeStruct(
                (a.shape[0], a.shape[1] * nw) + tuple(a.shape[2:]),
                a.dtype) for a in raws]
        abstract = (
            jax.ShapeDtypeStruct(key_shape, ex_key.dtype),
            jax.ShapeDtypeStruct(hyp, jnp.float32),
            jax.ShapeDtypeStruct(hyp, jnp.float32),
            jax.ShapeDtypeStruct(hyp, jnp.float32),
            {k: jax.ShapeDtypeStruct((), jnp.float32)
             for k in _fused._scalars(tr._optimizer)},
            [jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
             for p in params],
            [tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                   for s in flat) for flat in state_flats],
            [jax.ShapeDtypeStruct((nw, n), jnp.float32)
             for n in regs.resid_sizes],
            *batch_avals,
        )
        with mesh:
            jax.eval_shape(pure_step, *abstract)
        self._warn_foreign_aux(aux_cell)
        # per-dispatch comms accounting for the in-fold exchange (the
        # trace_report comms table + counters): logical payload sizes per
        # LOGICAL step, plus hop-level detail when the ring algorithm
        # carries the buckets over explicit ppermute
        from ..comm import ring as ring_mod

        b_raw = b_wire = hops = hop_wire = hop_fp32 = 0
        codec_ids = []
        for codec, rows in buckets:
            n = sum(r[2] for r in rows)
            b_raw += 4 * n
            if codec is None:
                b_wire += 4 * n
            else:
                b_wire += int(codec.wire_nbytes(n))
                codec_ids.append(codec.id)
                if algo == "ring":
                    h, bb = ring_mod.hop_plan(codec, n, nw)
                    hops += h
                    hop_wire += h * bb
                    # what a fp32 ring would move per hop: one raw chunk
                    hop_fp32 += h * 4 * ring_mod._ring_chunk(codec, n, nw)
        comm_args = None
        if codec_ids:
            comm_args = {"bytes_raw": int(b_raw), "bytes_wire": int(b_wire),
                         "codec": ",".join(sorted(set(codec_ids))),
                         "algo": algo, "hops": int(hops),
                         "bytes_hop": int(hop_wire // hops) if hops else 0,
                         "bytes_hop_fp32":
                             int(hop_fp32 // hops) if hops else 0}
        return {"fn": fn, "params": params, "state_flats": state_flats,
                "plan_names": plan_names, "dist": True, "k": kw,
                "declared_warmup": kw is not None and kw != self._k,
                "comm_args": comm_args, "abstract": abstract}


class _DistRegisters:
    """Donated global registers for the multi-process fold: replicated
    params/optimizer state and sharded error-feedback residuals live as
    jax global arrays across steps (zero per-step staging); Parameters and
    ``trainer._states`` are refreshed lazily via ``sync_out``."""

    def __init__(self, trainer, params, state_flats, mesh, ef_buckets,
                 loss_meta):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._trainer = trainer
        self._params = params
        self._state_flats = state_flats
        self._mesh = mesh
        self._loss_meta = loss_meta
        self._rep = NamedSharding(mesh, P())
        self._row = NamedSharding(mesh, P("w"))
        self.param_arrays = [self._replicate(_raw(p._data)) for p in params]
        self.state_arrays = [tuple(self._replicate(_raw(s)) for s in flat)
                             for flat in state_flats]
        self.resid_sizes = [sum(n for _, _, n, _ in rows)
                            for codec, rows in ef_buckets
                            if codec is not None]
        # error-feedback residuals persist through the trainer's
        # ErrorFeedback store (the PR 14 contract: save_states carries
        # them, a rebuild re-stages them — never silently zeroed); each
        # process stages its OWN local rows, per-host-file style
        import jax as _jax

        nw = mesh.devices.size
        local_rows = max(1, nw // _jax.process_count())
        self.residuals = []
        for b, n in enumerate(self.resid_sizes):
            local = None
            fb = trainer._grad_feedback
            if fb is not None:
                stored = fb._res.get(self._resid_key(b, n))
                if stored is not None and \
                        tuple(_np.shape(stored)) == (local_rows, n):
                    local = _np.asarray(stored, _np.float32)
            if local is None:
                local = _np.zeros((local_rows, n), _np.float32)
            self.residuals.append(self._stage_rows(local))

    def _replicate(self, arr):
        import jax as _jax

        local = _jax.device_put(_np.asarray(arr),
                                self._mesh.local_devices[0])
        return _jax.make_array_from_single_device_arrays(
            tuple(local.shape), self._rep, [local])

    @staticmethod
    def _resid_key(b, n):
        return f"__fold_dist__:{b}:{n}"

    def _stage_rows(self, local):
        """This process's residual rows -> the 'w'-sharded global array."""
        import jax as _jax

        if _jax.process_count() == 1:
            return _jax.device_put(local, self._row)
        return _jax.make_array_from_process_local_data(self._row, local)

    def _global_batch(self, arr, window=False):
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        # a [K, batch, ...] window shards on its BATCH axis (axis 1); a
        # plain batch shards on axis 0
        if window:
            spec = P(*((None, "w") + (None,) * (arr.ndim - 2)))
        else:
            spec = P(*(("w",) + (None,) * (arr.ndim - 1)))
        sharding = NamedSharding(self._mesh, spec)
        return _jax.make_array_from_process_local_data(
            sharding, _np.asarray(arr))

    def stage_call(self, key, lrs, wds, ts, scalars, raws, window=False):
        rep = self._replicate
        return (rep(key), rep(lrs), rep(wds), rep(ts),
                {k: rep(v) for k, v in scalars.items()},
                self.param_arrays, self.state_arrays, self.residuals,
                *[self._global_batch(a, window=window) for a in raws])

    def wire(self, entry, touched, out, keep_grads):
        # everything stays DEVICE-RESIDENT: addressable_data(0) hands back
        # this process's shard buffer without a host sync — an immediate
        # np.asarray here would block dispatch on the whole step's device
        # completion every step and forfeit the overlap the fold buys
        # (the PR 12 MoE-extras lesson); sync_out() is the host boundary
        it = iter(out)
        new_params, new_states, new_resid, loss_out = (
            next(it), next(it), next(it), next(it))
        grads = next(it) if keep_grads else None
        self.param_arrays = new_params
        self.state_arrays = [tuple(s) for s in new_states]
        self.residuals = list(new_resid)
        if grads is not None:
            for (_, p), g in zip(touched, grads):
                p._data._grad._data = g.addressable_data(0)
                p._data._grad._version += 1
        local = loss_out.addressable_data(0)
        kw = entry.get("k")
        if self._loss_meta and self._loss_meta[0] == 0:
            # scalar user loss: [1] per worker, or [K, 1] stacked
            local = local.reshape((kw,) if kw else ())
        return NDArray(local)

    def sync_out(self):
        """Fold registers -> live Parameters / trainer states (gathered
        off the mesh so eager ops see single-device arrays).  Residuals
        land in the trainer's ErrorFeedback store so ``save_states``
        persists them and a rebuild re-stages them."""
        with autograd.pause():
            for p, a in zip(self._params, self.param_arrays):
                p._data._data = jnp.asarray(_np.asarray(
                    a.addressable_data(0)))
                p._data._version += 1
            for flat, arrs in zip(self._state_flats, self.state_arrays):
                for s_nd, a in zip(flat, arrs):
                    s_nd._data = jnp.asarray(_np.asarray(
                        a.addressable_data(0)))
                    s_nd._version += 1
        if self.residuals:
            from ..comm import compression as comp_mod

            tr = self._trainer
            if tr._grad_feedback is None:
                tr._grad_feedback = comp_mod.ErrorFeedback()
            for b, (n, arr) in enumerate(zip(self.resid_sizes,
                                             self.residuals)):
                tr._grad_feedback.update(
                    self._resid_key(b, n),
                    _np.asarray(arr.addressable_data(0)))


class EvalProgram:
    """The folded evaluation pass (``Trainer.fold_eval(loss_fn, k)``).

    Calling the program with a batch (K=1) or a ``[K, batch, ...]``
    stacked window (``pipeline.stage_window(k)``) runs forward-only loss
    under the SAME ``trace_scope`` ceremony as the training fold — but
    with ``is_training=False``, so BatchNorm reads running stats and
    dropout is identity — and accumulates the summed loss IN-PROGRAM
    into a device-resident f32 register.  The host reads nothing until
    :meth:`result`, once per eval pass: an N-batch eval is N/K dispatches
    and ONE device->host transfer.

    Compile site ``gluon.fold_eval``; every fresh build registers as a
    declared warmup (eval programs are built once per batch signature,
    usually after the train guard armed).  Escape hatches and fallback
    accounting (``step_fold_fallback`` labels) match :class:`StepProgram`.
    """

    def __init__(self, trainer, loss_fn, block=None, k=None):
        self._trainer = trainer
        self._loss_fn = loss_fn
        self._block = block
        self._k = max(1, int(k if k is not None else fold_k()))
        self._cache = {}          # (batch sig, kw) -> entry
        self._fallback_reason = None
        self._fallback_label = None
        self._warned = False
        self._guard_armed = False
        self._acc = None          # device f32 scalar — summed loss so far
        self._host_sum = 0.0      # eager-path contribution
        self._count = 0           # loss elements accumulated
        self._synced_at = -1      # train-fold progress at last register sync
        if not fold_enabled():
            self._fallback_reason = "MXNET_STEP_FOLD=0"
            self._fallback_label = "env-off"
        elif _engine.is_naive():
            self._fallback_reason = "NaiveEngine"
            self._fallback_label = "naive-engine"
        elif _opted_out(block):
            self._fallback_reason = "block opt-out (_step_fold_opt_out)"
            self._fallback_label = "block-opt-out"

    @property
    def folded(self):
        return self._fallback_reason is None

    @property
    def fallback_reason(self):
        return self._fallback_reason

    @property
    def k(self):
        return self._k

    @property
    def count(self):
        """Loss elements accumulated since the last ``result(reset=True)``."""
        return self._count

    def _note_fallback(self, reason, label="capture-failure"):
        self._fallback_reason = reason
        self._fallback_label = label
        if not self._warned:
            self._warned = True
            _warnings.warn(
                f"eval fold disabled ({reason}); running the eager "
                "forward path instead — see docs/step_fold.md",
                UserWarning, stacklevel=3)

    def _sync_train_fold(self):
        """A multi-process TRAIN fold keeps the live trajectory in donated
        registers — pull them back into the Parameters once per train
        progress before evaluating against them."""
        ref = getattr(self._trainer, "_fold", None)
        fold = ref() if ref is not None else None
        if fold is not None and fold._dist is not None and \
                fold._logical_steps != self._synced_at:
            fold.sync()
            self._synced_at = fold._logical_steps

    def __call__(self, *batch):
        nds = [b if isinstance(b, NDArray) else NDArray(jnp.asarray(b))
               for b in batch]
        self._sync_train_fold()
        tr = self._trainer
        if self._fallback_reason is not None or any(
                p._deferred_init is not None or p._data is None
                for p in tr._params):
            return self._eager_eval(nds)
        return self._folded_eval(nds)

    def _eager_eval(self, nds):
        _profiler.incr_labeled("step_fold_fallback",
                               self._fallback_label or "deferred-init")
        rows = [nds]
        if self._k > 1 and nds and nds[0].ndim >= 2:
            kw = int(nds[0].shape[0])
            rows = [[NDArray(nd._data[j]) for nd in nds]
                    for j in range(kw)]
        with autograd.pause():
            for row in rows:
                loss = self._loss_fn(*row)
                self._host_sum += float(jnp.sum(
                    loss._data.astype(jnp.float32)))
                self._count += int(loss._data.size)

    def _folded_eval(self, nds):
        kw = None
        if self._k > 1:
            if nds[0].ndim < 2:
                raise ValueError(
                    f"fold_eval(k={self._k}) expects stacked [k, batch, "
                    "...] windows (pipeline.stage_window(k)); got shape "
                    f"{tuple(nds[0].shape)}")
            kw = int(nds[0].shape[0])
            if any(int(nd.shape[0]) != kw for nd in nds):
                raise ValueError(
                    "window leading dims disagree: "
                    f"{[tuple(nd.shape) for nd in nds]}")
        raws = [_raw(nd) for nd in nds]
        batch_sig = tuple((tuple(a.shape), str(a.dtype)) for a in raws)
        key_sig = (batch_sig, kw)
        entry = self._cache.get(key_sig)
        fresh = entry is None
        if fresh:
            try:
                entry = self._build(raws, kw)
            except Exception as e:
                self._note_fallback(f"capture failed: {e!r:.200}")
                return self._eager_eval(nds)
            self._cache[key_sig] = entry
        acc = self._acc
        if acc is None:
            acc = jnp.zeros((), jnp.float32)
        param_arrs = [_raw(p._data) for p in entry["params"]]
        tc = _perf() if fresh else None
        t0 = _perf() if _profiler._active else None
        try:
            try:
                new_acc = entry["fn"](acc, param_arrs, *raws)
            except Exception as e:
                _profiler.maybe_oom_postmortem(e, "gluon.fold_eval")
                raise
            self._acc = new_acc
            self._count += entry["loss_size"] * (kw or 1)
            if tc is not None:
                # every eval build is a DECLARED warmup: one program per
                # batch signature, typically compiled after the train
                # guard armed — register it, don't judge it
                with _profiler.compile_guard_paused():
                    _profiler.record_compile(
                        "gluon.fold_eval", self._compile_sig(entry, raws),
                        (_perf() - tc) * 1e3)
            if t0 is not None:
                _profiler.record_span(
                    "trainer.fold_eval", "trainer", t0,
                    args={"params": len(entry["params"]),
                          "k": int(kw or 1)})
            _profiler.incr("fold_eval_call")
        finally:
            _profiler.step_boundary()
        if not self._guard_armed:
            self._guard_armed = True
            _profiler.arm_compile_guard("gluon.fold_eval")

    def result(self, reset=True):
        """Mean loss over every element accumulated since the last reset —
        THE one host read of an eval pass."""
        total = self._host_sum
        if self._acc is not None:
            total += float(self._acc)
        count = self._count
        if reset:
            self._acc = None
            self._host_sum = 0.0
            self._count = 0
        return total / max(1, count)

    def _build(self, raws, kw):
        tr = self._trainer
        params = [p for p in tr._params if p._data is not None]
        loss_fn = self._loss_fn
        loss_cell = []

        def one_eval(param_arrs, batch):
            # a fixed key: eval is deterministic (dropout is identity
            # under is_training=False; the key only seeds the ceremony)
            key = jax.random.PRNGKey(0)
            with trace_scope(params, list(param_arrs), key, False):
                loss = loss_fn(*[NDArray(b) for b in batch])
            loss_data = loss._data
            if not loss_cell:
                loss_cell.append(int(_np.prod(loss_data.shape)))
            return jnp.sum(loss_data.astype(jnp.float32))

        if kw is None:
            def pure_eval(acc, param_arrs, *batch):
                return acc + one_eval(param_arrs, batch)
        else:
            def pure_eval(acc, param_arrs, *windows):
                def body(carry, xs):
                    return carry + one_eval(param_arrs, xs), None

                acc2, _ = jax.lax.scan(body, acc, tuple(windows))
                return acc2

        abstract = (
            jax.ShapeDtypeStruct((), jnp.float32),
            [jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
             for p in params],
            *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in raws],
        )
        jax.eval_shape(pure_eval, *abstract)
        # nothing donated: eval must never consume the live Parameters,
        # and the tiny acc register isn't worth a donation aliasing rule
        fn = jax.jit(pure_eval)
        return {"fn": fn, "params": params, "k": kw,
                "loss_size": loss_cell[0] if loss_cell else 1,
                "abstract": abstract}

    def _compile_sig(self, entry, raws):
        sig = {"__program__": f"fold_eval[{entry.get('k') or 1}]",
               "params": _profiler.sig_static(len(entry["params"]))}
        for i, a in enumerate(raws):
            sig[f"in{i}"] = {"k": "array", "shape": tuple(a.shape),
                             "dtype": str(a.dtype)}
        return sig


# ---------------------------------------------------------------------------
# The MXNET_STEP_FOLD=1 fast path inside Trainer.step: fold the whole
# optimizer tail (every fused group) into ONE donated jitted dispatch.
# ---------------------------------------------------------------------------

_TAIL_JITS = {}


def _tail_fn(plan_key, steps, donate):
    fn = _TAIL_JITS.get((plan_key, donate))
    if fn is None:
        def body(weights, grads, states, lrs, wds, ts, scalars):
            new_w = []
            new_s = []
            for g, step in enumerate(steps):
                gw, gs = [], []
                for m in range(len(weights[g])):
                    nw, ns = step(weights[g][m], grads[g][m], states[g][m],
                                  lrs[g][m], wds[g][m], ts[g][m], scalars)
                    gw.append(nw)
                    gs.append(list(ns))
                new_w.append(gw)
                new_s.append(gs)
            return new_w, new_s

        fn = jax.jit(body, donate_argnums=(0, 2) if donate else ())
        _TAIL_JITS[(plan_key, donate)] = fn
        while len(_TAIL_JITS) > 64:
            _TAIL_JITS.pop(next(iter(_TAIL_JITS)))
    return fn


def fold_update(optimizer, items, states):
    """Folded optimizer tail — :func:`optimizer.fused.fused_update`'s
    drop-in twin that updates EVERY fused group in one donated jitted
    dispatch instead of one ``group_apply`` per group (the
    ``MXNET_STEP_FOLD=1`` fast path inside ``Trainer.step``).  Returns the
    leftover per-tensor items, exactly like ``fused_update``."""
    agg = int(getattr(optimizer, "aggregate_num", 0) or 0)
    if agg <= 1 or not items or _engine.is_naive():
        return items
    groups, rest = _fused.plan_groups(optimizer, items, states)
    if not groups:
        return rest
    # bump ALL counts first, then read lr/wd/t (fused_update discipline)
    for members in groups.values():
        for i, _, _, _ in members:
            optimizer._update_count(i)
    ws, gs, sts, lrs, wds, ts, flats = [], [], [], [], [], [], []
    steps = []
    plan_key_parts = []
    for (step, dt, cx), members in groups.items():
        steps.append(step)
        plan_key_parts.append((step, len(members)))
        ws.append([_fused._concrete(w) for _, w, _, _ in members])
        gs.append([_fused._concrete(g) for _, _, g, _ in members])
        sts.append([[_fused._concrete(s) for s in flat]
                    for _, _, _, flat in members])
        lrs.append(jnp.asarray([optimizer._get_lr(i)
                                for i, _, _, _ in members], jnp.float32))
        wds.append(jnp.asarray([optimizer._get_wd(i)
                                for i, _, _, _ in members], jnp.float32))
        ts.append(jnp.asarray([optimizer._index_update_count[i]
                               for i, _, _, _ in members], jnp.float32))
        flats.append([flat for _, _, _, flat in members])
    scalars = {k: jnp.asarray(v, jnp.float32)
               for k, v in _fused._scalars(optimizer).items()}
    donate = _fused.donation_enabled()
    fn = _tail_fn(tuple(plan_key_parts), tuple(steps), donate)
    n_params = sum(len(m) for m in ws)
    n0 = _profiler.jit_cache_size(fn)
    tc = _perf()
    t0 = tc if _profiler._active else None
    guard_err = None
    try:
        new_w, new_s = fn(ws, gs, sts, lrs, wds, ts, scalars)
    except Exception as e:
        _profiler.maybe_oom_postmortem(e, "gluon.step_fold")
        raise
    compiled = n0 >= 0 and _profiler.jit_cache_size(fn) > n0
    if compiled:
        sig = {"__program__": "update_tail",
               "groups": _profiler.sig_static(
                   [(getattr(s, "__name__", "?"), n)
                    for s, n in plan_key_parts])}
        k = 0
        for grp in ws:
            for w in grp:
                sig[f"w{k}"] = {"k": "array", "shape": tuple(w.shape),
                                "dtype": str(w.dtype)}
                k += 1
        try:
            _profiler.record_compile("gluon.step_fold", sig,
                                     (_perf() - tc) * 1e3)
        except _profiler.CompileGuardError as e:
            guard_err = e   # buffers are donated: wire first, raise after
    for g, members in enumerate(groups.values()):
        for m, (_, w, _, _) in enumerate(members):
            _swap(w, new_w[g][m])
            for s_nd, s_new in zip(flats[g][m], new_s[g][m]):
                _swap(s_nd, s_new)
    if t0 is not None:
        _profiler.record_span("fused.group_apply", "optimizer", t0,
                              args={"params": n_params,
                                    "groups": len(groups), "folded": True})
    _profiler.incr("fused_step_call")
    _profiler.incr("fused_step_params", n_params)
    if guard_err is not None:
        raise guard_err
    if rest:
        _profiler.incr("fused_step_fallback_params", len(rest))
    return rest
