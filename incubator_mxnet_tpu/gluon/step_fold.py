"""One compiled program per training step — the Gluon step fold.

A classic Gluon training step is several host dispatches: the hybridized
forward (CachedOp jit), the autograd backward (one jitted vjp per tape
node), the bucketed ``allreduce_grads`` pushpulls, and one fused
``group_apply`` per optimizer group.  ``SPMDTrainer`` has lowered its whole
step to ONE donated-buffer program since PR 3 — this module brings the same
whole-program compilation to the imperative ``gluon.Trainer`` contract
(the Julia-to-TPU full-compilation result in PAPERS.md: XLA's fusion pays
off at program granularity, not op granularity):

* :class:`StepProgram` (``Trainer.fold_step(loss_fn)``) traces Block
  forward + loss + backward + the fused optimizer tail into one jitted,
  donated-buffer program per (batch signature, optimizer-group-set).  The
  capture enters the SAME ``gluon.block.trace_scope`` ceremony as the
  CachedOp build and the SPMDTrainer step builders (the unification of the
  repo's partial graph capturers), and the optimizer tail composes the
  SAME per-tensor step adapters ``optimizer/fused.py`` groups with
  (``plan_groups``), so folded numerics cannot drift from the unfused
  kernels they inline.  Weights, optimizer state (and under error
  feedback, compression residuals) are donated; the fresh outputs are
  swapped back into the live ``Parameter``/state NDArrays, so folded and
  unfused steps stay interchangeable mid-training and
  ``save_states``/``load_states`` keep working.

* Multi-process runs against a ``dist_sync`` store fold the gradient
  exchange IN-PROGRAM: forward/backward runs per worker shard inside one
  ``shard_map`` over the kvstore's worker mesh, and each size-capped
  gradient bucket becomes an explicit ``psum`` (or the PR 14 codec's
  quantize → integer psum → dequantize, ``comm.traced_allreduce``) graph
  node that depends only on its own bucket's grads — XLA's scheduler is
  free to start a bucket's collective while the remaining backward still
  computes, which is where MLPerf-on-TPU-pods finds most pod-scale
  headroom.

* :func:`fold_update` is the ``MXNET_STEP_FOLD=1`` fast path inside
  ``Trainer.step``: the whole optimizer tail — every fused group — folds
  into ONE donated jitted dispatch instead of one ``group_apply`` per
  group (forward/backward already ran eagerly by the time ``step()`` is
  called, so this is the part of the step ``Trainer.step`` can fold).

Escape hatches (docs/step_fold.md): ``MXNET_STEP_FOLD=0`` disables both
entries, a block opts out with ``block._step_fold_opt_out = True``, and
any capture failure or unsupported optimizer falls back to the eager
record/backward/step path (counted in ``step_fold_fallback``), never
erroring.  ``NaiveEngine`` bypasses folding entirely.
"""
from __future__ import annotations

import os as _os
import warnings as _warnings
from time import perf_counter as _perf

import jax
import jax.numpy as jnp
import numpy as _np

from .. import autograd
from .. import engine as _engine
from .. import profiler as _profiler
from ..ndarray.ndarray import NDArray
from ..optimizer import fused as _fused
from ..optimizer.optimizer import _swap
from ..random import get_key
from .block import trace_scope

__all__ = ["StepProgram", "fold_update", "fold_enabled", "step_fast_path",
           "host_dispatch_total", "DISPATCH_COUNTERS"]


def fold_enabled():
    """Whether ``Trainer.fold_step`` folds (default yes;
    ``MXNET_STEP_FOLD=0`` is the escape hatch — the returned StepProgram
    still works, running the eager record/backward/step path)."""
    return _os.environ.get("MXNET_STEP_FOLD", "1") != "0"


def step_fast_path():
    """Whether ``Trainer.step`` routes its optimizer tail through
    :func:`fold_update` (opt-in: ``MXNET_STEP_FOLD=1`` exactly — the
    default keeps the established per-group ``group_apply`` path)."""
    return _os.environ.get("MXNET_STEP_FOLD") == "1"


# Counters that each tick once per HOST-ISSUED device dispatch.  The
# steady-state folded step must move this total by exactly 1 (its own
# ``step_fold_call``) — the opperf harness and tests assert the delta.
DISPATCH_COUNTERS = (
    "dispatch_cache_hit", "dispatch_cache_miss", "dispatch_cache_bypass",
    "dispatch_cache_fallback", "bulk_flush", "fused_step_call",
    "allreduce_bucket", "step_fold_call",
)


def host_dispatch_total(counters=None):
    """Sum of the per-dispatch counters (see ``DISPATCH_COUNTERS``)."""
    c = counters if counters is not None else _profiler.counters()
    return sum(c[k] for k in DISPATCH_COUNTERS)


# concrete jax array of an NDArray, flushing a pending bulk deferred in
# place — THE shared flush-before-donation rule (optimizer/fused.py)
_raw = _fused._concrete


def _opted_out(block):
    """Per-block opt-out: ``block._step_fold_opt_out = True`` anywhere in
    the tree keeps the fold off (docs/step_fold.md)."""
    if block is None:
        return False
    if getattr(block, "_step_fold_opt_out", False):
        return True
    return any(_opted_out(c) for c in getattr(block, "_children", {}).values())


class StepProgram:
    """The folded training step for one ``(Trainer, loss_fn)`` pair.

    ``loss_fn(*batch_ndarrays) -> loss NDArray`` computes the loss from
    the batch (calling the Block(s) whose Parameters the Trainer owns);
    calling the program runs forward + backward + allreduce + optimizer
    update as ONE compiled dispatch and returns the loss NDArray.

    Built via ``Trainer.fold_step(loss_fn)``; see docs/step_fold.md for
    the capture contract (what may run inside ``loss_fn``) and the escape
    hatches.
    """

    def __init__(self, trainer, loss_fn, block=None, keep_grads=False):
        self._trainer = trainer
        self._loss_fn = loss_fn
        self._block = block
        self._keep_grads = bool(keep_grads)
        self._cache = {}            # (batch sig, group sig) -> entry dict
        self._fallback_reason = None
        self._warned = False
        self._guard_armed = False
        self._dist = None           # _DistRegisters when folding over a mesh
        if not fold_enabled():
            self._fallback_reason = "MXNET_STEP_FOLD=0"
        elif _engine.is_naive():
            self._fallback_reason = "NaiveEngine"
        elif _opted_out(block):
            self._fallback_reason = "block opt-out (_step_fold_opt_out)"

    # -- public surface --------------------------------------------------
    @property
    def folded(self):
        """False once the program has fallen back to the eager path for
        good (reason in ``fallback_reason``)."""
        return self._fallback_reason is None

    @property
    def fallback_reason(self):
        return self._fallback_reason

    def __call__(self, *batch, batch_size=None):
        tr = self._trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        nds = [b if isinstance(b, NDArray) else NDArray(jnp.asarray(b))
               for b in batch]
        if batch_size is None:
            batch_size = nds[0].shape[0]
        if self._fallback_reason is not None:
            return self._eager_step(nds, batch_size)
        # deferred-init params can only materialize through a real eager
        # forward — run ONE unfused step, then fold from the next call
        # (mirrors HybridBlock.__call__'s DeferredInit retry)
        if any(p._deferred_init is not None or p._data is None
               for p in tr._params):
            return self._eager_step(nds, batch_size)
        # the folded program embeds the gradient collectives — arm the
        # collective watchdog around the whole dispatch (import at call
        # time: gluon must not import the parallel package at load)
        from ..parallel import elastic as _elastic
        _elastic.watchdog_arm("step_fold.call")
        try:
            return self._folded_step(nds, batch_size)
        finally:
            _elastic.watchdog_disarm()

    def sync(self):
        """Write fold-held state back into the live Parameters/Trainer
        (no-op for the local fold, which swaps buffers every step; the
        multi-process fold keeps donated global registers and syncs
        lazily — ``Trainer.save_states`` calls this)."""
        if self._dist is not None:
            self._dist.sync_out()

    def invalidate(self):
        """Drop compiled programs and (dist) registers so the next call
        re-stages from the live Parameters — required after
        ``load_states`` or direct ``set_data`` on a multi-process fold."""
        self._cache.clear()
        self._dist = None

    # -- fallback path ---------------------------------------------------
    def _note_fallback(self, reason):
        if self._dist is not None:
            # the registers hold the live trajectory; the eager path reads
            # the Parameters — refresh them before switching over
            self._dist.sync_out()
            self._dist = None
        self._fallback_reason = reason
        if not self._warned:
            self._warned = True
            _warnings.warn(
                f"step fold disabled ({reason}); running the eager "
                "record/backward/step path instead — see docs/step_fold.md",
                UserWarning, stacklevel=3)

    def _eager_step(self, nds, batch_size):
        """The unfused reference path: record forward+loss, tape backward,
        ``Trainer.step`` (allreduce + fused optimizer groups).  EVERY
        eager execution through the program counts in
        ``step_fold_fallback`` — the counter quantifies how much of a
        run escaped the fold, not how many distinct reasons there were."""
        _profiler.incr("step_fold_fallback")
        with autograd.record():
            loss = self._loss_fn(*nds)
        autograd.backward([loss])
        self._trainer.step(batch_size)
        return loss

    # -- the folded step -------------------------------------------------
    def _folded_step(self, nds, batch_size):
        tr = self._trainer
        opt = tr._optimizer
        tr._check_and_rescale_grad(tr._scale / batch_size)
        touched = []
        for i, p in enumerate(tr._params):
            if p.grad_req == "null" or p._data is None:
                continue
            if p._data._grad is None:
                raise UserWarning(
                    f"Gradient of Parameter `{p.name}` has no grad buffer")
            if p.grad_req != "write":
                # grad_req='add' accumulates across backwards — a folded
                # step would overwrite the running sum
                self._note_fallback(f"{p.name} has grad_req="
                                    f"{p.grad_req!r} (fold needs 'write')")
                return self._eager_step(nds, batch_size)
            if i not in tr._states:
                tr._states[i] = opt.create_state_multi_precision(i, p.data())
            touched.append((i, p))
        tr._account_memory(touched)
        groups, rest = _fused.plan_groups(
            opt, [(i, p.data(), None) for i, p in touched], tr._states)
        if rest or not groups:
            names = [tr._params[i].name for i, _, _ in rest][:3]
            self._note_fallback(
                f"no fused kernels for {type(opt).__name__} on "
                f"{names or 'these params'} (lazy/sparse or unsupported)")
            return self._eager_step(nds, batch_size)

        # kvstore routing: a dist store either folds in-program (SPMD
        # collectives available) or forces the eager path (async PS —
        # server-side optimizer, host TCP wire)
        kv = tr._kvstore
        dist = kv is not None and kv.num_workers > 1
        if dist and not (hasattr(kv, "_worker_mesh")
                         and kv.supports_grad_bucketing()):
            self._note_fallback(
                f"kvstore {getattr(kv, 'type', kv)!r} cannot fold "
                "(server-side optimizer / async tier)")
            return self._eager_step(nds, batch_size)

        tpos_of = {i: t for t, (i, _) in enumerate(touched)}
        group_sig = tuple(
            (step.__name__, dt, cx,
             tuple(i for i, _, _, _ in members),
             tuple(len(flat) for _, _, _, flat in members))
            for (step, dt, cx), members in groups.items())
        raws = [_raw(nd) for nd in nds]
        batch_sig = tuple((tuple(a.shape), str(a.dtype)) for a in raws)
        key_sig = (batch_sig, group_sig, bool(dist))

        entry = self._cache.get(key_sig)
        fresh = entry is None
        if fresh:
            try:
                entry = self._build(raws, touched, groups, tpos_of, dist, kv)
            except Exception as e:  # capture failure: loud sticky fallback
                self._note_fallback(f"capture failed: {e!r:.200}")
                return self._eager_step(nds, batch_size)
            self._cache[key_sig] = entry

        # per-step dynamic hypers: bump ALL counts first, then read lr/wd
        # (the fused_update discipline — synchronized params all see the
        # same num_update)
        for i, _ in touched:
            opt._update_count(i)
        lrs = jnp.asarray([opt._get_lr(i) for i, _ in touched], jnp.float32)
        wds = jnp.asarray([opt._get_wd(i) for i, _ in touched], jnp.float32)
        ts = jnp.asarray([opt._index_update_count[i] for i, _ in touched],
                         jnp.float32)
        scalars = {k: jnp.asarray(v, jnp.float32)
                   for k, v in _fused._scalars(opt).items()}
        key = get_key()

        return self._dispatch(entry, touched, key, lrs, wds, ts, scalars,
                              raws, fresh)

    def _dispatch(self, entry, touched, key, lrs, wds, ts, scalars, raws,
                  fresh):
        tr = self._trainer
        if self._dist is not None:
            call_args = self._dist.stage_call(key, lrs, wds, ts, scalars,
                                              raws)
        else:
            param_arrs = [_raw(p._data) for p in entry["params"]]
            state_arrs = [tuple(_raw(s) for s in flat)
                          for flat in entry["state_flats"]]
            call_args = (key, lrs, wds, ts, scalars, param_arrs, state_arrs,
                         *raws)
        tc = _perf() if fresh else None
        t0 = _perf() if _profiler._active else None
        try:
            try:
                out = entry["fn"](*call_args)
            except Exception as e:
                # the donated whole-step dispatch is an OOM choke point
                _profiler.maybe_oom_postmortem(e, "gluon.step_fold")
                raise
            loss_local = self._wire_outputs(entry, touched, out)
            if tc is not None:
                # AFTER output wiring: a guard in raise mode must never
                # leave Parameters pointing at donated-and-deleted buffers
                _profiler.record_compile(
                    "gluon.step_fold", self._compile_sig(entry, raws),
                    (_perf() - tc) * 1e3)
            if t0 is not None:
                _profiler.record_span(
                    "trainer.step_fold", "trainer", t0,
                    args={"params": len(touched),
                          "dist": self._dist is not None})
            _profiler.incr("step_fold_call")
            # freshness snapshot (Trainer._update parity): only a future
            # backward/user write may flip a param back to fresh
            for i, p in touched:
                tr._grad_versions[i] = p.grad_version
        finally:
            _profiler.step_boundary()
        if not self._guard_armed:
            self._guard_armed = True
            _profiler.arm_compile_guard("gluon.step_fold")
        return loss_local

    def _compile_sig(self, entry, raws):
        sig = {"__program__": "step_fold" + (":dist" if entry["dist"]
                                             else ""),
               "params": _profiler.sig_static(len(entry["params"])),
               "groups": _profiler.sig_static(
                   [g[0] for g in entry["plan_names"]])}
        for i, a in enumerate(raws):
            sig[f"in{i}"] = {"k": "array", "shape": tuple(a.shape),
                             "dtype": str(a.dtype)}
        return sig

    def _warn_foreign_aux(self, aux_cell):
        """One loud warning when the capture saw aux updates for params
        the trainer doesn't own: their OLD value is a baked trace
        constant, so they stay FROZEN in-fold (pass the block's full
        ``collect_params()`` to the Trainer to fold them)."""
        foreign = aux_cell[0][1] if aux_cell else []
        if foreign:
            _warnings.warn(
                "step fold: aux updates for parameters the Trainer does "
                f"not own stay FROZEN inside the fold ({foreign[:3]}...); "
                "construct the Trainer with the block's full "
                "collect_params() to fold their running stats — "
                "docs/step_fold.md", UserWarning, stacklevel=4)

    def _wire_outputs(self, entry, touched, out):
        """Swap the program's fresh buffers into the live NDArrays (local
        fold) or registers (dist fold).  Returns the loss NDArray."""
        if self._dist is not None:
            return self._dist.wire(entry, touched, out, self._keep_grads)
        it = iter(out)
        new_params, new_states, loss_data = next(it), next(it), next(it)
        grads = next(it) if self._keep_grads else None
        for p, arr in zip(entry["params"], new_params):
            _swap(p._data, arr)
        for flat, new in zip(entry["state_flats"], new_states):
            for s_nd, s_new in zip(flat, new):
                _swap(s_nd, s_new)
        if grads is not None:
            for (_, p), g in zip(touched, grads):
                _swap(p._data._grad, g)
        return NDArray(loss_data)

    # -- capture ---------------------------------------------------------
    def _build(self, raws, touched, groups, tpos_of, dist, kv):
        """Trace + jit the whole step.  Returns the cache entry dict.  The
        capture is validated with ``jax.eval_shape`` (no device work), so
        a loss_fn the tracer cannot swallow fails HERE — cleanly — and the
        caller falls back to the eager path."""
        tr = self._trainer
        params = [p for p in tr._params if p._data is not None]
        slot_of = {id(p): s for s, p in enumerate(params)}
        trainable_slots = [slot_of[id(p)] for _, p in touched]
        state_flats = [None] * len(touched)
        plan = []        # (step_fn, [(tpos, slot)])
        plan_names = []
        for (step, dt, cx), members in groups.items():
            rows = []
            for i, w, _, flat in members:
                t = tpos_of[i]
                state_flats[t] = tuple(flat)
                rows.append((t, slot_of[id(tr._params[i])]))
            plan.append((step, tuple(rows)))
            plan_names.append((step.__name__, dt, len(members)))
        loss_fn = self._loss_fn
        keep_grads = self._keep_grads
        aux_cell = []     # [(in_slots, out_params)] discovered on trace 1
        loss_meta = []    # [ndim] of the user loss

        def forward_loss(train_arrs, full_arrs, key, batch):
            full = list(full_arrs)
            for s, arr in zip(trainable_slots, train_arrs):
                full[s] = arr
            with trace_scope(params, full, key, True) as collector:
                loss = loss_fn(*[NDArray(b) for b in batch])
            loss_data = loss._data
            if not loss_meta:
                loss_meta.append(loss_data.ndim)
            if not aux_cell:
                # per-POSITION ownership (slot index, or None for a param
                # the trainer doesn't hold): owned and foreign aux may
                # interleave in forward order.  Foreign aux updates are
                # DROPPED, not written back — the old value is baked into
                # the trace as a constant, so a write-back would keep
                # re-deriving the update from the original stats forever
                # (frozen is honest; a warning surfaces it at build).
                kinds, foreign = [], []
                for p, _ in collector:
                    s = slot_of.get(id(p))
                    kinds.append(s)
                    if s is None:
                        foreign.append(p.name)
                aux_cell.append((kinds, foreign))
            aux_vals = tuple(v._data if isinstance(v, NDArray) else v
                             for _, v in collector)
            # differentiate the SUM in the loss's own dtype — exact parity
            # with loss.backward()'s implicit ones head-grads
            return jnp.sum(loss_data), (aux_vals, loss_data)

        def optimizer_tail(param_arrs, state_arrs, grads, lrs, wds, ts,
                           scalars):
            new_full = list(param_arrs)
            new_states = list(state_arrs)
            for step, rows in plan:
                for t, s in rows:
                    nw, ns = step(param_arrs[s], grads[t], state_arrs[t],
                                  lrs[t], wds[t], ts[t], scalars)
                    new_full[s] = nw
                    new_states[t] = tuple(ns)
            return new_full, new_states

        def apply_aux(new_full, param_arrs, aux_vals):
            kinds, _ = aux_cell[0]
            for s, v in zip(kinds, aux_vals):
                if s is not None:
                    new_full[s] = v.astype(param_arrs[s].dtype)

        if dist:
            return self._build_dist(raws, touched, params, state_flats,
                                    plan, plan_names, trainable_slots,
                                    forward_loss, optimizer_tail, apply_aux,
                                    aux_cell, loss_meta, kv)

        def pure_step(key, lrs, wds, ts, scalars, param_arrs, state_arrs,
                      *batch):
            train_arrs = [param_arrs[s] for s in trainable_slots]
            (_, (aux_vals, loss_data)), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(train_arrs, param_arrs, key,
                                            batch)
            new_full, new_states = optimizer_tail(
                param_arrs, state_arrs, grads, lrs, wds, ts, scalars)
            apply_aux(new_full, param_arrs, aux_vals)
            out = (new_full, new_states, loss_data)
            if keep_grads:
                out += (list(grads),)
            return out

        # abstract validation pass — populates aux_cell/loss_meta and
        # surfaces capture failures without any device work.  The key aval
        # comes from a FRESH PRNGKey(0), never get_key(): splitting the
        # ambient stream at build time would desync fold-vs-unfused
        # dropout parity by one key.
        ex_key = jax.random.PRNGKey(0)
        key_aval = jax.ShapeDtypeStruct(ex_key.shape, ex_key.dtype)
        abstract = (
            key_aval,
            jax.ShapeDtypeStruct((len(touched),), jnp.float32),
            jax.ShapeDtypeStruct((len(touched),), jnp.float32),
            jax.ShapeDtypeStruct((len(touched),), jnp.float32),
            {k: jax.ShapeDtypeStruct((), jnp.float32)
             for k in _fused._scalars(tr._optimizer)},
            [jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
             for p in params],
            [tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                   for s in flat) for flat in state_flats],
            *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in raws],
        )
        jax.eval_shape(pure_step, *abstract)
        self._warn_foreign_aux(aux_cell)
        donate = (5, 6) if _fused.donation_enabled() else ()
        fn = jax.jit(pure_step, donate_argnums=donate)
        return {"fn": fn, "params": params, "state_flats": state_flats,
                "plan_names": plan_names, "dist": False}

    # -- the multi-process (in-fold collectives) build -------------------
    def _build_dist(self, raws, touched, params, state_flats, plan,
                    plan_names, trainable_slots, forward_loss,
                    optimizer_tail, apply_aux, aux_cell, loss_meta, kv):
        """Fold the gradient exchange into the program: forward/backward
        per worker shard under ONE ``shard_map`` over the kvstore's worker
        mesh, with each size-capped gradient bucket an explicit allreduce
        node (fp32 ``psum``, or the PR 14 codec's in-program quantized
        exchange) that XLA may schedule as soon as that bucket's grads
        exist — comms overlapped against the remaining backward.  The
        optimizer tail then runs on the replicated reduced grads."""
        from jax.sharding import PartitionSpec as P

        from .. import kvstore as kv_mod
        from ..comm import compression as comp_mod
        from ..parallel.mesh import get_shard_map

        tr = self._trainer
        mesh = kv._worker_mesh()
        keep_grads = self._keep_grads
        policy = comp_mod.resolve_policy()
        ef = policy is not None and policy.error_feedback

        # THE deterministic bucket rule (kvstore.plan_buckets — shared
        # with bucketed_pushpull and the overlap hook, so in-fold and
        # out-of-fold paths can never draw different bucket boundaries);
        # positions index ``touched`` order = the grads list
        _, kv_buckets = kv_mod.plan_buckets(
            [(i, p.grad()) for i, p in touched],
            names=[p.name for _, p in touched], compression=policy)
        buckets = []   # (codec|None, [(tpos, off, n, shape)])
        for bk in kv_buckets:
            rows, off = [], 0
            for t in bk["positions"]:
                a = touched[t][1]._data._data
                rows.append((t, off, int(a.size), tuple(a.shape)))
                off += int(a.size)
            buckets.append((bk["codec"], tuple(rows)))
        n_train = len(touched)
        smap = get_shard_map()
        P0 = P()
        PW = P("w")
        batch_specs = tuple(P(*(("w",) + (None,) * (a.ndim - 1)))
                            for a in raws)

        def shard_body(train_arrs, full_arrs, key, residuals, *batch):
            # distinct PRNG stream per worker — the documented dist-fold
            # convention (matches the SPMD quantized-collective build)
            key = jax.random.fold_in(key, jax.lax.axis_index("w"))
            (_, (aux_vals, loss_data)), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(train_arrs, full_arrs, key,
                                            batch)
            new_grads = [None] * n_train
            new_resid = []
            ri = 0
            for codec, rows in buckets:
                flat = jnp.concatenate(
                    [grads[t].reshape(-1) for t, _, _, _ in rows])
                if codec is None:
                    red = jax.lax.psum(flat, "w")
                else:
                    red, resid = comp_mod.traced_allreduce(
                        codec, flat, residuals[ri][0] if ef else None,
                        ("w",))
                    if ef:
                        new_resid.append(resid[None, :])
                        ri += 1
                for t, off, n, shape in rows:
                    new_grads[t] = red[off:off + n].reshape(shape)
            # local loss leaves sharded over 'w' (each worker reads its
            # own shard — parity with the per-worker eager loss); aux
            # stats pmean so every worker applies the same running stats
            loss_out = loss_data if loss_data.ndim >= 1 \
                else loss_data[None]
            aux_vals = tuple(jax.lax.pmean(a, "w") for a in aux_vals)
            return (tuple(new_grads), tuple(new_resid), loss_out, aux_vals)

        def pure_step(key, lrs, wds, ts, scalars, param_arrs, state_arrs,
                      residuals, *batch):
            train_arrs = [param_arrs[s] for s in trainable_slots]
            mapped = smap(
                shard_body, mesh=mesh,
                in_specs=(P0, P0, P0, PW) + batch_specs,
                out_specs=(P0, PW, PW, P0))
            grads_t, new_resid, loss_out, aux_vals = mapped(
                train_arrs, list(param_arrs), key, tuple(residuals), *batch)
            new_full, new_states = optimizer_tail(
                param_arrs, state_arrs, list(grads_t), lrs, wds, ts,
                scalars)
            apply_aux(new_full, param_arrs, aux_vals)
            out = (new_full, new_states, list(new_resid), loss_out)
            if keep_grads:
                out += (list(grads_t),)
            return out

        if self._dist is not None:
            # a rebuild (new batch signature): the live Parameters are
            # stale — refresh them from the old registers before re-staging
            self._dist.sync_out()
        regs = _DistRegisters(tr, params, state_flats, mesh,
                              buckets if ef else [], loss_meta)
        self._dist = regs
        donate = (5, 6, 7) if _fused.donation_enabled() else ()
        with mesh:
            fn = jax.jit(pure_step, donate_argnums=donate)
        # validation trace (abstract; global shapes)
        ex_key = jax.random.PRNGKey(0)
        key_aval = jax.ShapeDtypeStruct(ex_key.shape, ex_key.dtype)
        nw = mesh.devices.size
        abstract = (
            key_aval,
            jax.ShapeDtypeStruct((n_train,), jnp.float32),
            jax.ShapeDtypeStruct((n_train,), jnp.float32),
            jax.ShapeDtypeStruct((n_train,), jnp.float32),
            {k: jax.ShapeDtypeStruct((), jnp.float32)
             for k in _fused._scalars(tr._optimizer)},
            [jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
             for p in params],
            [tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                   for s in flat) for flat in state_flats],
            [jax.ShapeDtypeStruct((nw, n), jnp.float32)
             for n in regs.resid_sizes],
            *[jax.ShapeDtypeStruct((a.shape[0] * nw,) + tuple(a.shape[1:]),
                                   a.dtype) for a in raws],
        )
        with mesh:
            jax.eval_shape(pure_step, *abstract)
        self._warn_foreign_aux(aux_cell)
        return {"fn": fn, "params": params, "state_flats": state_flats,
                "plan_names": plan_names, "dist": True}


class _DistRegisters:
    """Donated global registers for the multi-process fold: replicated
    params/optimizer state and sharded error-feedback residuals live as
    jax global arrays across steps (zero per-step staging); Parameters and
    ``trainer._states`` are refreshed lazily via ``sync_out``."""

    def __init__(self, trainer, params, state_flats, mesh, ef_buckets,
                 loss_meta):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._trainer = trainer
        self._params = params
        self._state_flats = state_flats
        self._mesh = mesh
        self._loss_meta = loss_meta
        self._rep = NamedSharding(mesh, P())
        self._row = NamedSharding(mesh, P("w"))
        self.param_arrays = [self._replicate(_raw(p._data)) for p in params]
        self.state_arrays = [tuple(self._replicate(_raw(s)) for s in flat)
                             for flat in state_flats]
        self.resid_sizes = [sum(n for _, _, n, _ in rows)
                            for codec, rows in ef_buckets
                            if codec is not None]
        # error-feedback residuals persist through the trainer's
        # ErrorFeedback store (the PR 14 contract: save_states carries
        # them, a rebuild re-stages them — never silently zeroed); each
        # process stages its OWN local rows, per-host-file style
        import jax as _jax

        nw = mesh.devices.size
        local_rows = max(1, nw // _jax.process_count())
        self.residuals = []
        for b, n in enumerate(self.resid_sizes):
            local = None
            fb = trainer._grad_feedback
            if fb is not None:
                stored = fb._res.get(self._resid_key(b, n))
                if stored is not None and \
                        tuple(_np.shape(stored)) == (local_rows, n):
                    local = _np.asarray(stored, _np.float32)
            if local is None:
                local = _np.zeros((local_rows, n), _np.float32)
            self.residuals.append(self._stage_rows(local))

    def _replicate(self, arr):
        import jax as _jax

        local = _jax.device_put(_np.asarray(arr),
                                self._mesh.local_devices[0])
        return _jax.make_array_from_single_device_arrays(
            tuple(local.shape), self._rep, [local])

    @staticmethod
    def _resid_key(b, n):
        return f"__fold_dist__:{b}:{n}"

    def _stage_rows(self, local):
        """This process's residual rows -> the 'w'-sharded global array."""
        import jax as _jax

        if _jax.process_count() == 1:
            return _jax.device_put(local, self._row)
        return _jax.make_array_from_process_local_data(self._row, local)

    def _global_batch(self, arr):
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(*(("w",) + (None,) * (arr.ndim - 1)))
        sharding = NamedSharding(self._mesh, spec)
        return _jax.make_array_from_process_local_data(
            sharding, _np.asarray(arr))

    def stage_call(self, key, lrs, wds, ts, scalars, raws):
        rep = self._replicate
        return (rep(key), rep(lrs), rep(wds), rep(ts),
                {k: rep(v) for k, v in scalars.items()},
                self.param_arrays, self.state_arrays, self.residuals,
                *[self._global_batch(a) for a in raws])

    def wire(self, entry, touched, out, keep_grads):
        # everything stays DEVICE-RESIDENT: addressable_data(0) hands back
        # this process's shard buffer without a host sync — an immediate
        # np.asarray here would block dispatch on the whole step's device
        # completion every step and forfeit the overlap the fold buys
        # (the PR 12 MoE-extras lesson); sync_out() is the host boundary
        it = iter(out)
        new_params, new_states, new_resid, loss_out = (
            next(it), next(it), next(it), next(it))
        grads = next(it) if keep_grads else None
        self.param_arrays = new_params
        self.state_arrays = [tuple(s) for s in new_states]
        self.residuals = list(new_resid)
        if grads is not None:
            for (_, p), g in zip(touched, grads):
                p._data._grad._data = g.addressable_data(0)
                p._data._grad._version += 1
        local = loss_out.addressable_data(0)
        if self._loss_meta and self._loss_meta[0] == 0:
            local = local.reshape(())
        return NDArray(local)

    def sync_out(self):
        """Fold registers -> live Parameters / trainer states (gathered
        off the mesh so eager ops see single-device arrays).  Residuals
        land in the trainer's ErrorFeedback store so ``save_states``
        persists them and a rebuild re-stages them."""
        with autograd.pause():
            for p, a in zip(self._params, self.param_arrays):
                p._data._data = jnp.asarray(_np.asarray(
                    a.addressable_data(0)))
                p._data._version += 1
            for flat, arrs in zip(self._state_flats, self.state_arrays):
                for s_nd, a in zip(flat, arrs):
                    s_nd._data = jnp.asarray(_np.asarray(
                        a.addressable_data(0)))
                    s_nd._version += 1
        if self.residuals:
            from ..comm import compression as comp_mod

            tr = self._trainer
            if tr._grad_feedback is None:
                tr._grad_feedback = comp_mod.ErrorFeedback()
            for b, (n, arr) in enumerate(zip(self.resid_sizes,
                                             self.residuals)):
                tr._grad_feedback.update(
                    self._resid_key(b, n),
                    _np.asarray(arr.addressable_data(0)))


# ---------------------------------------------------------------------------
# The MXNET_STEP_FOLD=1 fast path inside Trainer.step: fold the whole
# optimizer tail (every fused group) into ONE donated jitted dispatch.
# ---------------------------------------------------------------------------

_TAIL_JITS = {}


def _tail_fn(plan_key, steps, donate):
    fn = _TAIL_JITS.get((plan_key, donate))
    if fn is None:
        def body(weights, grads, states, lrs, wds, ts, scalars):
            new_w = []
            new_s = []
            for g, step in enumerate(steps):
                gw, gs = [], []
                for m in range(len(weights[g])):
                    nw, ns = step(weights[g][m], grads[g][m], states[g][m],
                                  lrs[g][m], wds[g][m], ts[g][m], scalars)
                    gw.append(nw)
                    gs.append(list(ns))
                new_w.append(gw)
                new_s.append(gs)
            return new_w, new_s

        fn = jax.jit(body, donate_argnums=(0, 2) if donate else ())
        _TAIL_JITS[(plan_key, donate)] = fn
        while len(_TAIL_JITS) > 64:
            _TAIL_JITS.pop(next(iter(_TAIL_JITS)))
    return fn


def fold_update(optimizer, items, states):
    """Folded optimizer tail — :func:`optimizer.fused.fused_update`'s
    drop-in twin that updates EVERY fused group in one donated jitted
    dispatch instead of one ``group_apply`` per group (the
    ``MXNET_STEP_FOLD=1`` fast path inside ``Trainer.step``).  Returns the
    leftover per-tensor items, exactly like ``fused_update``."""
    agg = int(getattr(optimizer, "aggregate_num", 0) or 0)
    if agg <= 1 or not items or _engine.is_naive():
        return items
    groups, rest = _fused.plan_groups(optimizer, items, states)
    if not groups:
        return rest
    # bump ALL counts first, then read lr/wd/t (fused_update discipline)
    for members in groups.values():
        for i, _, _, _ in members:
            optimizer._update_count(i)
    ws, gs, sts, lrs, wds, ts, flats = [], [], [], [], [], [], []
    steps = []
    plan_key_parts = []
    for (step, dt, cx), members in groups.items():
        steps.append(step)
        plan_key_parts.append((step, len(members)))
        ws.append([_fused._concrete(w) for _, w, _, _ in members])
        gs.append([_fused._concrete(g) for _, _, g, _ in members])
        sts.append([[_fused._concrete(s) for s in flat]
                    for _, _, _, flat in members])
        lrs.append(jnp.asarray([optimizer._get_lr(i)
                                for i, _, _, _ in members], jnp.float32))
        wds.append(jnp.asarray([optimizer._get_wd(i)
                                for i, _, _, _ in members], jnp.float32))
        ts.append(jnp.asarray([optimizer._index_update_count[i]
                               for i, _, _, _ in members], jnp.float32))
        flats.append([flat for _, _, _, flat in members])
    scalars = {k: jnp.asarray(v, jnp.float32)
               for k, v in _fused._scalars(optimizer).items()}
    donate = _fused.donation_enabled()
    fn = _tail_fn(tuple(plan_key_parts), tuple(steps), donate)
    n_params = sum(len(m) for m in ws)
    n0 = _profiler.jit_cache_size(fn)
    tc = _perf()
    t0 = tc if _profiler._active else None
    guard_err = None
    try:
        new_w, new_s = fn(ws, gs, sts, lrs, wds, ts, scalars)
    except Exception as e:
        _profiler.maybe_oom_postmortem(e, "gluon.step_fold")
        raise
    compiled = n0 >= 0 and _profiler.jit_cache_size(fn) > n0
    if compiled:
        sig = {"__program__": "update_tail",
               "groups": _profiler.sig_static(
                   [(getattr(s, "__name__", "?"), n)
                    for s, n in plan_key_parts])}
        k = 0
        for grp in ws:
            for w in grp:
                sig[f"w{k}"] = {"k": "array", "shape": tuple(w.shape),
                                "dtype": str(w.dtype)}
                k += 1
        try:
            _profiler.record_compile("gluon.step_fold", sig,
                                     (_perf() - tc) * 1e3)
        except _profiler.CompileGuardError as e:
            guard_err = e   # buffers are donated: wire first, raise after
    for g, members in enumerate(groups.values()):
        for m, (_, w, _, _) in enumerate(members):
            _swap(w, new_w[g][m])
            for s_nd, s_new in zip(flats[g][m], new_s[g][m]):
                _swap(s_nd, s_new)
    if t0 is not None:
        _profiler.record_span("fused.group_apply", "optimizer", t0,
                              args={"params": n_params,
                                    "groups": len(groups), "folded": True})
    _profiler.incr("fused_step_call")
    _profiler.incr("fused_step_params", n_params)
    if guard_err is not None:
        raise guard_err
    if rest:
        _profiler.incr("fused_step_fallback_params", len(rest))
    return rest
