"""Recurrent cells (parity: [U:python/mxnet/gluon/rnn/rnn_cell.py]).

Gate orders match the reference exactly (LSTM: [i, f, g, o] with the
forget-gate slice at [h:2h] — the contract LSTMBias init depends on;
GRU: [r, z, n]), so checkpoints and ported code behave identically.
Cells unroll as Python loops (fine under trace: the graph unrolls); the
fused lax.scan path lives in rnn_layer.py.
"""
from __future__ import annotations

from ..block import HybridBlock

__all__ = [
    "RecurrentCell",
    "RNNCell",
    "LSTMCell",
    "GRUCell",
    "SequentialRNNCell",
    "DropoutCell",
    "ResidualCell",
    "BidirectionalCell",
    "ZoneoutCell",
]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd

        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape, **{**info, **kwargs}))
        return states

    def __call__(self, inputs, states, *args):
        self._counter += 1
        return super().__call__(inputs, states, *args)

    def forward(self, inputs, states):
        from ..block import HybridBlock as _HB

        return _HB.forward(self, inputs, states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None,
               valid_length=None):
        """Unroll over time (parity: ``RecurrentCell.unroll``)."""
        from ... import ndarray as nd

        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            inputs = [
                nd.squeeze(nd.slice_axis(inputs, axis=axis, begin=i, end=i + 1), axis=axis)
                for i in range(length)
            ]
        if begin_state is None:
            batch = inputs[0].shape[0]
            begin_state = self.begin_state(batch_size=batch, ctx=inputs[0].context)
        states = begin_state
        outputs = []
        for i in range(length):
            out, states = self(inputs[i], states)
            outputs.append(out)
        if valid_length is not None:
            stacked = nd.stack(*outputs, axis=axis)
            stacked = nd.SequenceMask(
                stacked if axis == 0 else nd.swapaxes(stacked, 0, 1),
                sequence_length=valid_length,
                use_sequence_length=True,
            )
            if axis != 0:
                stacked = nd.swapaxes(stacked, 0, 1)
            outputs = stacked
            if merge_outputs is False:
                outputs = [
                    nd.squeeze(nd.slice_axis(outputs, axis=axis, begin=i, end=i + 1), axis=axis)
                    for i in range(length)
                ]
        elif merge_outputs or merge_outputs is None:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs)


class RNNCell(RecurrentCell):
    """Vanilla RNN cell (parity: ``rnn.RNNCell``)."""

    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(hidden_size, input_size),
                                              init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(hidden_size, hidden_size),
                                              init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                            init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def _shape_inference(self, x, *args):
        self.i2h_weight._finish_deferred_init((self._hidden_size, x.shape[-1]))
        self.h2h_weight._finish_deferred_init((self._hidden_size, self._hidden_size))
        self.i2h_bias._finish_deferred_init((self._hidden_size,))
        self.h2h_bias._finish_deferred_init((self._hidden_size,))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias, num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(RecurrentCell):
    """LSTM cell, gate order [i, f, g, o] (parity: ``rnn.LSTMCell``)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros", input_size=0,
                 activation="tanh", recurrent_activation="sigmoid", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._activation = activation
        self._recurrent_activation = recurrent_activation
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(4 * hidden_size, input_size),
                                              init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(4 * hidden_size, hidden_size),
                                              init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,),
                                            init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,),
                                            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [
            {"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
            {"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
        ]

    def _alias(self):
        return "lstm"

    def _shape_inference(self, x, *args):
        self.i2h_weight._finish_deferred_init((4 * self._hidden_size, x.shape[-1]))
        self.h2h_weight._finish_deferred_init((4 * self._hidden_size, self._hidden_size))
        self.i2h_bias._finish_deferred_init((4 * self._hidden_size,))
        self.h2h_bias._finish_deferred_init((4 * self._hidden_size,))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias, num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.split(gates, num_outputs=4, axis=1)
        in_gate = self._get_activation(F, slices[0], self._recurrent_activation)
        forget_gate = self._get_activation(F, slices[1], self._recurrent_activation)
        in_transform = self._get_activation(F, slices[2], self._activation)
        out_gate = self._get_activation(F, slices[3], self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class GRUCell(RecurrentCell):
    """GRU cell, gate order [r, z, n] (parity: ``rnn.GRUCell``)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros", input_size=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(3 * hidden_size, input_size),
                                              init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(3 * hidden_size, hidden_size),
                                              init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,),
                                            init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,),
                                            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _shape_inference(self, x, *args):
        self.i2h_weight._finish_deferred_init((3 * self._hidden_size, x.shape[-1]))
        self.h2h_weight._finish_deferred_init((3 * self._hidden_size, self._hidden_size))
        self.i2h_bias._finish_deferred_init((3 * self._hidden_size,))
        self.h2h_bias._finish_deferred_init((3 * self._hidden_size,))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight, i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias, num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=1)
        reset = F.sigmoid(i2h_r + h2h_r)
        update = F.sigmoid(i2h_z + h2h_z)
        nextg = F.tanh(i2h_n + reset * h2h_n)
        next_h = (1.0 - update) * nextg + update * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (parity: ``rnn.SequentialRNNCell``)."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p : p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def hybrid_forward(self, *args, **kwargs):
        raise NotImplementedError


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class _ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, **kwargs):
        return self.base_cell.begin_state(**kwargs)


class ResidualCell(_ModifierCell):
    """Parity: ``rnn.ResidualCell``."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError


class ZoneoutCell(_ModifierCell):
    """Parity: ``rnn.ZoneoutCell`` — stochastic state preservation."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        from ... import ndarray as nd
        from ... import autograd

        next_output, next_states = self.base_cell(inputs, states)
        if not autograd.is_training():
            return next_output, next_states
        po, ps = self.zoneout_outputs, self.zoneout_states

        def mask(p, like):
            return nd.Dropout(nd.ones_like(like), p=p, training=True)

        prev_output = self._prev_output if self._prev_output is not None else nd.zeros_like(next_output)
        output = (
            nd.where(mask(po, next_output), next_output, prev_output) if po > 0 else next_output
        )
        new_states = (
            [nd.where(mask(ps, ns), ns, s) for ns, s in zip(next_states, states)]
            if ps > 0
            else next_states
        )
        self._prev_output = output
        return output, new_states

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError


class BidirectionalCell(RecurrentCell):
    """Parity: ``rnn.BidirectionalCell`` (unroll-only, like the reference)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix=None, params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        raise NotImplementedError("Bidirectional cannot be stepped. Please use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None, valid_length=None):
        from ... import ndarray as nd

        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            inputs = [
                nd.squeeze(nd.slice_axis(inputs, axis=axis, begin=i, end=i + 1), axis=axis)
                for i in range(length)
            ]
        batch = inputs[0].shape[0]
        l_cell, r_cell = self._children["l_cell"], self._children["r_cell"]
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch, ctx=inputs[0].context)
        n_l = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(
            length, inputs, begin_state[:n_l], layout, merge_outputs=False, valid_length=valid_length
        )

        def _reverse_seq(seq):
            """Reverse only the valid prefix per sample when valid_length is
            given (parity: upstream uses SequenceReverse)."""
            if valid_length is None:
                return list(reversed(seq))
            stacked = nd.stack(*seq, axis=0)  # (T, B, ...)
            rev = nd.SequenceReverse(stacked, sequence_length=valid_length, use_sequence_length=True)
            return [
                nd.squeeze(nd.slice_axis(rev, axis=0, begin=i, end=i + 1), axis=0)
                for i in range(length)
            ]

        r_out, r_states = r_cell.unroll(
            length, _reverse_seq(inputs), begin_state[n_l:], layout, merge_outputs=False,
            valid_length=valid_length,
        )
        if isinstance(r_out, list):
            r_out = _reverse_seq(r_out)
        outputs = [nd.concat(lo, ro, dim=1) for lo, ro in zip(l_out, r_out)]
        if merge_outputs or merge_outputs is None:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, l_states + r_states

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError
