"""``gluon.rnn`` (parity: [U:python/mxnet/gluon/rnn/])."""
from .rnn_cell import (
    RecurrentCell,
    RNNCell,
    LSTMCell,
    GRUCell,
    SequentialRNNCell,
    DropoutCell,
    ResidualCell,
    BidirectionalCell,
    ZoneoutCell,
)
from .rnn_layer import RNN, LSTM, GRU

__all__ = [
    "RecurrentCell",
    "RNNCell",
    "LSTMCell",
    "GRUCell",
    "SequentialRNNCell",
    "DropoutCell",
    "ResidualCell",
    "BidirectionalCell",
    "ZoneoutCell",
    "RNN",
    "LSTM",
    "GRU",
]

# reference alias: the hybridizable sequential container shares the
# implementation here (cells are already hybrid-safe)
HybridSequentialRNNCell = SequentialRNNCell  # noqa: F405
