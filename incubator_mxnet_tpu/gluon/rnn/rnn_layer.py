"""Fused RNN layers (parity: [U:python/mxnet/gluon/rnn/rnn_layer.py] —
``rnn.RNN/LSTM/GRU`` backed by the fused op in ops/rnn_ops.py, the cuDNN
path's TPU equivalent).  Parameter naming matches the reference
(``{l|r}{k}_i2h_weight`` ...) so checkpoints transfer."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert layout in ("TNC", "NTC"), f"Invalid layout {layout}; must be TNC or NTC"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for j in ["l", "r"][: self._dir]:
                    name = f"{j}{i}_"
                    setattr(self, f"{name}i2h_weight", self.params.get(
                        f"{name}i2h_weight", shape=(ng * nh, ni if i == 0 else nh * self._dir),
                        init=i2h_weight_initializer, allow_deferred_init=True))
                    setattr(self, f"{name}h2h_weight", self.params.get(
                        f"{name}h2h_weight", shape=(ng * nh, nh),
                        init=h2h_weight_initializer, allow_deferred_init=True))
                    setattr(self, f"{name}i2h_bias", self.params.get(
                        f"{name}i2h_bias", shape=(ng * nh,),
                        init=i2h_bias_initializer, allow_deferred_init=True))
                    setattr(self, f"{name}h2h_bias", self.params.get(
                        f"{name}h2h_bias", shape=(ng * nh,),
                        init=h2h_bias_initializer, allow_deferred_init=True))

    def state_info(self, batch_size=0):
        if self._mode == "lstm":
            return [
                {"shape": (self._num_layers * self._dir, batch_size, self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size, self._hidden_size), "__layout__": "LNC"},
            ]
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size), "__layout__": "LNC"}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd

        if func is None:
            func = nd.zeros
        return [func(info["shape"], **kwargs) for info in self.state_info(batch_size)]

    def _shape_inference(self, x, *args):
        in_size = x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                name = f"{j}{i}_"
                getattr(self, f"{name}i2h_weight")._finish_deferred_init(
                    (ng * nh, in_size if i == 0 else nh * self._dir))
                getattr(self, f"{name}h2h_weight")._finish_deferred_init((ng * nh, nh))
                getattr(self, f"{name}i2h_bias")._finish_deferred_init((ng * nh,))
                getattr(self, f"{name}h2h_bias")._finish_deferred_init((ng * nh,))

    def hybrid_forward(self, F, inputs, states=None, **params):
        from ... import ndarray as nd
        from ... import autograd
        from ...random import get_key

        if self._layout == "NTC":
            inputs = nd.swapaxes(inputs, 0, 1)
        batch = inputs.shape[1]
        skip_states = states is None
        if states is None:
            states = self.begin_state(batch, ctx=inputs.context, dtype=inputs.dtype)
        if not isinstance(states, (list, tuple)):
            states = [states]
        h0 = states[0]
        c0 = states[1] if self._mode == "lstm" else states[0]
        weights = []
        for i in range(self._num_layers):
            for j in ["l", "r"][: self._dir]:
                name = f"{j}{i}_"
                weights.extend([
                    params[f"{name}i2h_weight"],
                    params[f"{name}h2h_weight"],
                    params[f"{name}i2h_bias"],
                    params[f"{name}h2h_bias"],
                ])
        training = autograd.is_training()
        out = nd.RNNFused(
            inputs, h0, c0, *weights,
            mode=self._mode, num_layers=self._num_layers, hidden_size=self._hidden_size,
            bidirectional=self._dir == 2, dropout=self._dropout, training=training,
            key=get_key() if (self._dropout > 0 and training) else None,
        )
        if self._mode == "lstm":
            output, h_n, c_n = out
            out_states = [h_n, c_n]
        else:
            output, h_n = out
            out_states = [h_n]
        if self._layout == "NTC":
            output = nd.swapaxes(output, 0, 1)
        if skip_states:
            return output
        return output, out_states

    def __repr__(self):
        return (f"{type(self).__name__}({self._input_size or '?'} -> {self._hidden_size}, "
                f"{self._layout}, layers={self._num_layers}"
                + (", bidirectional" if self._dir == 2 else "") + ")")


class RNN(_RNNLayer):
    """Parity: ``rnn.RNN``."""

    def __init__(self, hidden_size, num_layers=1, activation="relu", layout="TNC", dropout=0,
                 bidirectional=False, i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros", input_size=0,
                 prefix=None, params=None):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, prefix=prefix, params=params)


class LSTM(_RNNLayer):
    """Parity: ``rnn.LSTM`` (fused lax.scan; cuDNN-path equivalent)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0, bidirectional=False,
                 input_size=0, i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", prefix=prefix, params=params)


class GRU(_RNNLayer):
    """Parity: ``rnn.GRU``."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0, bidirectional=False,
                 input_size=0, i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros", prefix=None, params=None):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", prefix=prefix, params=params)
