"""Gluon Trainer.

Parity target: [U:python/mxnet/gluon/trainer.py].  Same API and step
semantics (``step(batch_size)`` = allreduce grads, then optimizer update
with ``rescale_grad = 1/batch_size``).  The reference binds params to a
KVStore for cross-device aggregation; here single-process gradients already
live on one (possibly mesh-sharded) array, and multi-host aggregation rides
the kvstore facade ('dist_sync' → psum inside the compiled step — see
kvstore/ and parallel/).

Two grouped fast paths (docs/optimizer_fusion.md):

* ``_update`` routes supported optimizers through the fused whole-group
  step (optimizer/fused.py): one jitted, buffer-donating dispatch per
  parameter group instead of one kernel launch + buffer swap per tensor.
* ``allreduce_grads`` against a dist kvstore buckets gradients into
  size-capped flat buffers (kvstore.bucketed_pushpull), so the wire sees a
  few large pushpulls instead of one per parameter.
"""
from __future__ import annotations

import warnings

from .. import profiler as _profiler
from .. import optimizer as opt_mod
from ..optimizer import fused as _fused
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(
        self,
        params,
        optimizer,
        optimizer_params=None,
        kvstore="device",
        compression_params=None,
        update_on_kvstore=None,
    ):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError("First argument must be a list or dict of Parameters")
        self._all_params = list(params)
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise ValueError(f"First argument must be a list or dict of Parameters, got {type(p)}")
            self._param2idx[p.name] = i
            self._params.append(p)
        self._compression_params = compression_params
        self._contains_sparse = False
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._update_on_kvstore = update_on_kvstore
        self._states = {}
        self._last_scale_set = None   # last rescale_grad THIS trainer wrote
        self._grad_versions = {}      # index -> grad buffer version at last update
        self._grad_feedback = None    # comm.ErrorFeedback when compression
                                      # with error feedback is active
        self._overlap_reduced = None  # param indices whose buckets already
                                      # pushpulled from the grad-readiness
                                      # hook (Trainer.backward overlap path)
        self._fold = None             # weakref to the last fold_step program
        # device-memory ledger accounting (docs/observability.md#device-
        # memory-observability): indices whose weight+grad+state bytes
        # have been reported, and the totals to release on close() — or
        # at GC via the finalizer, so a trainer dropped without close()
        # (the common local path) cannot leak ledger bytes.
        # Donation-aware by construction — the fused step swaps buffers
        # of identical shape/dtype, so accounted bytes never move.
        import weakref as _weakref

        self._mem_idx = set()
        self._mem_bytes = [0, 0]      # [params+grads, optimizer state]
        self._mem_finalizer = _weakref.finalize(
            self, _release_trainer_memory, self._mem_bytes)

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and list(optimizer_params) != ["rescale_grad"]:
                raise ValueError(
                    "optimizer_params must be None if optimizer is an Optimizer instance"
                )
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(optimizer, param_dict=param_dict, **optimizer_params)

    def _init_kvstore(self):
        from .. import kvstore as kv_mod

        if isinstance(self._kvstore_type, str):
            self._kvstore = kv_mod.create(self._kvstore_type)
        else:
            self._kvstore = self._kvstore_type
        if self._kvstore is not None:
            for i, p in enumerate(self._params):
                if p._data is not None:
                    self._kvstore.init(i, p.data())
            if self._kvstore.num_workers > 1:
                # pin the rank for trace/metrics metadata (the async store
                # already did; the SPMD dist store knows it only after
                # jax.distributed bootstraps, which init() just forced)
                _profiler.set_process_info(rank=self._kvstore.rank)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _check_and_rescale_grad(self, scale):
        """Set ``optimizer.rescale_grad`` for this step, warning when a
        user-set value is about to be clobbered (parity: the reference warns
        instead of silently overwriting a manual ``rescale_grad``).  Before
        the first step the expected value is ``self._scale`` (what
        ``_init_optimizer`` installed), so a pre-step manual edit warns too."""
        expected = (self._last_scale_set if self._last_scale_set is not None
                    else self._scale)
        if self._optimizer.rescale_grad != expected:
            warnings.warn(
                "Optimizer.rescale_grad was changed outside Trainer.step; "
                "Trainer recomputes it as trainer._scale/batch_size every "
                "step, overriding your value. Construct the Trainer with "
                "optimizer_params={'rescale_grad': ...} instead.",
                UserWarning, stacklevel=3)
        self._optimizer.rescale_grad = scale
        self._last_scale_set = scale

    def step(self, batch_size, ignore_stale_grad=False):
        """Gradient allreduce + optimizer update (parity: ``Trainer.step``)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._check_and_rescale_grad(self._scale / batch_size)
        # staleness must be judged BEFORE allreduce: the kvstore writes into
        # every grad buffer (bumping its version), which is transport, not
        # a fresh backward
        stale = self._stale_indices() if ignore_stale_grad else frozenset()
        try:
            with _profiler.span("trainer.allreduce", "trainer"):
                self.allreduce_grads()
            with _profiler.span("trainer.update", "trainer"):
                self._update(ignore_stale_grad, stale)
        finally:
            # the step boundary every span since the previous boundary
            # belongs to: closes step telemetry (buckets, slow-step check,
            # memory watermark) and advances the step id; no-op when the
            # profiler is off.  In a finally so a raised-and-recovered step
            # can't bill its partial time to the NEXT step's telemetry.
            _profiler.step_boundary()

    def backward(self, loss, head_grads=None):
        """Run the autograd backward for ``loss`` with the gradient
        exchange OVERLAPPED against it: each size-capped gradient bucket's
        ``bucketed_pushpull`` launches from a grad-readiness hook the
        moment every grad in the bucket is final — while later (earlier-
        layer) VJPs still run — so wire time hides under the remaining
        backward instead of serializing after it (docs/step_fold.md; the
        MLPerf-on-TPU-pods overlap, on the out-of-fold dist-kvstore path).

        Drop-in for ``loss.backward()``: when no dist bucketing store is
        attached (or ``MXNET_ALLREDUCE_OVERLAP=0``, or any param uses
        ``grad_req='add'`` — a running sum must not be pushed early) it IS
        a plain backward, and the following ``step()`` aggregates as
        usual.  Buckets already reduced here are skipped by ``step()``'s
        ``allreduce_grads``.  A wire failure mid-backward raises out of
        this call with the failed bucket's grads UNTOUCHED (never
        half-written); the step must then be abandoned on every worker —
        peers' collectives have already advanced."""
        import os as _os

        from .. import autograd as _ag

        if not self._kv_initialized:
            self._init_kvstore()
        heads = loss if isinstance(loss, (list, tuple)) else [loss]
        from .. import kvstore as kv_mod

        kv = self._kvstore
        pairs = [(i, p) for i, p in enumerate(self._params)
                 if p.grad_req != "null" and p._data is not None
                 and p._data._grad is not None]
        overlap = (
            _os.environ.get("MXNET_ALLREDUCE_OVERLAP", "1") != "0"
            and kv is not None and len(pairs) > 1
            and kv_mod.bucket_bytes() > 0
            and kv.supports_grad_bucketing()
            and all(p.grad_req == "write" for _, p in pairs))
        if not overlap:
            self._overlap_reduced = None
            _ag.backward(heads, head_grads)
            return
        policy, feedback = self._compression()
        epoch = kv.membership_epoch() if hasattr(kv, "membership_epoch") \
            else 0
        items = [(i, p.grad()) for i, p in pairs]
        _, buckets = kv_mod.plan_buckets(
            items, names=[p.name for _, p in pairs],
            compression=policy, epoch=epoch)
        kv_mod.retain_feedback(policy, feedback, epoch)
        pos_of = {id(p._data): n for n, (_, p) in enumerate(pairs)}
        bucket_of = {}
        remaining = []
        for b, bucket in enumerate(buckets):
            remaining.append(len(bucket["positions"]))
            for pos in bucket["positions"]:
                bucket_of[pos] = b
        launched = {}
        # the full plan survives into step(): leftover buckets (params the
        # loss never touched) execute from the SAME plan, so bucket keys —
        # and the error-feedback residuals hung off them — stay stable.
        # ``launched`` records each reduced bucket's grad VERSIONS so
        # step() can tell this plan from a stale one (an abandoned step
        # followed by a fresh plain backward must re-reduce everything).
        self._overlap_reduced = {
            "buckets": buckets, "items": items, "policy": policy,
            "feedback": feedback, "launched": launched,
        }

        def on_ready(leaf):
            pos = pos_of.get(id(leaf))
            if pos is None:
                return   # a leaf this trainer doesn't own
            b = bucket_of[pos]
            remaining[b] -= 1
            if remaining[b] == 0:
                # bucket complete: launch its pushpull NOW — the walk (and
                # the device's VJPs) continue while the wire carries it
                kv_mod.execute_bucket(kv, buckets[b], items, policy,
                                      feedback)
                launched[b] = tuple(items[q][1]._version
                                    for q in buckets[b]["positions"])
                _profiler.incr("allreduce_overlap_launched")

        try:
            _ag.backward(heads, head_grads, grad_ready_hook=on_ready)
        except BaseException:
            # the step is lost (docs/step_fold.md failure contract): drop
            # the plan so a RECOVERY backward + step() re-reduces
            # everything instead of skipping the buckets this failed walk
            # marked launched — stale skips would silently diverge workers
            self._overlap_reduced = None
            raise

    def allreduce_grads(self):
        """Aggregate gradients across devices/hosts via the kvstore facade
        (single-replica SPMD: aggregation happened inside the compiled step
        via psum, so this is a no-op unless a dist kvstore is attached).
        Against a dist store the grads travel as size-capped flat buckets —
        a few big pushpulls instead of one per parameter.  Buckets already
        pushed by ``Trainer.backward``'s grad-readiness overlap are
        skipped (their grads hold reduced values)."""
        if not self._kv_initialized:
            self._init_kvstore()
        overlap, self._overlap_reduced = self._overlap_reduced, None
        if self._kvstore is None:
            return
        from .. import kvstore as kv_mod

        if overlap is not None:
            # the plan is only valid if no backward re-wrote the reduced
            # grads since their buckets were pushed (versions unchanged) —
            # an abandoned overlap step followed by a plain backward must
            # NOT have its fresh grads skipped here
            fresh = all(
                tuple(overlap["items"][q][1]._version
                      for q in overlap["buckets"][b]["positions"]) == vers
                for b, vers in overlap["launched"].items())
            if fresh:
                # Trainer.backward pushed the ready buckets mid-backward;
                # finish the leftovers from the SAME plan
                for b, bucket in enumerate(overlap["buckets"]):
                    if b not in overlap["launched"]:
                        kv_mod.execute_bucket(self._kvstore, bucket,
                                              overlap["items"],
                                              overlap["policy"],
                                              overlap["feedback"])
                return
            # stale plan: fall through to the normal full aggregation
        pairs = [(i, p) for i, p in enumerate(self._params)
                 if p.grad_req != "null" and p._data is not None
                 and p._data._grad is not None]
        if not pairs:
            return
        if (len(pairs) > 1 and kv_mod.bucket_bytes() > 0
                and self._kvstore.supports_grad_bucketing()):
            policy, feedback = self._compression()
            kv_mod.bucketed_pushpull(self._kvstore,
                                     [(i, p.grad()) for i, p in pairs],
                                     names=[p.name for _, p in pairs],
                                     compression=policy, feedback=feedback)
            return
        for i, p in pairs:
            self._kvstore.pushpull(i, p.grad(), out=p.grad())

    def _compression(self):
        """The gradient-compression policy (``MXNET_GRAD_COMPRESS`` tier)
        + this trainer's lazily-created ErrorFeedback — ONE resolution
        rule for every exchange entry (``allreduce_grads``, the overlap
        ``backward``), so the paths can never build different wire
        formats."""
        from .. import comm

        policy = comm.resolve_policy()
        feedback = None
        if policy is not None and policy.error_feedback:
            if self._grad_feedback is None:
                self._grad_feedback = comm.ErrorFeedback()
            feedback = self._grad_feedback
        return policy, feedback

    def fold_step(self, loss_fn, block=None, keep_grads=False):
        """Build the FOLDED training step for this trainer: ONE compiled,
        donated-buffer program running Block forward + loss + backward +
        (dist) gradient allreduce + the fused optimizer tail per call —
        the ``SPMDTrainer`` discipline on the imperative Trainer contract
        (docs/step_fold.md).

        ``loss_fn(*batch) -> loss NDArray`` computes the loss from the
        batch NDArrays (calling the Block(s) whose Parameters this
        trainer owns).  Returns a :class:`~.step_fold.StepProgram`;
        ``program(data, label)`` replaces the whole
        record/forward/backward/``step()`` sequence and returns the loss.
        Escape hatches: ``MXNET_STEP_FOLD=0``, ``block=`` with
        ``_step_fold_opt_out``, or any unsupported construct — all fall
        back to the eager path (``step_fold_fallback`` counter), never
        erroring."""
        import weakref as _weakref

        from . import step_fold as _sf

        sp = _sf.StepProgram(self, loss_fn, block=block,
                             keep_grads=keep_grads)
        self._fold = _weakref.ref(sp)
        return sp

    def fold_steps(self, loss_fn, k=None, block=None, keep_grads=False,
                   donate_window=False):
        """The K-STEP fold: like :meth:`fold_step`, but the returned
        :class:`~.step_fold.StepProgram` runs K logical training steps
        per call as ONE compiled dispatch — a ``lax.scan`` over a
        ``[K, batch, ...]`` stacked window (``pipeline.stage_window(k)``)
        carrying params/optimizer state/EF residuals through the loop,
        with per-step lr/wd/t and PRNG keys staged as stacked ``[K]``
        device arrays.  Host dispatch cost drops to 1/K; numerics are
        bit-exact vs K unfolded steps.  ``k`` defaults to
        ``MXNET_STEP_FOLD_K`` (K=1 IS the :meth:`fold_step` program).

        Checkpoints land on K boundaries only: ``save_states`` refuses
        while ``program.window_pos != 0`` (only the ``step_one`` escape
        moves the cursor).  ``donate_window=True`` additionally donates
        the staged window buffers (docs/step_fold.md#multi-step-fold)."""
        import weakref as _weakref

        from . import step_fold as _sf

        sp = _sf.StepProgram(self, loss_fn, block=block,
                             keep_grads=keep_grads, k=k,
                             donate_window=donate_window)
        self._fold = _weakref.ref(sp)
        return sp

    def fold_eval(self, loss_fn, block=None, k=None):
        """The folded evaluation pass: forward-only loss over a batch (or
        a ``[K, batch, ...]`` window) as ONE compiled dispatch, with the
        summed loss accumulated in-program — the host reads metrics once
        per eval pass via ``program.result()``.  Shares the train fold's
        ``trace_scope`` ceremony (``is_training=False``: BatchNorm reads
        running stats, dropout is identity).  Returns a
        :class:`~.step_fold.EvalProgram` (docs/step_fold.md)."""
        from . import step_fold as _sf

        return _sf.EvalProgram(self, loss_fn, block=block, k=k)

    def update(self, batch_size, ignore_stale_grad=False):
        """Optimizer update only (assumes grads already aggregated)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._check_and_rescale_grad(self._scale / batch_size)
        try:
            with _profiler.span("trainer.update", "trainer"):
                self._update(ignore_stale_grad,
                             self._stale_indices() if ignore_stale_grad
                             else frozenset())
        finally:
            _profiler.step_boundary()

    def _stale_indices(self):
        """Params whose grad buffer was NOT rewritten since their last
        update (no backward ran for them) — the reference's ``_fresh_grad``
        complement."""
        return {i for i, p in enumerate(self._params)
                if p.grad_req != "null" and p._data is not None
                and p._data._grad is not None
                and self._grad_versions.get(i) == p.grad_version}

    def _update(self, ignore_stale_grad=False, stale=frozenset()):
        touched = []
        for i, p in enumerate(self._params):
            if p.grad_req == "null" or p._data is None:
                continue
            if p._data._grad is None:
                if ignore_stale_grad:
                    continue
                raise UserWarning(f"Gradient of Parameter `{p.name}` has no grad buffer")
            if ignore_stale_grad and i in stale:
                continue
            if i not in self._states:
                self._states[i] = self._optimizer.create_state_multi_precision(i, p.data())
            touched.append((i, p))
        self._account_memory(touched)
        # fused whole-group fast path; leftovers (unsupported optimizer,
        # lazy row-sparse params, NaiveEngine, aggregation disabled) take
        # the per-tensor loop below.  MXNET_STEP_FOLD=1 folds EVERY group
        # into one donated dispatch (step_fold.fold_update) instead of one
        # group_apply per group — the step() half of the step fold.
        from . import step_fold as _sf

        updater = (_sf.fold_update if _sf.step_fast_path()
                   else _fused.fused_update)
        rest = updater(
            self._optimizer,
            [(i, p.data(), p.grad()) for i, p in touched],
            self._states)
        for i, w, g in rest:
            self._optimizer.update_multi_precision(i, w, g, self._states[i])
        # snapshot CURRENT versions for EVERY grad-bearing param (updated,
        # skipped-stale, or left alone): only a future backward/user write
        # may flip a param back to fresh, never this step's own transport
        for i, p in enumerate(self._params):
            if p.grad_req != "null" and p._data is not None \
                    and p._data._grad is not None:
                self._grad_versions[i] = p.grad_version

    def _account_memory(self, touched):
        """Report newly-tracked weight+grad+state buffers into the
        device-memory ledger (shared ``trainer.params`` /
        ``trainer.optimizer_state`` owners — several trainers compose by
        deltas).  Steady state is a no-op: the index set is stable and
        donated buffer swaps keep every size constant."""
        new = [(i, p) for i, p in touched if i not in self._mem_idx]
        if not new:
            return
        pb = sb = 0
        for i, p in new:
            self._mem_idx.add(i)
            pb += 2 * _nd_nbytes(p._data)     # weight + grad buffer
            sb += _nd_nbytes(self._states.get(i))
        self._mem_bytes[0] += pb
        self._mem_bytes[1] += sb
        _profiler.track_memory("trainer.params", "params").alloc(pb)
        _profiler.track_memory("trainer.optimizer_state",
                               "optimizer_state").alloc(sb)

    def close(self):
        """Release distributed resources.  Against an elastic dist store
        (``dist_async``) this deregisters the rank — peers' barrier and
        SSP accounting shrink immediately instead of waiting out the
        lease-eviction window.  Idempotent; a no-op for local stores."""
        # before the first step _init_kvstore hasn't run: a store OBJECT
        # the caller passed in still lives in _kvstore_type and must be
        # closed all the same (string types were never instantiated)
        kv = self._kvstore
        if kv is None and not isinstance(self._kvstore_type, str):
            kv = self._kvstore_type
        if kv is not None and hasattr(kv, "close"):
            kv.close()
        # release this trainer's ledger share (idempotent — the finalizer
        # zeroes the shared cell, so a later GC pass frees nothing more)
        self._mem_finalizer()
        self._mem_idx.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def save_states(self, fname):
        """Parity: ``Trainer.save_states`` (optimizer state snapshot).
        Persists the per-index update counts too — Adam's bias-correction
        counter ``t`` must stay monotonic across a save/load roundtrip.
        Atomic (tmp + ``os.replace``): a preemption mid-write never tears
        the snapshot."""
        import pickle

        from ..checkpoint import atomic_write_bytes

        fold = self._fold() if self._fold is not None else None
        if fold is not None:
            if getattr(fold, "window_pos", 0) != 0:
                # the K-boundary checkpoint rule: mid-window state is not
                # a trajectory point K unfolded steps would ever visit —
                # a restore from it could never be exact
                raise RuntimeError(
                    f"save_states refused mid-window: the K-step fold is "
                    f"{fold.window_pos} step(s) past a K boundary "
                    f"(k={fold.k}). Checkpoints land on K boundaries only "
                    "— finish the window (further step_one calls) or "
                    "save before stepping off the boundary "
                    "(docs/step_fold.md#multi-step-fold).")
            # a multi-process fold holds params/states in donated global
            # registers; pull them into the live NDArrays first so the
            # snapshot sees the current trajectory (no-op for local folds)
            fold.sync()
        flat = {}
        for i, st in self._states.items():
            flat[i] = _states_to_numpy(st)
        payload = {
            "states": flat,
            "num_update": self._optimizer.num_update,
            "update_counts": dict(self._optimizer._index_update_count),
        }
        if fold is not None and fold.k > 1:
            # the fold window cursor rides the snapshot so elastic/exact
            # resume can assert it restarts ON a K boundary
            payload["fold_cursor"] = {"k": fold.k,
                                      "logical_steps": fold.logical_steps,
                                      "window_pos": 0}
        if self._grad_feedback is not None and len(self._grad_feedback):
            # gradient-compression residuals are optimizer-adjacent state:
            # dropping them at restore re-injects one step's quantization
            # error, so they ride the same snapshot
            payload["grad_feedback"] = self._grad_feedback.state_dict()
        atomic_write_bytes(fname, pickle.dumps(payload))

    def load_states(self, fname):
        import pickle

        with open(fname, "rb") as f:
            payload = pickle.load(f)
        for i, st in payload["states"].items():
            if i not in self._states:
                self._states[i] = self._optimizer.create_state_multi_precision(i, self._params[i].data())
            _numpy_to_states(self._states[i], st)
        num_update = payload.get("num_update", self._optimizer.num_update)
        counts = payload.get("update_counts")
        if counts is None:
            # older snapshots carry no per-index counts: reconstruct them
            # from num_update (the begin_num_update convention) so Adam's t
            # resumes at the restored step, not at 1
            counts = {i: num_update for i in payload["states"]}
        self._optimizer._index_update_count = dict(counts)
        self._optimizer.num_update = num_update
        self._optimizer.begin_num_update = num_update
        fold = self._fold() if self._fold is not None else None
        if fold is not None:
            # restored state lives in the Parameter/state NDArrays now; a
            # multi-process fold must re-stage its registers from them
            fold.invalidate()
            cursor = payload.get("fold_cursor")
            if cursor is not None:
                # snapshots are taken on K boundaries only; restore the
                # logical-step count and land the cursor back on one
                fold._logical_steps = int(cursor.get("logical_steps", 0))
                fold._window_pos = 0
        fb = payload.get("grad_feedback")
        if fb:
            from .. import comm

            if self._grad_feedback is None:
                self._grad_feedback = comm.ErrorFeedback()
            self._grad_feedback.load_state_dict(fb)
        elif self._grad_feedback is not None:
            # the snapshot carries NO residuals (saved before any
            # compressed step, or by an uncompressed run): keeping this
            # trainer's live ones would compensate the restored step with
            # errors from a different trajectory — restores must be
            # deterministic, so start fresh like the snapshot did
            self._grad_feedback.load_state_dict({})


# shape-x-dtype footprint (never resolves a pending deferred buffer) —
# the shared rule lives beside the ledger itself
_nd_nbytes = _profiler.array_nbytes


def _release_trainer_memory(cell):
    """weakref.finalize hook (also the close() body): free this trainer's
    share of the shared ledger owners and zero the mutable cell so the
    release can only ever happen once (module-level — must not reference
    the trainer)."""
    pb, sb = cell
    cell[0] = cell[1] = 0
    if pb:
        _profiler.track_memory("trainer.params", "params").free(pb)
    if sb:
        _profiler.track_memory("trainer.optimizer_state",
                               "optimizer_state").free(sb)


def _states_to_numpy(st):
    from ..ndarray.ndarray import NDArray

    if st is None:
        return None
    if isinstance(st, NDArray):
        return st.asnumpy()
    if isinstance(st, (list, tuple)):
        return type(st)(_states_to_numpy(s) for s in st)
    return st


def _numpy_to_states(st, data):
    from ..ndarray.ndarray import NDArray

    if st is None or data is None:
        return
    if isinstance(st, NDArray):
        st[:] = data
        return
    if isinstance(st, (list, tuple)):
        for s, d in zip(st, data):
            _numpy_to_states(s, d)
