"""Gluon Parameter / ParameterDict.

Parity target: [U:python/mxnet/gluon/parameter.py].  Same lifecycle as the
reference: construct (shape may contain 0 = unknown), ``initialize`` (may
defer until the first forward infers shapes), ``data()``/``grad()`` access,
``grad_req`` write/add/null, lr_mult/wd_mult, save/load by name.

Differences by design: a Parameter holds ONE NDArray (SPMD sharding over a
mesh replaces the reference's per-GPU replica list — see parallel/), and
``row_sparse`` stype is represented densely (documented divergence,
docs/sparse.md).
"""
from __future__ import annotations

import re

import numpy as _np

from ..base import DeferredInitializationError
from ..context import cpu, current_context
from ..ndarray.ndarray import NDArray, zeros
from .. import initializer as _init_mod

__all__ = ["Parameter", "Constant", "ParameterDict", "tensor_types"]

tensor_types = (NDArray,)


def _shape_is_known(shape):
    if shape is None:
        return False
    return all(s > 0 for s in shape)


class Parameter:
    """A trainable (or auxiliary) tensor of a Block."""

    def __init__(
        self,
        name,
        grad_req="write",
        shape=None,
        dtype="float32",
        lr_mult=1.0,
        wd_mult=1.0,
        init=None,
        allow_deferred_init=False,
        differentiable=True,
        stype="default",
        grad_stype="default",
    ):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._data = None
        self._deferred_init = None
        self._stype = stype

    # -- properties ------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        if len(self._shape) != len(new_shape) or any(
            s not in (0, n) for s, n in zip(self._shape, new_shape)
        ):
            raise ValueError(
                f"Parameter {self.name}: shape {new_shape} incompatible with inferred {self._shape}"
            )
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise ValueError(req)
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._data._grad = None
                self._data._grad_req = "null"
            else:
                self._data.attach_grad(req)

    @property
    def stype(self):
        return self._stype

    @property
    def grad_version(self):
        """Monotonic version of the gradient buffer (bumped by backward()
        and in-place grad writes).  ``Trainer.step(ignore_stale_grad=True)``
        compares it against the version it saw at the previous update to
        skip parameters whose grad was never refreshed — the reference's
        ``_fresh_grad`` tracking.  -1 when no grad buffer exists."""
        if self._data is None or self._data._grad is None:
            return -1
        return self._data._grad._version

    # -- init ------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        """Allocate and initialize (parity: ``Parameter.initialize``).
        Defers when the shape is still unknown and deferred init is allowed."""
        if default_init is None:
            default_init = _init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]  # SPMD replaces per-device replica lists
        if not _shape_is_known(self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise ValueError(
                f"Cannot initialize Parameter {self.name} because it has "
                f"invalid shape {self._shape}; pass concrete shapes or build "
                "the network with deferred initialization (run a forward pass)"
            )
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        data = zeros(self._shape, dtype=self.dtype, ctx=ctx)
        initializer = init or self.init or default_init
        if not isinstance(initializer, (_init_mod.Initializer, _init_mod.Load, _init_mod.Mixed)):
            initializer = _init_mod.create(initializer)
        initializer(_init_mod.InitDesc(self.name), data)
        self._data = data
        self._deferred_init = None
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req)

    def _finish_deferred_init(self, inferred_shape=None):
        if self._deferred_init is None:
            return
        if inferred_shape is not None:
            self.shape = inferred_shape
        if not _shape_is_known(self._shape):
            raise DeferredInitializationError(
                f"Parameter {self.name} has unknown shape {self._shape} and "
                "deferred initialization could not infer it"
            )
        init, ctx, default_init = self._deferred_init
        self._finish_init(init, ctx, default_init)

    # -- access ----------------------------------------------------------
    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init is not None:
            raise DeferredInitializationError(
                f"Parameter {self.name} has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass. Please pass one batch of data "
                "through the network before accessing Parameters."
            )
        raise RuntimeError(
            f"Parameter {self.name} has not been initialized. You should "
            "initialize parameters with Block.initialize() before using them."
        )

    def data(self, ctx=None):
        """The parameter value.  Inside a hybridize trace, returns the traced
        stand-in so child blocks compose into one compiled graph."""
        traced = getattr(self, "_traced_data", None)
        if traced is not None:
            return traced
        self._check_initialized()
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        self._check_initialized()
        if self._data._grad is None:
            raise RuntimeError(
                f"Cannot get gradient array for Parameter {self.name} because grad_req='null'"
            )
        return self._data._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        self._check_initialized()
        return [self._data.context]

    def set_data(self, data):
        if self._data is None:
            # loading into an uninitialized/deferred parameter: adopt the
            # incoming shape and materialize (parity: load_parameters works
            # without a prior initialize())
            self.shape = data.shape
            if self._deferred_init is not None:
                init, ctx, default_init = self._deferred_init
            else:
                from ..context import current_context

                ctx, default_init = current_context(), _init_mod.Zero()
            self._finish_init(_init_mod.Zero(), ctx, default_init)
        self._check_initialized()
        if tuple(data.shape) != tuple(self._data.shape):
            raise AssertionError(
                f"Failed to update param {self.name}: shape mismatch, "
                f"expected {tuple(self._data.shape)}, got {tuple(data.shape)}"
            )
        if isinstance(data, NDArray):
            self._data._data = data._data.astype(self._data.dtype)
        else:
            self._data[:] = data
        self._data._version += 1

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            self._data.zero_grad()

    def reset_ctx(self, ctx):
        self._check_initialized()
        self._data = self._data.as_in_context(ctx if not isinstance(ctx, (list, tuple)) else ctx[0])
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            grad_req = self._grad_req
            self._data = self._data.astype(dtype)
            if grad_req != "null":
                self._data.attach_grad(grad_req)

    def var(self):
        from .. import symbol as _sym

        return _sym.var(self.name, shape=self._shape, dtype=self.dtype)

    def __repr__(self):
        return f"Parameter {self.name} (shape={self._shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-trainable constant parameter (parity: ``gluon.Constant``)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = NDArray(_np.asarray(value, dtype="float32"))
        self.value = value

        class _CInit(_init_mod.Initializer):
            def __call__(self, _, arr):
                arr[:] = value

            def _init_weight(self, _, arr):
                arr[:] = value

        super().__init__(
            name,
            grad_req="null",
            shape=value.shape,
            dtype=value.dtype,
            init=_CInit(),
        )


class ParameterDict:
    """Ordered name -> Parameter mapping with prefix sharing
    (parity: ``gluon.ParameterDict``)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def __repr__(self):
        lines = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict '{self._prefix}' (\n{lines}\n)"

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, key):
        return key in self._params

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name, **kwargs):
        """Create-or-retrieve by suffix name (parity semantics: checks shared
        dict first, validates attribute compatibility)."""
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            param = Parameter(full, **kwargs)
            self._params[full] = param
        else:
            for k, v in kwargs.items():
                if k == "shape" and v is not None:
                    param.shape = tuple(s if s is not None else 0 for s in (v if not isinstance(v, int) else (v,)))
                elif k == "init" and v is not None and param.init is None:
                    param.init = v
        return param

    def get_constant(self, name, value=None):
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            if value is None:
                raise KeyError(f"No constant named {full}")
            param = Constant(full, value)
            self._params[full] = param
        return param

    def _get_impl(self, full):
        if full in self._params:
            return self._params[full]
        if self._shared is not None and full in self._shared:
            self._params[full] = self._shared[full]
            return self._params[full]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(f"Cannot update self with other because they have different Parameters with the same name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        if init is None:
            init = _init_mod.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray.utils import save as nd_save

        arg_dict = {}
        for param in self.values():
            block = param.data()
            name = param.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = block
        nd_save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False, ignore_extra=False, restore_prefix=""):
        from ..ndarray.utils import load as nd_load

        loaded = nd_load(filename)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in loaded:
                    raise IOError(f"Parameter {name} is missing in file {filename}")
        for name, v in loaded.items():
            if name not in self._params:
                if not ignore_extra:
                    raise IOError(f"Parameter {name} loaded from {filename} is not present in ParameterDict")
                continue
            self[name].set_data(v)
