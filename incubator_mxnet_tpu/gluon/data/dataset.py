"""Datasets (parity: [U:python/mxnet/gluon/data/dataset.py])."""
from __future__ import annotations

import os

from ...ndarray.ndarray import NDArray, array

__all__ = ["Dataset", "ArrayDataset", "SimpleDataset", "RecordFileDataset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self)) if fn(self[i])])

    def shard(self, num_shards, index):
        """Per-host sharding (parity: 1.7 ``Dataset.shard`` — the
        num_parts/part_index equivalent for data-parallel input)."""
        assert 0 <= index < num_shards
        idx = list(range(index, len(self), num_shards))
        base = self

        class _Shard(Dataset):
            def __len__(self):
                return len(idx)

            def __getitem__(self, i):
                return base[idx[i]]

        return _Shard()

    def take(self, count):
        base = self

        class _Take(Dataset):
            def __len__(self):
                return min(count, len(base))

            def __getitem__(self, i):
                if i >= len(self):
                    raise IndexError
                return base[i]

        return _Take()

    def transform(self, fn, lazy=True):
        return _LazyTransformDataset(self, fn)

    def transform_first(self, fn, lazy=True):
        def first(*items):
            if len(items) == 1:
                return fn(items[0])
            return (fn(items[0]),) + items[1:]

        return _LazyTransformDataset(self, first, unpack=True)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn, unpack=False):
        self._data = data
        self._fn = fn
        self._unpack = unpack

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if self._unpack and isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of arrays (parity: ``data.ArrayDataset``)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for d in args:
            assert len(d) == self._length, "All arrays must have the same length"
            self._data.append(d)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (parity: ``data.RecordFileDataset``;
    format-compatible with im2rec packs via recordio.py)."""

    def __init__(self, filename):
        from ... import recordio

        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])


NDArray, array  # re-export convenience
