"""``gluon.data.vision`` (parity: [U:python/mxnet/gluon/data/vision/])."""
from .datasets import MNIST, FashionMNIST, CIFAR10, CIFAR100, ImageRecordDataset, ImageFolderDataset, SyntheticImageDataset
from . import transforms

__all__ = [
    "MNIST",
    "FashionMNIST",
    "CIFAR10",
    "CIFAR100",
    "ImageRecordDataset",
    "ImageFolderDataset",
    "SyntheticImageDataset",
    "transforms",
]
