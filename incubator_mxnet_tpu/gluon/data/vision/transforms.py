"""Vision transforms (parity: [U:python/mxnet/gluon/data/vision/transforms.py])."""
from __future__ import annotations

import numpy as _np

from ...block import Block, HybridBlock
from ...nn.basic_layers import Sequential
from ....ndarray.ndarray import NDArray, array

__all__ = [
    "Compose",
    "Cast",
    "ToTensor",
    "Normalize",
    "Resize",
    "CenterCrop",
    "RandomResizedCrop",
    "RandomCrop",
    "RandomFlipLeftRight",
    "RandomFlipTopBottom",
    "RandomBrightness",
    "RandomContrast",
    "RandomSaturation",
    "RandomHue",
    "RandomColorJitter",
    "RandomLighting",
]


class Compose(Sequential):
    """Parity: ``transforms.Compose``."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)

    def forward(self, x, *args):
        for child in self._children.values():
            x = child(x)
        return x


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (parity: ``ToTensor``)."""

    def hybrid_forward(self, F, x):
        if x.ndim == 3:
            return x.astype("float32").transpose((2, 0, 1)) / 255.0
        return x.astype("float32").transpose((0, 3, 1, 2)) / 255.0


class Normalize(HybridBlock):
    """Channel-wise normalize of CHW tensors (parity: ``Normalize``)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, dtype="float32")
        self._std = _np.asarray(std, dtype="float32")

    def hybrid_forward(self, F, x):
        c = x.shape[0] if x.ndim == 3 else x.shape[1]
        mean = _np.broadcast_to(self._mean, (c,)).reshape(
            (c, 1, 1) if x.ndim == 3 else (1, c, 1, 1)
        )
        std = _np.broadcast_to(self._std, (c,)).reshape(
            (c, 1, 1) if x.ndim == 3 else (1, c, 1, 1)
        )
        return (x - array(mean)) / array(std)


def _to_np(img):
    return img.asnumpy() if isinstance(img, NDArray) else _np.asarray(img)


class Resize(Block):
    """Parity: ``transforms.Resize`` (HWC input)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        data = x._data if isinstance(x, NDArray) else jnp.asarray(_np.asarray(x))
        h, w = self._size[1], self._size[0]
        out = jax.image.resize(
            data.astype(jnp.float32), (h, w, data.shape[-1]), method="linear"
        )
        return NDArray(jnp.clip(jnp.round(out), 0, 255).astype(data.dtype))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        img = _to_np(x)
        w, h = self._size
        H, W = img.shape[0], img.shape[1]
        y0 = max(0, (H - h) // 2)
        x0 = max(0, (W - w) // 2)
        return array(img[y0 : y0 + h, x0 : x0 + w])


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._pad = pad

    def forward(self, x):
        img = _to_np(x)
        if self._pad:
            img = _np.pad(img, ((self._pad, self._pad), (self._pad, self._pad), (0, 0)), mode="constant")
        w, h = self._size
        H, W = img.shape[0], img.shape[1]
        y0 = _np.random.randint(0, max(1, H - h + 1))
        x0 = _np.random.randint(0, max(1, W - w + 1))
        return array(img[y0 : y0 + h, x0 : x0 + w])


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3), interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        img = _to_np(x)
        H, W = img.shape[0], img.shape[1]
        area = H * W
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area
            aspect = _np.random.uniform(*self._ratio)
            w = int(round(_np.sqrt(target_area * aspect)))
            h = int(round(_np.sqrt(target_area / aspect)))
            if w <= W and h <= H:
                x0 = _np.random.randint(0, W - w + 1)
                y0 = _np.random.randint(0, H - h + 1)
                crop = img[y0 : y0 + h, x0 : x0 + w]
                break
        else:
            crop = img
        out = jax.image.resize(
            jnp.asarray(crop, dtype=jnp.float32),
            (self._size[1], self._size[0], img.shape[-1]),
            method="linear",
        )
        return NDArray(jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8 if img.dtype == _np.uint8 else img.dtype))


class RandomFlipLeftRight(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if _np.random.rand() < self._p:
            return array(_to_np(x)[:, ::-1].copy())
        return x if isinstance(x, NDArray) else array(_to_np(x))


class RandomFlipTopBottom(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if _np.random.rand() < self._p:
            return array(_to_np(x)[::-1].copy())
        return x if isinstance(x, NDArray) else array(_to_np(x))


class _RandomJitter(Block):
    def __init__(self, amount):
        super().__init__()
        self._amount = amount

    def _factor(self):
        return 1.0 + _np.random.uniform(-self._amount, self._amount)


class RandomBrightness(_RandomJitter):
    def forward(self, x):
        img = _to_np(x).astype("float32") * self._factor()
        return array(_np.clip(img, 0, 255).astype(_to_np(x).dtype))


class RandomContrast(_RandomJitter):
    def forward(self, x):
        img = _to_np(x).astype("float32")
        mean = img.mean()
        out = (img - mean) * self._factor() + mean
        return array(_np.clip(out, 0, 255).astype(_to_np(x).dtype))


class RandomSaturation(_RandomJitter):
    def forward(self, x):
        img = _to_np(x).astype("float32")
        gray = img.mean(axis=-1, keepdims=True)
        out = (img - gray) * self._factor() + gray
        return array(_np.clip(out, 0, 255).astype(_to_np(x).dtype))


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (parity: ``RandomLighting``)."""

    _eigval = _np.asarray([55.46, 4.794, 1.148], dtype="float32")
    _eigvec = _np.asarray(
        [[-0.5675, 0.7192, 0.4009], [-0.5808, -0.0045, -0.814], [-0.5836, -0.6948, 0.4203]],
        dtype="float32",
    )

    def __init__(self, alpha=0.1):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        img = _to_np(x).astype("float32")
        a = _np.random.normal(0, self._alpha, 3).astype("float32")
        noise = (self._eigvec * a * self._eigval).sum(axis=1)
        out = img + noise
        return array(_np.clip(out, 0, 255).astype(_to_np(x).dtype))


class RandomHue(_RandomJitter):
    """YIQ-rotation hue jitter (parity: ``transforms.RandomHue``); the
    rotation matrix comes from ``image.HueJitterAug.hue_matrix``."""

    def forward(self, x):
        from ....image.image import HueJitterAug

        src = _to_np(x)
        alpha = _np.random.uniform(-self._amount, self._amount)
        t = HueJitterAug.hue_matrix(alpha)
        out = src.astype("float32") @ t.T
        if _np.issubdtype(src.dtype, _np.integer):
            out = _np.rint(out)
        return array(_np.clip(out, 0, 255).astype(src.dtype))


class RandomColorJitter(Block):
    """Random-order brightness/contrast/saturation/hue jitter (parity:
    ``transforms.RandomColorJitter``)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        # numpy's global RNG orders AND draws every jitter, so one
        # np.random.seed reproduces the whole augmentation
        order = [self._ts[i] for i in _np.random.permutation(len(self._ts))]
        for t in order:
            x = t(x)
        return x
