"""Vision datasets (parity: [U:python/mxnet/gluon/data/vision/datasets.py]).

MNIST/CIFAR read the standard on-disk formats from a local root (this
sandbox has zero egress, so the reference's auto-download is gated);
``SyntheticImageDataset`` provides the `--benchmark 1` synthetic-data mode
the reference builds into its trainers ([U:example/image-classification/
common/fit.py]) as a first-class dataset.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as _np

from ...data.dataset import Dataset
from ....ndarray.ndarray import array

__all__ = [
    "MNIST",
    "FashionMNIST",
    "CIFAR10",
    "CIFAR100",
    "ImageRecordDataset",
    "ImageFolderDataset",
    "SyntheticImageDataset",
]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._root = os.path.expanduser(root)
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from idx-ubyte files (parity: ``vision.MNIST``).  Looks for the
    standard files under root; falls back to a deterministic synthetic set
    when absent (zero-egress sandbox) so examples/tests stay runnable."""

    _train_files = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
    _test_files = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")
    _shape = (28, 28, 1)
    _classes = 10

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_idx(self, path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            data = f.read()
        magic, = struct.unpack(">i", data[:4])
        ndim = magic % 256
        dims = struct.unpack(">" + "i" * ndim, data[4 : 4 + 4 * ndim])
        return _np.frombuffer(data, dtype=_np.uint8, offset=4 + 4 * ndim).reshape(dims)

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        paths = [os.path.join(self._root, f) for f in files]
        alt = [p[:-3] for p in paths]  # uncompressed variants
        if all(os.path.exists(p) for p in paths) or all(os.path.exists(p) for p in alt):
            use = paths if os.path.exists(paths[0]) else alt
            images = self._read_idx(use[0])
            labels = self._read_idx(use[1])
            self._data = array(images.reshape(-1, *self._shape))
            self._label = labels.astype("int32")
        else:
            n = 6000 if self._train else 1000
            rng = _np.random.RandomState(42 if self._train else 43)
            labels = rng.randint(0, self._classes, size=n).astype("int32")
            images = _np.zeros((n,) + self._shape, dtype="uint8")
            # class-dependent pattern so models can actually learn
            for i, lab in enumerate(labels):
                img = rng.uniform(0, 48, self._shape).astype("uint8")
                r, c = divmod(int(lab), 4)
                img[4 + r * 6 : 10 + r * 6, 4 + c * 6 : 10 + c * 6, :] = 220
                images[i] = img
            self._data = array(images)
            self._label = labels

    def __getitem__(self, idx):
        img = self._data[idx]
        lab = self._label[idx]
        if self._transform is not None:
            return self._transform(img, lab)
        return img, lab


class FashionMNIST(MNIST):
    _train_files = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
    _test_files = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")

    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from python-pickle batches; synthetic fallback offline."""

    _classes = 10
    _shape = (32, 32, 3)

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        batch_dir = os.path.join(self._root, "cifar-10-batches-py")
        files = (
            [f"data_batch_{i}" for i in range(1, 6)] if self._train else ["test_batch"]
        )
        paths = [os.path.join(batch_dir, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            xs, ys = [], []
            for p in paths:
                with open(p, "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                xs.append(_np.asarray(d[b"data"]).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
                ys.append(_np.asarray(d[b"labels"] if b"labels" in d else d[b"fine_labels"]))
            self._data = array(_np.concatenate(xs).astype("uint8"))
            self._label = _np.concatenate(ys).astype("int32")
        else:
            n = 5000 if self._train else 1000
            rng = _np.random.RandomState(7 if self._train else 8)
            labels = rng.randint(0, self._classes, size=n).astype("int32")
            images = rng.randint(0, 64, (n,) + self._shape).astype("uint8")
            for i, lab in enumerate(labels):
                images[i, :, :, lab % 3] = images[i, :, :, lab % 3] // 2 + 16 * (lab + 1)
            self._data = array(images)
            self._label = labels

    def __getitem__(self, idx):
        img = self._data[idx]
        lab = self._label[idx]
        if self._transform is not None:
            return self._transform(img, lab)
        return img, lab


class CIFAR100(CIFAR10):
    _classes = 100

    def __init__(self, root="~/.mxnet/datasets/cifar100", fine_label=True, train=True, transform=None):
        super().__init__(root, train, transform)


class ImageRecordDataset(Dataset):
    """ImageRecord pack (parity: ``vision.ImageRecordDataset``) — reads
    im2rec-format RecordIO via recordio.py and decodes with image.imdecode."""

    def __init__(self, filename, flag=1, transform=None):
        from ...data.dataset import RecordFileDataset

        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from .... import recordio, image

        raw = self._record[idx]
        header, img_bytes = recordio.unpack(raw)
        img = image.imdecode(img_bytes, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """Folder-of-class-folders dataset (parity:
    ``vision.ImageFolderDataset``)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from .... import image

        fname, label = self.items[idx]
        with open(fname, "rb") as f:
            img = image.imdecode(f.read(), flag=self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class SyntheticImageDataset(Dataset):
    """Deterministic random images+labels entirely on device — the
    `--benchmark 1` mode as a dataset (input pipeline measured separately)."""

    def __init__(self, num_samples=1280, shape=(224, 224, 3), classes=1000, seed=0, dtype="uint8"):
        self._n = num_samples
        rng = _np.random.RandomState(seed)
        self._data = rng.randint(0, 255, (num_samples,) + tuple(shape)).astype(dtype)
        self._label = rng.randint(0, classes, (num_samples,)).astype("int32")

    def __len__(self):
        return self._n

    def __getitem__(self, idx):
        return array(self._data[idx]), self._label[idx]
