"""DataLoader (parity: [U:python/mxnet/gluon/data/dataloader.py]).

Same API: batchify over a Dataset with samplers, ``num_workers`` background
workers, prefetching.  Implementation differences (TPU-first): workers are
*threads* feeding a bounded prefetch queue rather than forked processes with
shared-memory NDArray pickling — decode/augment is numpy-side (NumPy releases
the GIL for the heavy parts) and the hot path for packed datasets is the C++
RecordIO reader (see native/), so fork+shm machinery (and the engine
fork-handler dance in [U:src/initialize.cc]) is unnecessary.
"""
from __future__ import annotations

import queue as _queue
import threading

import numpy as _np

from ...ndarray.ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (parity: ``default_batchify_fn``)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return NDArray(jnp.stack([d._data for d in data]))
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(items)) for items in zip(*data))
    arr = _np.asarray(data)
    if arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    return array(arr)


default_mp_batchify_fn = default_batchify_fn


class DataLoader:
    def __init__(
        self,
        dataset,
        batch_size=None,
        shuffle=False,
        sampler=None,
        last_batch=None,
        batch_sampler=None,
        batchify_fn=None,
        num_workers=0,
        pin_memory=False,
        prefetch=None,
        thread_pool=False,
        timeout=120,
    ):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be specified if batch_sampler is specified."
            )
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None else 2 * self._num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn

    def __len__(self):
        return len(self._batch_sampler)

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        yield from self._threaded_iter()

    def _threaded_iter(self):
        """Bounded-queue worker pool preserving batch order.  Workers stall
        once ``prefetch`` batches are waiting unconsumed, bounding memory."""
        import time as _time

        batches = list(self._batch_sampler)
        bound = max(self._prefetch, self._num_workers)
        out_q: dict[int, object] = {}
        consumed = [0]  # next index the consumer will take
        lock = threading.Lock()
        done = threading.Event()
        work_q = _queue.Queue()
        for i, b in enumerate(batches):
            work_q.put((i, b))

        def worker():
            while not done.is_set():
                try:
                    i, indices = work_q.get_nowait()
                except _queue.Empty:
                    return
                # respect the prefetch bound: don't run ahead of the consumer
                while not done.is_set():
                    with lock:
                        if i < consumed[0] + bound:
                            break
                    _time.sleep(0.001)
                if done.is_set():
                    return
                try:
                    batch = self._make_batch(indices)
                except Exception as e:  # surface in consumer
                    batch = e
                with lock:
                    out_q[i] = batch

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        try:
            for i in range(len(batches)):
                import time

                deadline = time.time() + self._timeout
                while True:
                    with lock:
                        if i in out_q:
                            batch = out_q.pop(i)
                            consumed[0] = i + 1
                            break
                    if time.time() > deadline:
                        raise RuntimeError(f"DataLoader timed out waiting for batch {i}")
                    time.sleep(0.001)
                if isinstance(batch, Exception):
                    raise batch
                yield batch
        finally:
            done.set()
