"""DataLoader (parity: [U:python/mxnet/gluon/data/dataloader.py]).

Same API: batchify over a Dataset with samplers, ``num_workers``
background workers, prefetching.  Worker model:

* ``num_workers>0`` (default path) — **process** workers like the
  reference, Python transforms escape the GIL.  Divergences, by design:
  the pool uses the *spawn* context (fork is unsafe once JAX/XLA's
  threaded runtime is initialized — the analog of the engine fork-handler
  dance in [U:src/initialize.cc] is "don't fork"), and workers return
  plain numpy batches over pickle instead of shared-memory NDArray
  chunks (the parent wraps them; device placement happens on the
  training thread where the accelerator lives anyway).
  ``MXNET_MP_CONTEXT=fork`` restores fork for numpy-only datasets.
  As with every spawn-based loader, script entry points need the
  standard ``if __name__ == "__main__":`` guard.
* ``thread_pool=True`` — thread workers with a bounded prefetch queue
  (cheap startup; fine when decode is C++/NumPy which release the GIL).
"""
from __future__ import annotations

import os as _os
import queue as _queue
import threading

import numpy as _np

from ...ndarray.ndarray import NDArray, array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (parity: ``default_batchify_fn``)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return NDArray(jnp.stack([d._data for d in data]))
    if isinstance(data[0], (tuple, list)):
        return tuple(default_batchify_fn(list(items)) for items in zip(*data))
    arr = _np.asarray(data)
    if arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    return array(arr)


def default_mp_batchify_fn(data):
    """Batchify in a WORKER process: stacks to numpy (the wire format the
    parent re-wraps; parity role of the reference's shared-memory
    ``reduce_ndarray`` path)."""
    first = data[0]
    if isinstance(first, NDArray):
        return _np.stack([_np.asarray(d.asnumpy()) for d in data])
    if isinstance(first, (tuple, list)):
        return tuple(default_mp_batchify_fn(list(items)) for items in zip(*data))
    arr = _np.asarray(data)
    if arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)
    return arr


def _wrap_np(batch):
    if isinstance(batch, tuple):
        return tuple(_wrap_np(b) for b in batch)
    return array(batch)


# -- process-worker globals (installed by the pool initializer) -----------
_WORKER_STATE = {}


def _mp_init(dataset, batchify_fn):
    # workers must never claim the accelerator (the parent holds it):
    # force the CPU backend before any jax import the dataset may trigger
    _os.environ["JAX_PLATFORMS"] = "cpu"
    _os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    _WORKER_STATE["dataset"] = dataset
    _WORKER_STATE["batchify"] = batchify_fn


def _mp_make_batch(indices):
    ds = _WORKER_STATE["dataset"]
    return _WORKER_STATE["batchify"]([ds[i] for i in indices])


class DataLoader:
    def __init__(
        self,
        dataset,
        batch_size=None,
        shuffle=False,
        sampler=None,
        last_batch=None,
        batch_sampler=None,
        batchify_fn=None,
        num_workers=0,
        pin_memory=False,
        prefetch=None,
        thread_pool=False,
        timeout=120,
    ):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be specified if batch_sampler is specified."
            )
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch if prefetch is not None else 2 * self._num_workers)
        self._custom_batchify = batchify_fn is not None
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._pool = None

    def __len__(self):
        return len(self._batch_sampler)

    def _make_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        if self._thread_pool:
            yield from self._threaded_iter()
        else:
            yield from self._mp_iter()

    # -- process workers (the reference's default worker model) ----------
    def _get_pool(self):
        if self._pool is None:
            import multiprocessing as mp

            ctx = mp.get_context(_os.environ.get("MXNET_MP_CONTEXT", "spawn"))
            batchify = (self._batchify_fn if self._custom_batchify
                        else default_mp_batchify_fn)
            self._pool = ctx.Pool(self._num_workers, initializer=_mp_init,
                                  initargs=(self._dataset, batchify))
        return self._pool

    def _mp_iter(self):
        pool = self._get_pool()
        batches = list(self._batch_sampler)
        bound = max(self._prefetch, self._num_workers)
        pending = {}
        nxt = 0
        for i in range(len(batches)):
            while nxt < len(batches) and nxt < i + bound:
                pending[nxt] = pool.apply_async(_mp_make_batch, (batches[nxt],))
                nxt += 1
            batch = pending.pop(i).get(self._timeout)
            yield _wrap_np(batch) if not self._custom_batchify else batch

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            try:
                pool.terminate()
            except Exception:
                pass  # interpreter shutdown: multiprocessing may be torn down

    def _threaded_iter(self):
        """Bounded-queue worker pool preserving batch order.  Workers stall
        once ``prefetch`` batches are waiting unconsumed, bounding memory."""
        import time as _time

        batches = list(self._batch_sampler)
        bound = max(self._prefetch, self._num_workers)
        out_q: dict[int, object] = {}
        consumed = [0]  # next index the consumer will take
        lock = threading.Lock()
        done = threading.Event()
        work_q = _queue.Queue()
        for i, b in enumerate(batches):
            work_q.put((i, b))

        def worker():
            while not done.is_set():
                try:
                    i, indices = work_q.get_nowait()
                except _queue.Empty:
                    return
                # respect the prefetch bound: don't run ahead of the consumer
                while not done.is_set():
                    with lock:
                        if i < consumed[0] + bound:
                            break
                    _time.sleep(0.001)
                if done.is_set():
                    return
                try:
                    batch = self._make_batch(indices)
                except Exception as e:  # surface in consumer
                    batch = e
                with lock:
                    out_q[i] = batch

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        try:
            for i in range(len(batches)):
                import time

                deadline = time.time() + self._timeout
                while True:
                    with lock:
                        if i in out_q:
                            batch = out_q.pop(i)
                            consumed[0] = i + 1
                            break
                    if time.time() > deadline:
                        raise RuntimeError(f"DataLoader timed out waiting for batch {i}")
                    time.sleep(0.001)
                if isinstance(batch, Exception):
                    raise batch
                yield batch
        finally:
            done.set()
