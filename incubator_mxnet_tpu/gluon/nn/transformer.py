"""Transformer layers (Gluon authoring style).

The reference keeps transformer blocks out-of-repo (GluonNLP); they are
in-repo here because BERT-base and Transformer-big are two of the five
baseline workloads (BASELINE.md).  Layers follow the reference's Gluon
conventions — ``hybrid_forward(F, ..., **params)``, deferred shapes via
``_shape_inference`` — so they hybridize/jit and shard like every other
block.  The attention core is :func:`ops.attention.flash_attention`
(Pallas on TPU); head projections are single fused matmuls (MXU-friendly:
one [B·S, D]×[D, 3D] GEMM for self-attention QKV).

TP sharding conventions (used by parallel.ShardingRules in the models):
qkv/ffn-in weights shard over 'tp' on the output dim (column-parallel),
out-proj/ffn-out over the input dim (row-parallel).
"""
from __future__ import annotations

import math

import numpy as _np

from ..block import HybridBlock
from .basic_layers import Dense, Dropout, LayerNorm, Embedding

__all__ = [
    "MultiHeadAttention",
    "PositionwiseFFN",
    "TransformerEncoderCell",
    "TransformerEncoder",
    "TransformerDecoderCell",
    "TransformerDecoder",
    "PositionalEmbedding",
    "SinusoidalPositionalEncoding",
]


class MultiHeadAttention(HybridBlock):
    """Multi-head attention with fused QKV projection and flash-attention
    core.  Inputs [B, S, D]; optional [B, S_kv, D] memory for cross-attn."""

    def __init__(self, units, num_heads, dropout=0.0, causal=False, use_bias=True,
                 cross=False, dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads:
            raise ValueError(f"units {units} not divisible by num_heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._causal = causal
        self._cross = cross
        with self.name_scope():
            if cross:
                self.q_proj = Dense(units, use_bias=use_bias, flatten=False, dtype=dtype, prefix="q_")
                self.kv_proj = Dense(2 * units, use_bias=use_bias, flatten=False, dtype=dtype, prefix="kv_")
            else:
                self.qkv = Dense(3 * units, use_bias=use_bias, flatten=False, dtype=dtype, prefix="qkv_")
            self.out_proj = Dense(units, use_bias=use_bias, flatten=False, dtype=dtype, prefix="out_")
        self._dropout = Dropout(dropout) if dropout else None
        if self._dropout is not None:
            self.register_child(self._dropout, "dropout")

    def forward(self, x, memory=None):
        F = self._F
        H = self._num_heads
        if self._cross:
            if memory is None:
                raise ValueError("cross-attention requires a memory input")
            q = self.q_proj(x)
            kv = self.kv_proj(memory)
            out = F.contrib.fused_kv_attention(q, kv, num_heads=H, causal=self._causal)
        else:
            qkv = self.qkv(x)  # [B, S, 3D]
            out = F.contrib.fused_qkv_attention(qkv, num_heads=H, causal=self._causal)
        out = self.out_proj(out)
        if self._dropout is not None:
            out = self._dropout(out)
        return out

    @property
    def _F(self):
        from ... import ndarray as nd_mod

        return nd_mod

    def __repr__(self):
        return f"MultiHeadAttention(units={self._units}, heads={self._num_heads}, causal={self._causal})"


class PositionwiseFFN(HybridBlock):
    """FFN sublayer: Dense→act→(dropout)→Dense (one MXU GEMM each).

    Optional rematerialization under jit tracing (SPMDTrainer / hybridize,
    no imperative tape), selected by ``MXNET_TPU_REMAT_FFN``:

    * ``none`` (DEFAULT): no checkpoint.  Measured on-chip (BERT-base
      B=64 S=128) every checkpoint variant lost ~10% end-to-end — the
      boundary breaks XLA's cross-sublayer fusion — so remat is opt-in.
    * ``policy``: ``jax.checkpoint`` saving ONLY the pre-activation
      (recomputes the ALU-cheap activation in backward, halving the
      [B, S, hidden] activation-pair HBM round-trip; the ffn_1 matmul is
      NOT recomputed).
    * ``drop_pre_act``: the complementary policy (saves everything except
      the pre-activation) — an A/B knob.
    * ``full``: recompute the whole sublayer in backward (long-context
      memory mode: trades an extra GEMM for linear-in-S residency).
    """

    def __init__(self, units, hidden_size, activation="gelu", dropout=0.0,
                 dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ffn_1 = Dense(hidden_size, flatten=False, dtype=dtype, prefix="ffn1_")
            self.ffn_2 = Dense(units, flatten=False, dtype=dtype, prefix="ffn2_")
        self._activation = activation
        self._dropout = Dropout(dropout) if dropout else None
        if self._dropout is not None:
            self.register_child(self._dropout, "dropout")

    def _body(self, x, mark=None):
        from ... import ndarray as F
        from ...ndarray.ndarray import NDArray

        h = self.ffn_1(x)
        if mark is not None:
            h = NDArray(mark(h._data))
        if self._activation == "gelu":
            h = F.LeakyReLU(h, act_type="gelu")
        else:
            h = F.Activation(h, act_type=self._activation)
        if self._dropout is not None:
            h = self._dropout(h)
        return self.ffn_2(h)

    def forward(self, x):
        import os

        # default "none": measured on-chip (BERT-base B=64 S=128) the
        # checkpoint boundary cost ~10% throughput — XLA loses cross-
        # sublayer fusion — outweighing the 1.2 GB/step of saved
        # activation writes at this scale.  "policy"/"full" remain for
        # long-context configs where residency, not bandwidth, binds.
        mode = os.environ.get("MXNET_TPU_REMAT_FFN", "none")
        if mode not in ("none", "0"):
            import jax
            from jax.ad_checkpoint import checkpoint_name

            from ... import autograd
            from ...ndarray.ndarray import NDArray

            if isinstance(x._data, jax.core.Tracer) and not autograd.is_recording():
                if mode == "full":
                    ckpt = jax.checkpoint(
                        lambda xd: self._body(NDArray(xd))._data)
                else:
                    ckpt = jax.checkpoint(
                        lambda xd: self._body(
                            NDArray(xd),
                            mark=lambda h: checkpoint_name(h, "ffn_pre_act"),
                        )._data,
                        policy=jax.checkpoint_policies.save_anything_except_these_names(
                            "ffn_pre_act") if mode == "drop_pre_act" else
                        jax.checkpoint_policies.save_only_these_names("ffn_pre_act"),
                    )
                return NDArray(ckpt(x._data))
        return self._body(x)


class TransformerEncoderCell(HybridBlock):
    """Pre/post-LN encoder layer (post-LN default = BERT convention)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="gelu", dtype="float32",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._pre_norm = pre_norm
        with self.name_scope():
            # sublayer (residual) dropout is applied ONCE by this cell via
            # self._drop — the wrapped blocks get dropout=0 to avoid
            # double-dropping the same tensor.
            self.attention = MultiHeadAttention(units, num_heads, dropout=0.0, dtype=dtype, prefix="attn_")
            self.ln_attn = LayerNorm(prefix="ln1_")
            self.ffn = PositionwiseFFN(units, hidden_size, activation, dropout=0.0, dtype=dtype, prefix="ffn_")
            self.ln_ffn = LayerNorm(prefix="ln2_")
        self._drop = Dropout(dropout) if dropout else None
        if self._drop is not None:
            self.register_child(self._drop, "dropout")

    def forward(self, x):
        if self._pre_norm:
            h = self.attention(self.ln_attn(x))
            x = x + (self._drop(h) if self._drop else h)
            h = self.ffn(self.ln_ffn(x))
            return x + (self._drop(h) if self._drop else h)
        h = self.attention(x)
        x = self.ln_attn(x + (self._drop(h) if self._drop else h))
        h = self.ffn(x)
        return self.ln_ffn(x + (self._drop(h) if self._drop else h))


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="gelu", dtype="float32",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._layers = []
        with self.name_scope():
            for i in range(num_layers):
                cell = TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout, pre_norm, activation,
                    dtype=dtype, prefix=f"layer{i}_",
                )
                self.register_child(cell, f"layer{i}")
                self._layers.append(cell)

    def forward(self, x):
        for cell in self._layers:
            x = cell(x)
        return x


class TransformerDecoderCell(HybridBlock):
    """Decoder layer: causal self-attn + cross-attn + FFN."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="relu", dtype="float32",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._pre_norm = pre_norm
        with self.name_scope():
            # same single-residual-dropout discipline as the encoder cell
            self.self_attention = MultiHeadAttention(
                units, num_heads, dropout=0.0, causal=True, dtype=dtype, prefix="selfattn_"
            )
            self.ln_self = LayerNorm(prefix="ln1_")
            self.cross_attention = MultiHeadAttention(
                units, num_heads, dropout=0.0, cross=True, dtype=dtype, prefix="crossattn_"
            )
            self.ln_cross = LayerNorm(prefix="ln2_")
            self.ffn = PositionwiseFFN(units, hidden_size, activation, dropout=0.0, dtype=dtype, prefix="ffn_")
            self.ln_ffn = LayerNorm(prefix="ln3_")
        self._drop = Dropout(dropout) if dropout else None
        if self._drop is not None:
            self.register_child(self._drop, "dropout")

    def forward(self, x, memory):
        d = self._drop if self._drop is not None else (lambda t: t)
        if self._pre_norm:
            x = x + d(self.self_attention(self.ln_self(x)))
            x = x + d(self.cross_attention(self.ln_cross(x), memory))
            return x + d(self.ffn(self.ln_ffn(x)))
        x = self.ln_self(x + d(self.self_attention(x)))
        x = self.ln_cross(x + d(self.cross_attention(x, memory)))
        return self.ln_ffn(x + d(self.ffn(x)))


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="relu", dtype="float32",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._layers = []
        with self.name_scope():
            for i in range(num_layers):
                cell = TransformerDecoderCell(
                    units, hidden_size, num_heads, dropout, pre_norm, activation,
                    dtype=dtype, prefix=f"layer{i}_",
                )
                self.register_child(cell, f"layer{i}")
                self._layers.append(cell)

    def forward(self, x, memory):
        for cell in self._layers:
            x = cell(x, memory)
        return x


class PositionalEmbedding(HybridBlock):
    """Learned positional embedding (BERT style)."""

    def __init__(self, max_length, units, dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._max_length = max_length
        with self.name_scope():
            self.embed = Embedding(max_length, units, dtype=dtype, prefix="pos_")

    def forward(self, x):
        """x: [B, S, D] → x + pos[:S]."""
        from ... import ndarray as F

        positions = F.arange(0, x.shape[1], dtype="int32")
        return x + self.embed(positions)


class SinusoidalPositionalEncoding(HybridBlock):
    """Fixed sinusoidal encoding (Transformer-WMT style); no parameters."""

    def __init__(self, units, max_length=4096, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        pos = _np.arange(max_length)[:, None]
        dim = _np.arange((units + 1) // 2)[None, :]
        angle = pos / _np.power(10000.0, 2 * dim / units)
        table = _np.zeros((max_length, units), dtype=_np.float32)
        table[:, 0::2] = _np.sin(angle)
        table[:, 1::2] = _np.cos(angle[:, : units // 2])
        self._table = table

    def forward(self, x):
        import jax.numpy as jnp

        from ...ndarray.ndarray import NDArray

        seq = x.shape[1]
        table = jnp.asarray(self._table[:seq]).astype(x.dtype)  # no bf16→f32 promotion
        return x + NDArray(table)
