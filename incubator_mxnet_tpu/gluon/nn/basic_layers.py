"""Basic neural-network layers.

Parity target: [U:python/mxnet/gluon/nn/basic_layers.py] — Sequential,
Dense, Dropout, BatchNorm, LayerNorm, GroupNorm, InstanceNorm, Embedding,
Flatten, Lambda/HybridLambda.  Authoring convention (hybrid_forward with
params as kwargs) matches the reference so user subclasses port unchanged.
"""
from __future__ import annotations

import numpy as _np

from .. import block as _block
from ..block import Block, HybridBlock, collect_aux_update
from ... import initializer as init_mod

__all__ = [
    "Sequential",
    "HybridSequential",
    "Dense",
    "Dropout",
    "BatchNorm",
    "LayerNorm",
    "GroupNorm",
    "InstanceNorm",
    "Embedding",
    "Flatten",
    "Lambda",
    "HybridLambda",
    "Concatenate",
    "HybridConcatenate",
    "Identity",
]


def _split_stages(container, sizes):
    """Shared ``split_stages`` body for the sequential containers: carve
    the child list into consecutive stages of ``sizes[i]`` layers each.
    Stages are new containers holding the SAME child blocks (and thus the
    same Parameters) — exactly what ``SPMDTrainer(stages=...)`` needs:
    the stage partition is a view, never a copy."""
    sizes = [int(n) for n in sizes]
    if any(n < 1 for n in sizes):
        raise ValueError(f"every stage needs >= 1 layer, got {sizes}")
    n = len(container)
    if sum(sizes) != n:
        raise ValueError(
            f"stage sizes {sizes} sum to {sum(sizes)} but the container "
            f"has {n} layers")
    out, at = [], 0
    for k in sizes:
        out.append(container[at:at + k])
        at += k
    return out


class Sequential(Block):
    """Sequential container (parity: ``nn.Sequential``)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def split_stages(self, sizes):
        """Partition into pipeline stages: ``net.split_stages([2, 3, 2])``
        → three Sequentials of 2/3/2 consecutive layers sharing this
        container's child blocks/Parameters (for ``SPMDTrainer``'s
        ``stages=`` pipeline tier)."""
        return _split_stages(self, sizes)

    def forward(self, x, *args):
        for child in self._children.values():
            x = child(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*layers[key])
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Hybridizable sequential container (parity: ``nn.HybridSequential``)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def split_stages(self, sizes):
        """Partition into pipeline stages (see ``Sequential.split_stages``)."""
        return _split_stages(self, sizes)

    def forward(self, x, *args):
        # container: no own params; recurse into children directly
        return self._seq_forward(x, *args)

    def _seq_forward(self, x, *args):
        for child in self._children.values():
            x = child(x, *args)
            args = ()
        return x

    def hybrid_forward(self, F, x, *args, **params):
        return self._seq_forward(x, *args)

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*layers[key])
            return net
        return layers[key]

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (parity: ``nn.Dense`` → FullyConnected op →
    one MXU matmul).  ``in_units`` may be deferred."""

    def __init__(
        self,
        units,
        activation=None,
        use_bias=True,
        flatten=True,
        dtype="float32",
        weight_initializer=None,
        bias_initializer="zeros",
        in_units=0,
        prefix=None,
        params=None,
    ):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        self._use_bias = use_bias
        self._act_type = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight",
                shape=(units, in_units),
                dtype=dtype,
                init=weight_initializer,
                allow_deferred_init=True,
            )
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype, init=bias_initializer, allow_deferred_init=True
                )

    def _shape_inference(self, x, *args):
        in_units = int(_np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight._finish_deferred_init((self._units, in_units))
        if self._use_bias:
            self.bias._finish_deferred_init((self._units,))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units, no_bias=bias is None, flatten=self._flatten)
        if self._act_type is not None:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return f"Dense({shape[1] if shape and len(shape) > 1 else None} -> {self._units}, " \
               f"{'linear' if self._act_type is None else self._act_type})"


class Dropout(HybridBlock):
    """Parity: ``nn.Dropout``."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with running statistics (parity: ``nn.BatchNorm``).

    Under hybridize the running-stat update rides the compiled graph as
    extra outputs (see block.collect_aux_update); eagerly it's applied
    immediately — either way semantics match the reference's in-op
    aux mutation.
    """

    def __init__(
        self,
        axis=1,
        momentum=0.9,
        epsilon=1e-5,
        center=True,
        scale=True,
        use_global_stats=False,
        beta_initializer="zeros",
        gamma_initializer="ones",
        running_mean_initializer="zeros",
        running_variance_initializer="ones",
        in_channels=0,
        prefix=None,
        params=None,
    ):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma",
                grad_req="write" if scale else "null",
                shape=(in_channels,),
                init=gamma_initializer,
                allow_deferred_init=True,
                differentiable=scale,
            )
            self.beta = self.params.get(
                "beta",
                grad_req="write" if center else "null",
                shape=(in_channels,),
                init=beta_initializer,
                allow_deferred_init=True,
                differentiable=center,
            )
            self.running_mean = self.params.get(
                "running_mean",
                grad_req="null",
                shape=(in_channels,),
                init=running_mean_initializer,
                allow_deferred_init=True,
                differentiable=False,
            )
            self.running_var = self.params.get(
                "running_var",
                grad_req="null",
                shape=(in_channels,),
                init=running_variance_initializer,
                allow_deferred_init=True,
                differentiable=False,
            )

    def _shape_inference(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p._finish_deferred_init((c,))

    def cast(self, dtype):
        if _np.dtype(dtype).kind == "f" and str(dtype) in ("float16", "bfloat16"):
            dtype = "float32"  # parity: BN statistics stay fp32
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd

        training = autograd.is_training()
        use_global = self._use_global_stats or not training
        out = F.BatchNorm(
            x,
            gamma,
            beta,
            running_mean,
            running_var,
            eps=self._epsilon,
            momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=use_global,
        )
        out, batch_mean, batch_var = out
        if not use_global:
            m = self._momentum
            collect_aux_update(self.running_mean, running_mean * m + batch_mean * (1 - m))
            collect_aux_update(self.running_var, running_var * m + batch_var * (1 - m))
        return out

    def __repr__(self):
        return f"BatchNorm(axis={self._axis}, eps={self._epsilon}, momentum={self._momentum}, in_channels={self.in_channels})"


class LayerNorm(HybridBlock):
    """Parity: ``nn.LayerNorm``."""

    def __init__(
        self,
        axis=-1,
        epsilon=1e-5,
        center=True,
        scale=True,
        beta_initializer="zeros",
        gamma_initializer="ones",
        in_channels=0,
        prefix=None,
        params=None,
    ):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null", shape=(in_channels,),
                init=gamma_initializer, allow_deferred_init=True
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null", shape=(in_channels,),
                init=beta_initializer, allow_deferred_init=True
            )

    def _shape_inference(self, x, *args):
        c = x.shape[self._axis]
        self.gamma._finish_deferred_init((c,))
        self.beta._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    """Parity: ``nn.GroupNorm``."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True)

    def _shape_inference(self, x, *args):
        c = x.shape[1]
        self.gamma._finish_deferred_init((c,))
        self.beta._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups, eps=self._epsilon)


class InstanceNorm(HybridBlock):
    """Parity: ``nn.InstanceNorm``."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True)

    def _shape_inference(self, x, *args):
        c = x.shape[1]
        self.gamma._finish_deferred_init((c,))
        self.beta._finish_deferred_init((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class Embedding(HybridBlock):
    """Parity: ``nn.Embedding``.  ``sparse_grad=True`` marks the weight
    ``row_sparse`` — gradients are stored densely on TPU (static shapes),
    but SGD/Adam then apply the reference's LAZY row semantics: rows not
    touched by a batch skip momentum decay / weight decay entirely
    (ops/optimizer_ops.py ``*_lazy_update``)."""

    def __init__(self, input_dim, output_dim, dtype="float32", weight_initializer=None,
                 sparse_grad=False, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype, init=weight_initializer,
                stype="row_sparse" if sparse_grad else "default",
            )

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim, output_dim=self._output_dim)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    """Parity: ``nn.Flatten``."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class Lambda(Block):
    """Wrap a function as a Block (parity: ``nn.Lambda``)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    """Parity: ``nn.HybridLambda``."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._name_of_func = function

            def fn(F, *args):
                return getattr(F, function)(*args)

            self._func = fn
        else:
            self._func = lambda F, *args: function(F, *args)

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)


class Concatenate(Sequential):
    """Run children on the same input, concat outputs (parity:
    ``contrib.nn.Concurrent``)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as nd

        outs = [child(x) for child in self._children.values()]
        return nd.concat(*outs, dim=self.axis)


class HybridConcatenate(HybridSequential):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def _seq_forward(self, x, *args):
        from ... import ndarray as nd

        outs = [child(x) for child in self._children.values()]
        return nd.concat(*outs, dim=self.axis)
