"""Convolution / pooling layers.

Parity target: [U:python/mxnet/gluon/nn/conv_layers.py] — Conv1D/2D/3D,
Conv*DTranspose, Max/Avg/GlobalMax/GlobalAvg pooling, ReflectionPad2D.
NCHW/OIHW conventions preserved; XLA:TPU handles the layout for the MXU.
"""
from __future__ import annotations

import numpy as _np

from ..block import HybridBlock

__all__ = [
    "Conv1D",
    "Conv2D",
    "Conv3D",
    "Conv1DTranspose",
    "Conv2DTranspose",
    "Conv3DTranspose",
    "MaxPool1D",
    "MaxPool2D",
    "MaxPool3D",
    "AvgPool1D",
    "AvgPool2D",
    "AvgPool3D",
    "GlobalMaxPool1D",
    "GlobalMaxPool2D",
    "GlobalMaxPool3D",
    "GlobalAvgPool1D",
    "GlobalAvgPool2D",
    "GlobalAvgPool3D",
    "ReflectionPad2D",
]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    def __init__(
        self,
        channels,
        kernel_size,
        strides,
        padding,
        dilation,
        groups,
        layout,
        in_channels=0,
        activation=None,
        use_bias=True,
        weight_initializer=None,
        bias_initializer="zeros",
        op_name="Convolution",
        adj=None,
        prefix=None,
        params=None,
    ):
        super().__init__(prefix=prefix, params=params)
        ndim = len(kernel_size)
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._stride = strides
        self._pad = padding
        self._dilate = dilation
        self._groups = groups
        self._layout = layout
        self._act_type = activation
        self._use_bias = use_bias
        self._op_name = op_name
        self._adj = adj
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups if in_channels else 0) + kernel_size
            else:  # Deconvolution: (in, out/groups, *k)
                wshape = (in_channels if in_channels else 0, channels // groups) + kernel_size
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer, allow_deferred_init=True
            )
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,), init=bias_initializer)
            else:
                self.bias = None

    def _shape_inference(self, x, *args):
        c_in = x.shape[1]
        if self._op_name == "Convolution":
            self.weight._finish_deferred_init((self._channels, c_in // self._groups) + self._kernel)
        else:
            self.weight._finish_deferred_init((c_in, self._channels // self._groups) + self._kernel)
        self._in_channels = c_in

    def hybrid_forward(self, F, x, weight, bias=None):
        kwargs = dict(
            kernel=self._kernel,
            stride=self._stride,
            dilate=self._dilate,
            pad=self._pad,
            num_filter=self._channels,
            num_group=self._groups,
            no_bias=bias is None,
        )
        if self._op_name == "Deconvolution":
            kwargs["adj"] = self._adj
            out = F.Deconvolution(x, weight, bias, **kwargs)
        else:
            out = F.Convolution(x, weight, bias, **kwargs)
        if self._act_type is not None:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        return (
            f"{type(self).__name__}({self._in_channels or None} -> {self._channels}, "
            f"kernel_size={self._kernel}, stride={self._stride}, padding={self._pad})"
        )


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1, groups=1,
                 layout="NCW", in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1), _tup(padding, 1),
                         _tup(dilation, 1), groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, prefix=prefix, params=params)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0), dilation=(1, 1),
                 groups=1, layout="NCHW", in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2), _tup(padding, 2),
                         _tup(dilation, 2), groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, prefix=prefix, params=params)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros", prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3), _tup(padding, 3),
                         _tup(dilation, 3), groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, prefix=prefix, params=params)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, output_padding=0, dilation=1,
                 groups=1, layout="NCW", in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1), _tup(padding, 1),
                         _tup(dilation, 1), groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, op_name="Deconvolution",
                         adj=_tup(output_padding, 1), prefix=prefix, params=params)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0), output_padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros", prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2), _tup(padding, 2),
                         _tup(dilation, 2), groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, op_name="Deconvolution",
                         adj=_tup(output_padding, 2), prefix=prefix, params=params)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 output_padding=(0, 0, 0), dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 in_channels=0, activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3), _tup(padding, 3),
                         _tup(dilation, 3), groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, op_name="Deconvolution",
                         adj=_tup(output_padding, 3), prefix=prefix, params=params)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool, pool_type,
                 count_include_pad=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if strides is None:
            strides = pool_size
        self._kwargs = dict(
            kernel=pool_size,
            stride=strides,
            pad=padding,
            global_pool=global_pool,
            pool_type=pool_type,
            pooling_convention="full" if ceil_mode else "valid",
        )
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return f"{type(self).__name__}(size={self._kwargs['kernel']}, stride={self._kwargs['stride']}, padding={self._kwargs['pad']})"


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_tup(pool_size, 1), _tup(strides, 1) if strides is not None else None,
                         _tup(padding, 1), ceil_mode, False, "max", prefix=prefix, params=params)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_tup(pool_size, 2), _tup(strides, 2) if strides is not None else None,
                         _tup(padding, 2), ceil_mode, False, "max", prefix=prefix, params=params)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout="NCDHW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_tup(pool_size, 3), _tup(strides, 3) if strides is not None else None,
                         _tup(padding, 3), ceil_mode, False, "max", prefix=prefix, params=params)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW", ceil_mode=False,
                 count_include_pad=True, prefix=None, params=None):
        super().__init__(_tup(pool_size, 1), _tup(strides, 1) if strides is not None else None,
                         _tup(padding, 1), ceil_mode, False, "avg", count_include_pad, prefix=prefix, params=params)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW", ceil_mode=False,
                 count_include_pad=True, prefix=None, params=None):
        super().__init__(_tup(pool_size, 2), _tup(strides, 2) if strides is not None else None,
                         _tup(padding, 2), ceil_mode, False, "avg", count_include_pad, prefix=prefix, params=params)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout="NCDHW", ceil_mode=False,
                 count_include_pad=True, prefix=None, params=None):
        super().__init__(_tup(pool_size, 3), _tup(strides, 3) if strides is not None else None,
                         _tup(padding, 3), ceil_mode, False, "avg", count_include_pad, prefix=prefix, params=params)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", prefix=None, params=None):
        super().__init__((1,), None, (0,), False, True, "max", prefix=prefix, params=params)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", prefix=None, params=None):
        super().__init__((1, 1), None, (0, 0), False, True, "max", prefix=prefix, params=params)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", prefix=None, params=None):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "max", prefix=prefix, params=params)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", prefix=None, params=None):
        super().__init__((1,), None, (0,), False, True, "avg", prefix=prefix, params=params)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", prefix=None, params=None):
        super().__init__((1, 1), None, (0, 0), False, True, "avg", prefix=prefix, params=params)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", prefix=None, params=None):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "avg", prefix=prefix, params=params)


class ReflectionPad2D(HybridBlock):
    """Parity: ``nn.ReflectionPad2D``."""

    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)


_np  # keep import
