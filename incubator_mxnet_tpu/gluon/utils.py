"""Gluon utilities (parity: [U:python/mxnet/gluon/utils.py]):
``split_data``/``split_and_load`` (multi-device batch slicing),
``clip_global_norm``, ``check_sha1``, ``download`` (gated: zero-egress
sandbox)."""
from __future__ import annotations

import hashlib
import os

import numpy as _np

from ..ndarray.ndarray import NDArray, array

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into {num_slice} "
            f"slices along axis {batch_axis}"
        )
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch across contexts (parity: ``gluon.utils.split_and_load``).
    On a single TPU mesh this is commonly [one ctx] → returns [data]."""
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Parity: ``gluon.utils.clip_global_norm``."""
    import math

    total = 0.0
    for a in arrays:
        n = float(a.norm().asscalar())
        total += n * n
    total = math.sqrt(total)
    if check_isfinite and not math.isfinite(total):
        import warnings

        warnings.warn("nan or inf is detected. Clipping results will be undefined.")
    scale = max_norm / (total + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a *= scale
    return total


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5, verify_ssl=True):
    """Parity shim: this sandbox has zero egress; only file:// and existing
    local paths are served."""
    fname = path or url.split("/")[-1]
    if os.path.exists(fname) and not overwrite:
        return fname
    if url.startswith("file://"):
        import shutil

        shutil.copy(url[7:], fname)
        return fname
    raise RuntimeError(
        f"download({url}) unavailable: no network egress in this environment; "
        "place the file locally and pass its path"
    )


_np  # keep import
