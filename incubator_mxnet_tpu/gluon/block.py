"""Gluon Block / HybridBlock.

Parity target: [U:python/mxnet/gluon/block.py] + the CachedOp it drives
([U:src/imperative/cached_op.cc]).  THE central mapping of the whole build
(SURVEY.md §3.2): the reference's ``hybridize()`` traces ``hybrid_forward``
with symbols once and builds a CachedOp; here ``hybridize()`` compiles the
whole block tree into ONE ``jax.jit`` callable per input signature:

* the jitted function is pure: ``(prng_key, *inputs, *params) ->
  (*outputs, *aux_updates)``;
* during tracing, ``Parameter.data()`` returns traced stand-ins so child
  blocks compose into the same graph (the reference reaches the same goal
  by passing ``F=symbol`` down the tree);
* BatchNorm-style running-stat updates are collected as extra outputs and
  written back after execution (the reference mutates aux arrays inside
  the op);
* under ``autograd.record``, the whole jitted call is ONE tape node —
  exactly CachedOp's "one tape node for the whole cached graph";
* ``static_alloc`` maps to XLA buffer donation (donate_argnums on params is
  unsafe here because params persist; donation applies in the fused
  train-step path in parallel/), ``static_shape`` is implicit (XLA).
"""
from __future__ import annotations

import contextlib
import re
import threading

import jax
import numpy as _np

from time import perf_counter as _perf

from .. import autograd
from .. import ndarray as nd_mod
from .. import profiler as _profiler
from ..context import current_context
from ..engine import DeferredArray as _Deferred
from ..ndarray.ndarray import NDArray
from ..random import get_key, push_traced_key, pop_traced_key
from .parameter import Parameter, ParameterDict

__all__ = ["Block", "HybridBlock", "SymbolBlock", "name_scope",
           "trace_scope", "traced_params"]

_tls = threading.local()


def _naming_counter():
    if not hasattr(_tls, "counters"):
        _tls.counters = [{}]
    return _tls.counters[-1]


def _gen_prefix(hint):
    c = _naming_counter()
    idx = c.get(hint, 0)
    c[hint] = idx + 1
    return f"{hint}{idx}_"


@contextlib.contextmanager
def name_scope():
    if not hasattr(_tls, "counters"):
        _tls.counters = [{}]
    _tls.counters.append({})
    try:
        yield
    finally:
        _tls.counters.pop()


# -- aux-update collection (BatchNorm running stats under jit) --------------


def _aux_stack():
    if not hasattr(_tls, "aux"):
        _tls.aux = []
    return _tls.aux


def collect_aux_update(param, new_value):
    """Called by layers whose forward has aux side effects.  Inside a
    hybridize trace the update becomes an extra jit output; eagerly it is
    applied immediately."""
    stack = _aux_stack()
    if stack:
        stack[-1].append((param, new_value))
    else:
        with autograd.pause():
            param.set_data(new_value)


def _is_tracing():
    return bool(getattr(_tls, "tracing", 0))


@contextlib.contextmanager
def trace_scope(params, arrays, key, training, collector=None):
    """THE trace-scope ceremony shared by every whole-graph capturer in the
    repo — the CachedOp build (``_build_cache``), ``export_jittable``, the
    SPMDTrainer step builders (``parallel/trainer.py``) and the Gluon step
    fold (``step_fold.py``) all enter their traces through here, so the
    fragile save/restore protocol exists exactly once.

    For each ``(param, array)`` pair: sets ``param._traced_data`` so
    ``Parameter.data()`` returns the traced stand-in, pushes ``key`` as the
    traced PRNG key, pushes an aux-update frame (``collector`` or a fresh
    throwaway) so BatchNorm-style side effects are captured instead of
    applied, marks the block-tracing TLS, and enters recording-off autograd
    with the given ``training`` mode — restoring ALL of it on exit,
    exception or not.  Yields the aux frame."""
    saved = []
    for p, a in zip(params, arrays):
        saved.append(getattr(p, "_traced_data", None))
        p._traced_data = a if isinstance(a, NDArray) else NDArray(a)
    push_traced_key(key)
    own = collector if collector is not None else []
    _aux_stack().append(own)
    prev = getattr(_tls, "tracing", 0)
    _tls.tracing = prev + 1
    try:
        with autograd._scope(False, training):
            yield own
    finally:
        _tls.tracing = prev
        _aux_stack().pop()
        pop_traced_key()
        for p, s in zip(params, saved):
            p._traced_data = s


def traced_params(params, arrays):
    """Eval-mode :func:`trace_scope` with a fixed key — the ceremony for
    hand-built pure jit programs that call Gluon blocks with parameters
    BAKED IN as captured constants (the KV-cache decode discipline:
    per-leaf jit argument processing costs ~0.5 ms/arg on slow hosts, and
    inference params are frozen anyway).  Used by
    ``model_zoo.transformer._KVCacheDecoder`` and the serving tier's
    generation programs."""
    return trace_scope(params, arrays, jax.random.PRNGKey(0), False)


class _BlockScope:
    """Name-scope manager for Blocks (parity: ``_BlockScope`` in the
    reference — naming discipline matters for checkpoint compat)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _gen_prefix(hint)
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            idx = current._counter.get(hint, 0)
            current._counter[hint] = idx + 1
            prefix = f"{hint}{idx}_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


class Block:
    """Base building block (parity: ``gluon.Block``).  Define-by-run:
    ``__call__`` dispatches to ``forward`` with NDArrays."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    # -- attribute plumbing ---------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            if "_params" in self.__dict__:
                self._params._params[value.name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All Parameters of self and children, optionally regex-filtered
        (parity: ``Block.collect_params``)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({k: v for k, v in self.params.items() if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)
        return self

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)
        return self

    # -- save/load -------------------------------------------------------
    def save_parameters(self, filename, deduplicate=False):
        """Parity: ``Block.save_parameters`` (params only, by name)."""
        params = self._collect_params_with_prefix()
        from ..ndarray.utils import save as nd_save

        nd_save(filename, {k: v.data() for k, v in params.items()})

    def load_parameters(
        self, filename, ctx=None, allow_missing=False, ignore_extra=False, cast_dtype=False, dtype_source="current"
    ):
        from ..ndarray.utils import load as nd_load

        loaded = nd_load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise IOError(f"Parameter {name} missing in {filename}")
        for name, v in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise IOError(f"Parameter {name} in {filename} not found in Block")
                continue
            params[name].set_data(v)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {}
        for name, param in self.params.items():
            suffix = name[len(self._params.prefix):] if name.startswith(self._params.prefix) else name
            ret[prefix + suffix] = param
        for cname, child in self._children.items():
            attr = None
            for k, v in self.__dict__.items():
                if v is child:
                    attr = k
                    break
            ret.update(child._collect_params_with_prefix(prefix + (attr or cname)))
        return ret

    save_params = save_parameters

    def load_params(self, filename, ctx=None, **kwargs):
        self.load_parameters(filename, ctx, **kwargs)

    # -- execution -------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        """No-op on plain Blocks except to recurse (parity)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def export_jittable(self, training=False, rng_key=None):
        """Return ``(fn, param_arrays)`` — a PURE function over jax arrays.

        ``fn(param_arrays, *input_arrays) -> array | tuple of arrays`` runs
        this block's forward with parameters taken from the ``param_arrays``
        list (sorted by parameter name, matching ``param_arrays``'s order)
        instead of the block's own buffers.  It is safe to ``jax.jit``,
        ``jax.grad``, shard, or export to StableHLO — this is the supported
        surface for driver harnesses and serving (the role
        [U:src/c_api/c_predict_api.cc] plays for the reference), replacing
        any reach into ``_traced_data``/TLS internals.

        ``training`` selects train-mode semantics (dropout live, BatchNorm
        batch stats; aux-state side effects are NOT returned — use
        ``parallel.SPMDTrainer`` for a full training step).  ``rng_key``
        seeds dropout when training (default: a fixed key, so the exported
        fn is deterministic).
        """
        import jax

        params = sorted(self.collect_params().values(), key=lambda p: p.name)
        for p in params:
            if p._data is None:
                raise ValueError(
                    f"Parameter {p.name} is not materialized (deferred init?). "
                    "Run one forward pass before export_jittable().")
        param_arrays = [p._data._data for p in params]
        key = rng_key if rng_key is not None else jax.random.PRNGKey(0)
        block = self

        def fn(param_arrs, *inputs):
            with trace_scope(params, param_arrs, key, training):
                out = block(*[NDArray(x) if x is not None else None
                              for x in inputs])
            if isinstance(out, (list, tuple)):
                return tuple(o._data for o in out)
            return out._data

        return fn, param_arrays

    def summary(self, *inputs):
        """Print a per-layer summary (parity: ``Block.summary``)."""
        rows = []

        def add_hook(block, name):
            def hook(b, inp, out):
                o = out[0] if isinstance(out, (list, tuple)) else out
                n_params = sum(
                    int(_np.prod(p.shape)) for p in b.params.values() if p.shape and all(s > 0 for s in p.shape)
                )
                rows.append((name or b.name, type(b).__name__, tuple(getattr(o, "shape", ())), n_params))

            return hook

        handles = []
        for name, child in self._children.items():
            child._forward_hooks.append(add_hook(child, name))
            handles.append(child)
        try:
            self(*inputs)
        finally:
            for child in handles:
                child._forward_hooks.pop()
        header = f"{'Layer':<28}{'Type':<20}{'Output shape':<24}{'Params':<12}"
        print(header)
        print("-" * len(header))
        for r in rows:
            print(f"{r[0]:<28}{r[1]:<20}{str(r[2]):<24}{r[3]:<12}")

    def __repr__(self):
        lines = [f"{self.__class__.__name__}("]
        for name, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        lines.append(")")
        return "\n".join(lines)


class HybridBlock(Block):
    """A Block whose forward can be compiled (parity: ``gluon.HybridBlock``).

    Subclasses implement ``hybrid_forward(self, F, x, *args, **params)``
    where ``F`` is the nd namespace and params arrive as keyword NDArrays —
    the reference's exact authoring convention, so model code ports 1:1.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_graph = {}
        self._flags = {}
        from ..base import register_jit_cache_owner
        register_jit_cache_owner(self)

    def _invalidate_jit_cache(self):
        self._cached_graph.clear()

    def hybridize(self, active=True, static_alloc=False, static_shape=False, **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape, **kwargs)
        self._cached_graph.clear()
        super().hybridize(active, static_alloc=static_alloc, static_shape=static_shape, **kwargs)
        return self

    def infer_shape(self, *args):
        """Infer deferred parameter shapes by running an abstract forward
        (the reference uses the symbolic shape-inference pass; here
        ``jax.eval_shape`` on the same code)."""
        self._deferred_infer(args)

    def _deferred_infer(self, args):
        # Run once eagerly with recording off; layers finish deferred init
        # inside their hybrid_forward when they see concrete inputs.
        pass

    def cast(self, dtype):
        self._cached_graph.clear()
        return super().cast(dtype)

    # -- parameter plumbing for the compiled path -----------------------
    def _ordered_params(self):
        params = list(self.collect_params().values())
        params.sort(key=lambda p: p.name)
        return params

    def _call_defer_init(self, *args):
        """First call with concrete inputs: finish deferred param init by
        running the eager path under no-grad on a zero-cost abstract trace
        is impossible (init needs shapes only), so layers infer shapes from
        the concrete inputs inside hybrid_forward."""
        return None

    def __call__(self, *args, **kwargs):
        if self._active and not _is_tracing() and not kwargs:
            try:
                return self._call_cached(args)
            except DeferredInit:
                # materialization pass: run eagerly once with aux side
                # effects swallowed (a throwaway collector), then retry the
                # cached path so the first user-visible call compiles +
                # caches AND applies aux updates exactly once
                _aux_stack().append([])
                try:
                    super().__call__(*args, **kwargs)
                finally:
                    _aux_stack().pop()
                try:
                    return self._call_cached(args)
                except DeferredInit:
                    # a param forward never touches can stay deferred;
                    # fall back to plain eager (real side effects)
                    return super().__call__(*args, **kwargs)
        return super().__call__(*args, **kwargs)

    def forward(self, x, *args):
        """Dispatch to hybrid_forward with parameters as kwargs (parity:
        HybridBlock.forward's NDArray branch).  On deferred parameters the
        layer's shape-inference hook runs first (the reference does this via
        the symbolic infer-shape pass)."""
        from ..base import DeferredInitializationError

        def gather():
            out = {}
            for name, param in self.params.items():
                suffix = name[len(self._params.prefix):] if name.startswith(self._params.prefix) else name
                out[suffix] = param.data()
            return out

        try:
            params = gather()
        except DeferredInitializationError:
            self._shape_inference(x, *args)
            params = gather()
        return self.hybrid_forward(nd_mod, x, *args, **params)

    def _shape_inference(self, x, *args):
        """Finish deferred param init from concrete input shapes; layers with
        deferred params override this."""
        raise RuntimeError(
            f"{type(self).__name__} has deferred-init parameters but no "
            "shape-inference hook; initialize with concrete shapes"
        )

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- the CachedOp equivalent ----------------------------------------
    def _call_cached(self, args):
        flat_in = [a for a in args if isinstance(a, NDArray)]
        if len(flat_in) != len(args):
            return super().__call__(*args)
        params = self._ordered_params()
        for p in params:
            if p._deferred_init is not None or p._data is None:
                raise DeferredInit()
        training = autograd.is_training() or autograd.is_recording()
        key_sig = (
            tuple((tuple(a.shape), str(a.dtype)) for a in args),
            training,
        )
        entry = self._cached_graph.get(key_sig)
        fresh = entry is None
        if fresh:
            entry = self._build_cache(args, params, training)
            self._cached_graph[key_sig] = entry
        jit_fn, n_out, aux_params = entry
        key = get_key()
        raw_params = [p._data for p in params]  # NDArray leaves (tape prov)
        all_inputs = list(args) + raw_params
        # inputs produced inside an engine.bulk() scope may hold pending
        # DeferredArrays — jit_fn consumes raw jax arrays directly (this path
        # bypasses ndarray.invoke's resolve loop), so force them here
        for a in all_inputs:
            d = a._data
            if isinstance(d, _Deferred):
                a._data = d._resolve()

        def fn(*arrs, _jit=jit_fn, _key=key):
            return _jit(_key, *arrs)

        tc = _perf() if fresh else None
        node = None
        if autograd.is_recording():
            raws = [a._data for a in all_inputs]
            outs, node = autograd.record_op(fn, raws, all_inputs, {}, name=self.name)
            if node is None:
                outs = fn(*raws)
        else:
            outs = fn(*(a._data for a in all_inputs))
        if tc is not None:
            sig = {"__program__":
                   f"{self.name}:{'train' if training else 'eval'}"}
            for i, (shape, dt) in enumerate(key_sig[0]):
                sig[f"in{i}"] = {"k": "array", "shape": tuple(shape),
                                 "dtype": dt}
            sig["params"] = _profiler.sig_static(len(params))
            _profiler.record_compile("block.cached_op", sig,
                                     (_perf() - tc) * 1e3)
        outs = list(outs)
        aux_new = outs[n_out:]
        outs = outs[:n_out]
        with autograd.pause():
            for p, new in zip(aux_params, aux_new):
                p.set_data(NDArray(new))
        results = []
        for i, o in enumerate(outs):
            r = NDArray(o, ctx=flat_in[0]._ctx if flat_in else current_context())
            if autograd.is_recording() and node is not None:
                r._prov = (node, i)
            results.append(r)
        return results[0] if len(results) == 1 else results

    def _build_cache(self, args, params, training):
        """Trace + compile the whole block tree into one jit callable
        (the CachedOp ctor analog)."""
        n_out_cell = []
        aux_params_cell = []
        block = self

        def pure(key, *arrs):
            n_in = len(args)
            ins = [NDArray(a) for a in arrs[:n_in]]
            with trace_scope(params, arrs[n_in:], key, training) as collector:
                out = block.forward(*ins)
            outs = out if isinstance(out, (list, tuple)) else [out]
            if not n_out_cell:
                n_out_cell.append(len(outs))
                aux_params_cell.extend(p for p, _ in collector)
            return tuple(o._data for o in outs) + tuple(v._data if isinstance(v, NDArray) else v for _, v in collector)

        jit_fn = jax.jit(pure)
        # Populate n_out/aux metadata via an abstract trace (no execution).
        # The probe key is an AVAL, not get_key(): consuming a real split
        # here would shift the ambient PRNG stream by one on every fresh
        # signature — the folded step (step_fold.py) and this path must
        # draw identical per-step keys for dropout parity.
        ex = jax.random.PRNGKey(0)
        jax.eval_shape(pure, jax.ShapeDtypeStruct(ex.shape, ex.dtype),
                       *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args],
                       *[jax.ShapeDtypeStruct(p._data.shape, p._data.dtype) for p in params])
        return jit_fn, n_out_cell[0], aux_params_cell

    def export(self, path, epoch=0, remove_amp_cast=True):
        """Export compiled graph + params for deployment (parity:
        ``HybridBlock.export`` — symbol.json + params).  Saves StableHLO
        text instead of nnvm JSON (documented divergence)."""
        params = self._ordered_params()
        if not self._cached_graph:
            raise RuntimeError("Please first call block.hybridize() and then run forward with this block at least once before calling export.")
        from ..ndarray.utils import save as nd_save

        arg_dict = {}
        for p in params:
            prefix = "aux:" if p.grad_req == "null" else "arg:"
            arg_dict[prefix + p.name] = p.data()
        nd_save(f"{path}-{epoch:04d}.params", arg_dict)
        with open(f"{path}-symbol.json", "w") as f:
            import json

            f.write(json.dumps({"format": "stablehlo", "note": "see .mlir"}))
        return f"{path}-symbol.json", f"{path}-{epoch:04d}.params"

    def optimize_for(self, x, *args, backend=None, **kwargs):
        """Parity shim for the subgraph-backend API ([U:src/operator/subgraph/]):
        XLA performs fusion/placement; this simply hybridizes and warms the
        cache."""
        self.hybridize()
        self(x, *args)


class DeferredInit(Exception):
    pass


class SymbolBlock(HybridBlock):
    """Construct a Block from a Symbol graph (parity: ``gluon.SymbolBlock``).
    Implemented once the symbol module lands; see symbol/."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        self._outputs = outputs
        self._inputs = inputs

    def hybrid_forward(self, F, *args, **params):
        from ..symbol import _eval_symbol

        return _eval_symbol(self._outputs, self._inputs, args, params)
