"""Gluon: the imperative/hybrid front end
(parity: [U:python/mxnet/gluon/])."""
from . import parameter
from .parameter import Parameter, Constant, ParameterDict
from . import block
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss
from .trainer import Trainer
from . import utils
from . import data
from . import rnn
from . import model_zoo
from . import contrib

__all__ = [
    "Parameter",
    "Constant",
    "ParameterDict",
    "Block",
    "HybridBlock",
    "SymbolBlock",
    "Trainer",
    "nn",
    "loss",
    "utils",
    "data",
    "rnn",
    "model_zoo",
    "contrib",
]
