"""Loss blocks (parity: [U:python/mxnet/gluon/loss.py]).

Same class zoo and semantics: losses are HybridBlocks returning per-sample
loss vectors (batch axis preserved) with ``weight`` / ``sample_weight``
scaling.  CTCLoss is implemented with a lax.scan alpha recursion instead of
the reference's warp-ctc binding.
"""
from __future__ import annotations

import numpy as _np

from .block import HybridBlock

__all__ = [
    "Loss",
    "L2Loss",
    "L1Loss",
    "SigmoidBinaryCrossEntropyLoss",
    "SigmoidBCELoss",
    "SoftmaxCrossEntropyLoss",
    "SoftmaxCELoss",
    "KLDivLoss",
    "HuberLoss",
    "HingeLoss",
    "SquaredHingeLoss",
    "LogisticLoss",
    "TripletLoss",
    "PoissonNLLLoss",
    "CosineEmbeddingLoss",
    "CTCLoss",
    "SDMLLoss",
]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{self.__class__.__name__}(batch_axis={self._batch_axis}, w={self._weight})"

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = F.relu(pred) - pred * label + F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * (
                    F.Activation(-F.abs(pred), act_type="softrelu") + F.relu(-pred)
                )
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label + F.log(1.0 - pred + eps) * (1.0 - label))
            else:
                loss = -(
                    F.broadcast_mul(F.log(pred + eps) * label, pos_weight)
                    + F.log(1.0 - pred + eps) * (1.0 - label)
                )
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Parity: ``gluon.loss.SoftmaxCrossEntropyLoss`` (sparse or dense
    labels)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(
            loss > self._rho, loss - 0.5 * self._rho, (0.5 / self._rho) * F.square(loss)
        )
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed", **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred), axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (parity: ``gluon.loss.SDMLLoss``,
    1.6+): paired batches (x1[i] ~ x2[i]) — cross-entropy between the
    row-softmax of negative pairwise L2 distances and a label-smoothed
    identity, so each x1[i] should be closest to its own x2[i] among the
    batch."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._smoothing = smoothing_parameter

    def hybrid_forward(self, F, x1, x2):
        n = x1.shape[0]
        if n < 2:
            raise ValueError("SDMLLoss needs batch size >= 2 (in-batch "
                             "negatives)")
        # pairwise squared L2: [N, N]
        a = F.expand_dims(x1, axis=1)   # [N, 1, D]
        b = F.expand_dims(x2, axis=0)   # [1, N, D]
        dist = F.sum(F.square(F.broadcast_sub(a, b)), axis=2)
        logp = F.log_softmax(-dist, axis=1)
        eye = F.one_hot(F.arange(0, n), depth=n)
        labels = (eye * (1.0 - self._smoothing)
                  + (1.0 - eye) * (self._smoothing / (n - 1)))
        loss = -F.sum(labels * logp, axis=1)
        return _apply_weighting(F, loss, self._weight, None)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0, compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None, epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            stirling = target * F.log(target + epsilon) - target + 0.5 * F.log(2 * target * _np.pi + epsilon)
            stirling = F.where(target <= 1.0, F.zeros_like(target), stirling)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = input1.reshape((input1.shape[0], -1))
        input2 = input2.reshape((input2.shape[0], -1))
        cos = F.sum(input1 * input2, axis=1) / (
            F.norm(input1, axis=1) * F.norm(input2, axis=1) + 1e-12
        )
        label = label.reshape((-1,))
        loss = F.where(label == 1, 1.0 - cos, F.relu(cos - self._margin))
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification (parity:
    [U:src/operator/nn/ctc_loss.cc] / ``gluon.loss.CTCLoss``).

    TPU-native: the alpha recursion is a ``lax.scan`` over time with the
    standard log-sum-exp trellis — static shapes, no warp-ctc.
    Layouts: 'NTC' (default) or 'TNC'; blank label first or last.
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None, label_lengths=None, sample_weight=None):
        import jax.numpy as jnp
        from jax import lax
        from ..ndarray.ndarray import invoke  # noqa  (doc pointer)

        def ctc(pred_r, label_r, pl, ll):
            if self._layout == "NTC":
                pred_t = jnp.transpose(pred_r, (1, 0, 2))  # -> TNC
            else:
                pred_t = pred_r
            T, B, C = pred_t.shape
            logp = jnp.log(jnp.maximum(jnp.exp(pred_t - pred_t.max(-1, keepdims=True)) /
                                        jnp.sum(jnp.exp(pred_t - pred_t.max(-1, keepdims=True)), -1, keepdims=True), 1e-30))
            L = label_r.shape[1]
            S = 2 * L + 1
            blank = 0
            lab = label_r.astype(jnp.int32)
            # extended label sequence with blanks: [b, l1, b, l2, ..., b]
            ext = jnp.full((B, S), blank, dtype=jnp.int32)
            ext = ext.at[:, 1::2].set(lab)
            neg_inf = -1e30
            alpha0 = jnp.full((B, S), neg_inf)
            alpha0 = alpha0.at[:, 0].set(logp[0, jnp.arange(B), blank])
            alpha0 = alpha0.at[:, 1].set(logp[0, jnp.arange(B), ext[:, 1]])

            same = jnp.concatenate(
                [jnp.zeros((B, 2), dtype=bool), ext[:, 2:] == ext[:, :-2]], axis=1
            )

            def step(alpha, logp_t):
                a = alpha
                a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
                a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
                a2 = jnp.where(same, neg_inf, a2)
                m = jnp.maximum(jnp.maximum(a, a1), a2)
                m_safe = jnp.where(m == neg_inf, 0.0, m)
                summed = (
                    jnp.exp(a - m_safe) + jnp.exp(a1 - m_safe) + jnp.exp(a2 - m_safe)
                )
                new_alpha = jnp.where(m == neg_inf, neg_inf, m_safe + jnp.log(summed))
                emit = jnp.take_along_axis(logp_t, ext, axis=1)
                return new_alpha + emit, new_alpha + emit

            _, alphas_rest = lax.scan(step, alpha0, logp[1:])
            alphas = jnp.concatenate([alpha0[None], alphas_rest], axis=0)  # (T, B, S)
            # per-sample final timestep honors pred_lengths
            if pl is None:
                t_last = jnp.full((B,), T - 1, dtype=jnp.int32)
            else:
                t_last = (pl.astype(jnp.int32) - 1)
            if ll is None:
                lastS = jnp.full((B,), S - 1)
            else:
                lastS = (2 * ll).astype(jnp.int32)
            bidx = jnp.arange(B)
            alpha_T = alphas[t_last, bidx]  # (B, S)
            final = jnp.logaddexp(
                alpha_T[bidx, lastS], alpha_T[bidx, jnp.maximum(lastS - 1, 0)]
            )
            return -final

        from ..ndarray.ndarray import NDArray

        args = [pred, label]
        if pred_lengths is not None:
            args.append(pred_lengths)
        if label_lengths is not None:
            args.append(label_lengths)

        def fn(p, l, *rest):
            pl = rest[0] if pred_lengths is not None else None
            ll = rest[-1] if label_lengths is not None else None
            return ctc(p, l, pl, ll)

        loss = invoke(fn, tuple(args), {}, name="CTCLoss")
        return _apply_weighting(F, loss, self._weight, sample_weight)
