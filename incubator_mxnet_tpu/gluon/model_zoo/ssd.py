"""SSD single-shot detector (parity: the reference's SSD example family,
[U:example/ssd/symbol/symbol_builder.py] — BASELINE.md config 5).

TPU-first shape discipline: every stage is fixed-shape — anchors come from
``contrib_MultiBoxPrior`` on statically-shaped feature maps, the head
outputs concatenate to one [B, N, C+1] / [B, N·4] pair, and training
targets/NMS are the mask-based ops in :mod:`...ops.detection`.  The whole
forward (and the train step via SPMDTrainer) jits.

``SSDForward`` returns (anchors [1, N, 4], cls_preds [B, N, C+1],
box_preds [B, N·4]) — the triple MultiBoxTarget/MultiBoxDetection consume.
"""
from __future__ import annotations

from ..block import HybridBlock
from .. import nn

__all__ = ["SSD", "ssd_512_resnet18", "ssd_512_vgg16_atrous", "SSDAnchorScales"]

# Per-scale (sizes, ratios) — the classic SSD512 schedule, normalized.
SSDAnchorScales = [
    ((0.07, 0.1025), (1.0, 2.0, 0.5)),
    ((0.15, 0.2121), (1.0, 2.0, 0.5, 3.0, 1.0 / 3)),
    ((0.3, 0.3674), (1.0, 2.0, 0.5, 3.0, 1.0 / 3)),
    ((0.45, 0.5196), (1.0, 2.0, 0.5, 3.0, 1.0 / 3)),
    ((0.6, 0.6708), (1.0, 2.0, 0.5)),
    ((0.75, 0.8216), (1.0, 2.0, 0.5)),
]


def _n_anchors(sizes, ratios):
    return len(sizes) + len(ratios) - 1


class _DownsampleBlock(HybridBlock):
    """conv1x1 → conv3x3/s2 feature-pyramid step (the example's
    ``_add_extras``)."""

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 2, kernel_size=1))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=3, strides=2, padding=1))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))

    def hybrid_forward(self, F, x):
        return self.body(x)


class SSD(HybridBlock):
    """Generic SSD over a feature extractor.

    Parameters
    ----------
    features : HybridBlock
        Backbone mapping images → the first (highest-resolution) feature
        map used for prediction.
    num_classes : int
        Foreground classes (background is implicit class 0 of the head).
    scales : list of (sizes, ratios)
        Anchor schedule per pyramid level; levels beyond the backbone map
        are built with stride-2 downsample blocks.
    """

    def __init__(self, features, num_classes, scales=SSDAnchorScales,
                 channels=256, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self._scales = list(scales)
        with self.name_scope():
            self.features = features
            self.downsamplers = nn.HybridSequential(prefix="down_")
            for _ in range(len(self._scales) - 1):
                self.downsamplers.add(_DownsampleBlock(channels))
            self.cls_heads = nn.HybridSequential(prefix="cls_")
            self.box_heads = nn.HybridSequential(prefix="box_")
            for sizes, ratios in self._scales:
                a = _n_anchors(sizes, ratios)
                self.cls_heads.add(nn.Conv2D(a * (num_classes + 1),
                                             kernel_size=3, padding=1))
                self.box_heads.add(nn.Conv2D(a * 4, kernel_size=3, padding=1))

    def hybrid_forward(self, F, x):
        feats = [self.features(x)]
        for down in self.downsamplers._children.values():
            feats.append(down(feats[-1]))

        anchors, cls_preds, box_preds = [], [], []
        for feat, (sizes, ratios), cls_head, box_head in zip(
                feats, self._scales,
                self.cls_heads._children.values(),
                self.box_heads._children.values()):
            anchors.append(F.contrib.MultiBoxPrior(feat, sizes=sizes,
                                                   ratios=ratios, clip=True))
            # [B, A*(C+1), H, W] → [B, H·W·A, C+1]
            c = cls_head(feat).transpose((0, 2, 3, 1))
            cls_preds.append(c.reshape((0, -1, self.num_classes + 1)))
            b = box_head(feat).transpose((0, 2, 3, 1))
            box_preds.append(b.reshape((0, -1)))
        return (F.concat(*anchors, dim=1),
                F.concat(*cls_preds, dim=1),
                F.concat(*box_preds, dim=1))


def ssd_512_resnet18(num_classes=20, **kwargs):
    """SSD-512 with a ResNet-18 feature backbone (stages through conv4)."""
    from .vision.resnet import resnet18_v1

    base = resnet18_v1(classes=1)  # classifier head unused
    features = nn.HybridSequential(prefix="backbone_")
    # reference keeps everything up to (not incl.) the global pool / output
    for layer in list(base.features._children.values())[:-2]:
        features.add(layer)
    return SSD(features, num_classes, **kwargs)


def ssd_512_vgg16_atrous(num_classes=20, **kwargs):
    """SSD-512 with the reference's VGG-16 (atrous) backbone
    ([U:example/ssd/symbol/vgg16_reduced.py] / GluonCV
    ssd_512_vgg16_atrous): conv1_1..conv5_3 with the third maxpool
    ceil-rounded, pool5 3×3/1, and fc6 as a dilated 1024-channel conv +
    fc7 1×1 — the benchmark-parity backbone (the resnet18 variant is the
    lighter alternative)."""
    from ..nn import Conv2D, MaxPool2D

    layers, filters = [2, 2, 3, 3, 3], [64, 128, 256, 512, 512]
    features = nn.HybridSequential(prefix="vggbackbone_")
    for i, num in enumerate(layers):
        for _ in range(num):
            features.add(Conv2D(filters[i], kernel_size=3, padding=1,
                                activation="relu"))
        if i < len(layers) - 1:  # pool1..pool4 stride 2; pool5 below
            # pool3 ceil-rounds in the reference (75->38 at 300-input)
            features.add(MaxPool2D(pool_size=2, strides=2, ceil_mode=(i == 2)))
    # pool5: 3x3 stride 1 (keeps conv5 resolution for the atrous fc6)
    features.add(MaxPool2D(pool_size=3, strides=1, padding=1))
    # fc6: dilated conv (atrous trick), fc7: 1x1 conv
    features.add(Conv2D(1024, kernel_size=3, padding=6, dilation=6,
                        activation="relu"))
    features.add(Conv2D(1024, kernel_size=1, activation="relu"))
    return SSD(features, num_classes, **kwargs)
