"""BERT model family (baseline workload 3, BASELINE.md).

The reference ships BERT via GluonNLP (out-of-repo); in-repo here because
BERT-base pretraining is a headline benchmark.  Architecture follows the
original BERT conventions (post-LN encoder, learned positions, GELU).

TP/SP sharding: :func:`bert_sharding_rules` gives the Megatron-style
placement — QKV/FFN-in column-parallel, out-proj/FFN-out row-parallel,
embeddings vocab-sharded — consumed by ``parallel.SPMDTrainer``.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ..block import HybridBlock
from ..nn import Dense, Dropout, Embedding, LayerNorm
from ..nn.transformer import PositionalEmbedding, TransformerEncoder

__all__ = [
    "BERTModel",
    "BERTForPretrain",
    "bert_base",
    "bert_large",
    "bert_sharding_rules",
]


class BERTModel(HybridBlock):
    """Token+segment+position embeddings → encoder stack → (sequence
    output, pooled output)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512, type_vocab=2,
                 dropout=0.1, dtype="float32", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._vocab_size = vocab_size
        with self.name_scope():
            self.word_embed = Embedding(vocab_size, units, dtype=dtype, prefix="word_embed_")
            self.token_type_embed = Embedding(type_vocab, units, dtype=dtype, prefix="type_embed_")
            self.position_embed = PositionalEmbedding(max_length, units, dtype=dtype, prefix="pos_embed_")
            self.embed_ln = LayerNorm(prefix="embed_ln_")
            self.encoder = TransformerEncoder(
                num_layers, units, hidden_size, num_heads, dropout=dropout,
                activation="gelu", dtype=dtype, prefix="enc_",
            )
            self.pooler = Dense(units, activation="tanh", flatten=False, dtype=dtype, prefix="pooler_")
        self._embed_dropout = Dropout(dropout) if dropout else None
        if self._embed_dropout is not None:
            self.register_child(self._embed_dropout, "embed_dropout")

    def forward(self, token_ids, token_types=None):
        from ... import ndarray as F

        x = self.word_embed(token_ids)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = self.position_embed(x)
        x = self.embed_ln(x)
        if self._embed_dropout is not None:
            x = self._embed_dropout(x)
        seq = self.encoder(x)  # [B, S, D]
        pooled = self.pooler(F.slice_axis(seq, axis=1, begin=0, end=1).reshape((0, -1)))
        return seq, pooled


class BERTForPretrain(HybridBlock):
    """MLM head (tied-style decoder over vocab) + NSP head."""

    def __init__(self, bert: BERTModel, vocab_size=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.bert = bert
        units = bert._units
        if vocab_size is None:
            vocab_size = bert._vocab_size  # MLM decoder must match the embedding vocab
        with self.name_scope():
            self.mlm_transform = Dense(units, activation=None, flatten=False, prefix="mlm_dense_")
            self.mlm_ln = LayerNorm(prefix="mlm_ln_")
            self.mlm_decoder = Dense(vocab_size, flatten=False, prefix="mlm_decoder_")
            self.nsp = Dense(2, flatten=False, prefix="nsp_")

    def forward(self, token_ids, token_types=None, masked_positions=None):
        from ... import ndarray as F

        seq, pooled = self.bert(token_ids, token_types)
        h = seq
        if masked_positions is not None:
            # decode only the masked positions (GluonNLP masked_positions
            # semantics): the [*, V] vocab projection — the single biggest
            # matmul — runs on ~15% of tokens instead of all of them
            h = F.gather_positions(h, masked_positions)  # [B, P, D]
        h = self.mlm_transform(h)
        h = F.LeakyReLU(h, act_type="gelu")
        h = self.mlm_ln(h)
        mlm_logits = self.mlm_decoder(h)       # [B, P(or S), V]
        nsp_logits = self.nsp(pooled)          # [B, 2]
        return mlm_logits, nsp_logits


def bert_base(vocab_size=30522, max_length=512, dropout=0.1, dtype="float32", **kwargs):
    return BERTModel(vocab_size, units=768, hidden_size=3072, num_layers=12,
                     num_heads=12, max_length=max_length, dropout=dropout,
                     dtype=dtype, **kwargs)


def bert_large(vocab_size=30522, max_length=512, dropout=0.1, dtype="float32", **kwargs):
    return BERTModel(vocab_size, units=1024, hidden_size=4096, num_layers=24,
                     num_heads=16, max_length=max_length, dropout=dropout,
                     dtype=dtype, **kwargs)



def bert_sharding_rules(fsdp=False):
    """Megatron-style TP placement for the layer names above.

    Dense weights are [out, in] (x·Wᵀ), so column-parallel = shard axis 0
    over 'tp', row-parallel = shard axis 1.  XLA then keeps the attention/
    FFN block's activations tp-sharded between the two projections and
    inserts one reduce-scatter/all-gather pair per block.
    """
    from ...parallel.sharding import ShardingRules

    default = P("fsdp") if fsdp else P()
    return ShardingRules(
        [
            (r"qkv_weight$", P("tp", None)),
            (r"(q|kv)_weight$", P("tp", None)),
            (r"qkv_bias$", P("tp")),
            (r"(q|kv)_bias$", P("tp")),
            (r"ffn1_weight$", P("tp", None)),
            (r"ffn1_bias$", P("tp")),
            (r"out_weight$", P(None, "tp")),
            (r"ffn2_weight$", P(None, "tp")),
            (r"(word|pos|type)_embed.*weight$", P("tp", None)),
            (r"mlm_decoder_weight$", P("tp", None)),
        ],
        default=default,
    )
