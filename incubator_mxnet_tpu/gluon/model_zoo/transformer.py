"""Transformer encoder-decoder for seq2seq (WMT en-de, BASELINE.md
config 4; parity role: the reference's bucketing seq2seq example family,
[U:example/rnn/bucketing/], with the transformer itself living out-of-repo
in GluonNLP).

TPU-first inference design: there is no dynamic-shape KV cache — decode
steps re-run the causal decoder on the prefix padded to a **bucket**
length (powers of two), so the jit cache holds one program per bucket
(the BucketingModule discipline applied to inference), every shape is
static, and causal masking makes the padding invisible to the logits at
the read position.  Beam bookkeeping runs on the host in numpy; the
per-step network call is a single jitted program.
"""
from __future__ import annotations

import math

import numpy as _np

from ..block import HybridBlock
from ..nn.basic_layers import Dense, Dropout, Embedding
from ..nn.transformer import (TransformerEncoder, TransformerDecoder,
                              SinusoidalPositionalEncoding)

__all__ = ["Transformer", "transformer_base", "transformer_big",
           "transformer_sharding_rules", "beam_search", "greedy_search"]


class Transformer(HybridBlock):
    """Encoder-decoder transformer with tied source/target/output
    embeddings (the WMT convention)."""

    def __init__(self, vocab_size, units=512, hidden_size=2048, num_heads=8,
                 num_encoder_layers=6, num_decoder_layers=6, dropout=0.1,
                 max_length=1024, tie_weights=True, dtype="float32",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._vocab = vocab_size
        self._tie = tie_weights
        with self.name_scope():
            self.embed = Embedding(vocab_size, units, dtype=dtype, prefix="embed_")
            self.pos_enc = SinusoidalPositionalEncoding(units, max_length)
            self.encoder = TransformerEncoder(
                num_encoder_layers, units, hidden_size, num_heads, dropout,
                pre_norm=True, activation="relu", dtype=dtype, prefix="enc_")
            self.decoder = TransformerDecoder(
                num_decoder_layers, units, hidden_size, num_heads, dropout,
                pre_norm=True, activation="relu", dtype=dtype, prefix="dec_")
            if not tie_weights:
                self.proj = Dense(vocab_size, use_bias=False, flatten=False,
                                  dtype=dtype, prefix="proj_")
        self._drop = Dropout(dropout) if dropout else None
        if self._drop is not None:
            self.register_child(self._drop, "dropout")

    # -- halves (used by the search loops) ------------------------------
    def encode(self, src):
        x = self.embed(src) * math.sqrt(self._units)
        x = self.pos_enc(x)
        if self._drop is not None:
            x = self._drop(x)
        return self.encoder(x)

    def decode(self, tgt, memory):
        """tgt [B, T] int tokens → logits [B, T, V] (causal)."""
        x = self.embed(tgt) * math.sqrt(self._units)
        x = self.pos_enc(x)
        if self._drop is not None:
            x = self._drop(x)
        h = self.decoder(x, memory)
        if self._tie:
            from ... import ndarray as F
            # Parameter.data() returns the traced stand-in inside a jit
            # trace, so weight tying composes into the compiled graph
            return F.dot(h, self.embed.weight.data(), transpose_b=True)
        return self.proj(h)

    def forward(self, src, tgt):
        return self.decode(tgt, self.encode(src))


def transformer_base(vocab_size, max_length=1024, dropout=0.1, **kwargs):
    return Transformer(vocab_size, units=512, hidden_size=2048, num_heads=8,
                       max_length=max_length, dropout=dropout, **kwargs)


def transformer_big(vocab_size, max_length=1024, dropout=0.3, **kwargs):
    """The WMT'14 "big" configuration (BASELINE.md config 4)."""
    return Transformer(vocab_size, units=1024, hidden_size=4096, num_heads=16,
                       max_length=max_length, dropout=dropout, **kwargs)


def transformer_sharding_rules(fsdp=False):
    """Megatron-style TP placement for SPMDTrainer (same conventions as
    ``bert_sharding_rules``): QKV/FFN-in column-parallel, out-proj/FFN-out
    row-parallel, embedding vocab-sharded."""
    from ...parallel.sharding import ShardingRules

    dp = "fsdp" if fsdp else None
    return ShardingRules(rules=[
        (r".*qkv_weight$", ("tp", dp)),
        (r".*kv_weight$", ("tp", dp)),
        (r".*q_weight$", ("tp", dp)),
        (r".*ffn1_weight$", ("tp", dp)),
        (r".*(out|ffn2)_weight$", (dp, "tp")),
        (r".*embed_weight$", ("tp", dp)),
        (r".*_bias$", (None,)),
    ])


# ---------------------------------------------------------------------------
# Search (greedy + beam) — bucketed KV-cache decode
# ---------------------------------------------------------------------------
#
# Decode is O(T) per step: each decoder layer keeps an append-only K/V
# cache padded to a bucket length (static shapes — the BucketingModule
# discipline), the new position is written with dynamic_update_slice, and
# attention runs one query row against the cache.  One jitted program per
# bucket; cache buffers are donated so steady-state HBM holds one copy.
# The pre-round-3 re-run-the-prefix path (O(T²)/step) remains as
# ``use_cache=False`` and for post-norm decoders.


def _bucket(n, max_len):
    b = 8
    while b < n:
        b *= 2
    return min(b, max_len)


class _KVCacheDecoder:
    """Incremental decoder over bucketed K/V caches.

    Exceeds-reference area (the reference has no fused attention or
    incremental decode at all); the TPU discipline is constant shapes:
    caches live at bucket lengths, growing by re-padding + retracing at
    powers of two."""

    def __init__(self, model, memory, batch, max_length, dtype=None):
        import jax.numpy as jnp

        from ... import autograd  # noqa: F401  (scope import parity)

        cells = model.decoder._layers
        if not all(c._pre_norm for c in cells):
            raise NotImplementedError("KV-cache decode requires pre-norm cells")
        self._model = model
        self._cells = cells
        self._units = model._units
        self._heads = cells[0].self_attention._num_heads
        self._dh = self._units // self._heads
        self._max_length = max_length
        self._params = sorted(model.collect_params().values(), key=lambda p: p.name)
        if any(p._data is None for p in self._params):
            # deferred shapes: one [B,1] decode materializes every weight
            from ... import ndarray as _ndm

            model.decode(_ndm.zeros((batch, 1), dtype="int32"),
                         memory if hasattr(memory, "_data") else _nd_wrap(memory))
        self._param_arrays = [p._data._data for p in self._params]
        self._mem = memory._data if hasattr(memory, "_data") else memory
        self._dtype = dtype or self._mem.dtype
        self._bucket = _bucket(1, max_length)
        L, B, H, dh = len(cells), batch, self._heads, self._dh
        self._self_k = jnp.zeros((L, B, self._bucket, H, dh), self._dtype)
        self._self_v = jnp.zeros_like(self._self_k)
        # cross-attention K/V depend only on the encoder memory: computed
        # once per layer through the cells' own kv projections
        mem_kv = []
        for cell in cells:
            kv = cell.cross_attention.kv_proj(
                memory if hasattr(memory, "_data") else _nd_wrap(memory))
            arr = kv._data
            S = arr.shape[1]
            mem_kv.append(arr.reshape(B, S, 2, H, dh))
        self._mem_k = jnp.stack([a[:, :, 0] for a in mem_kv])  # [L, B, S, H, dh]
        self._mem_v = jnp.stack([a[:, :, 1] for a in mem_kv])
        self._step_cache = {}

    # -- cache maintenance ----------------------------------------------
    def _grow(self, needed):
        import jax.numpy as jnp

        while self._bucket < needed:
            new_b = min(self._bucket * 2, self._max_length)
            pad = new_b - self._bucket
            self._self_k = jnp.pad(self._self_k, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
            self._self_v = jnp.pad(self._self_v, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
            self._bucket = new_b

    def reorder(self, flat_indices):
        """Beam bookkeeping: permute the batch axis of the caches."""
        import jax.numpy as jnp

        idx = jnp.asarray(flat_indices)
        self._self_k = jnp.take(self._self_k, idx, axis=1)
        self._self_v = jnp.take(self._self_v, idx, axis=1)

    # -- the jitted step -------------------------------------------------
    def _make_step(self, bucket):
        import functools

        import jax
        import jax.numpy as jnp
        from jax import lax

        from ...gluon.block import traced_params
        from ...ndarray.ndarray import NDArray

        model = self._model
        cells = self._cells
        params = self._params
        H, dh, units = self._heads, self._dh, self._units
        scale = 1.0 / math.sqrt(dh)
        pos_table = model.pos_enc._table  # numpy [max_len, units]

        def attend(q, k, v, mask):
            # q [B,1,H,dh]; k/v [B,Tb,H,dh]; mask [Tb] bool (valid positions)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(mask[None, None, None, :], s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                              preferred_element_type=jnp.float32).astype(v.dtype)

        # Parameters are FROZEN during decode, so they are baked into the
        # compiled program as captured constants instead of being passed as
        # ~hundreds of jit arguments — per-leaf argument processing cost
        # ~0.5 ms/arg on slow hosts (measured 340 ms/step of pure dispatch
        # for a 2.6 ms compute).  The price is one baked copy per bucket
        # program; decode uses a handful of buckets.
        param_arrays = list(self._param_arrays)

        def pure(tok, t, self_k, self_v, mem_k, mem_v):
            with traced_params(params, param_arrays):  # eval mode
                B = tok.shape[0]
                x = model.embed(NDArray(tok))._data * math.sqrt(units)
                x = x + lax.dynamic_slice_in_dim(
                    jnp.asarray(pos_table), t, 1, 0).astype(x.dtype)
                valid = jnp.arange(bucket) <= t
                new_k, new_v = [], []
                for l, cell in enumerate(cells):
                    h = cell.ln_self(NDArray(x))._data
                    qkv = cell.self_attention.qkv(NDArray(h))._data
                    qkv = qkv.reshape(B, 1, 3, H, dh)
                    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                    ck = lax.dynamic_update_slice(
                        self_k[l], k.astype(self_k.dtype), (0, t, 0, 0))
                    cv = lax.dynamic_update_slice(
                        self_v[l], v.astype(self_v.dtype), (0, t, 0, 0))
                    new_k.append(ck)
                    new_v.append(cv)
                    out = attend(q, ck, cv, valid).reshape(B, 1, units)
                    x = x + cell.self_attention.out_proj(NDArray(out))._data
                    h = cell.ln_cross(NDArray(x))._data
                    q2 = cell.cross_attention.q_proj(NDArray(h))._data
                    q2 = q2.reshape(B, 1, H, dh)
                    S = mem_k.shape[2]
                    out2 = attend(q2, mem_k[l], mem_v[l],
                                  jnp.ones((S,), bool)).reshape(B, 1, units)
                    x = x + cell.cross_attention.out_proj(NDArray(out2))._data
                    h = cell.ln_ffn(NDArray(x))._data
                    x = x + cell.ffn(NDArray(h))._data
                if model._tie:
                    logits = jnp.einsum(
                        "bqd,vd->bqv", x,
                        model.embed.weight.data()._data.astype(x.dtype))
                else:
                    logits = model.proj(NDArray(x))._data
            return logits[:, 0], jnp.stack(new_k), jnp.stack(new_v)

        return jax.jit(pure, donate_argnums=(2, 3))

    _CACHE_LIMIT = 8  # programs; each bakes a full parameter copy

    def _step_key(self, bucket):
        # params baked as constants → the compiled program is only valid
        # for these exact arrays; id() changes whenever training updates them
        return (bucket, self._self_k.shape[1], self._mem_k.shape,
                str(self._dtype), tuple(id(a) for a in self._param_arrays))

    def step(self, tok_np, t):
        """tok_np: [B] int32 tokens at position t → logits [B, V] (numpy)."""
        import jax.numpy as jnp
        import numpy as np

        self._grow(t + 1)
        # compiled steps cached on the MODEL (bounded LRU: every program
        # bakes a full parameter copy, and training invalidates the key,
        # so an unbounded cache would pin stale parameter sets forever)
        from collections import OrderedDict

        model_cache = getattr(self._model, "_decode_step_cache", None)
        if model_cache is None:
            model_cache = self._model._decode_step_cache = OrderedDict()
        key = self._step_key(self._bucket)
        fn = model_cache.get(key)
        if fn is None:
            fn = self._make_step(self._bucket)
            model_cache[key] = fn
            while len(model_cache) > self._CACHE_LIMIT:
                model_cache.popitem(last=False)
        else:
            model_cache.move_to_end(key)
        logits, self._self_k, self._self_v = fn(
            jnp.asarray(tok_np.reshape(-1, 1)),
            jnp.int32(t), self._self_k, self._self_v,
            self._mem_k, self._mem_v)
        return np.asarray(logits)


def _nd_wrap(arr):
    from ...ndarray.ndarray import NDArray

    return NDArray(arr)


def _step_logits(model, tgt_padded, memory, t):
    """Logits for position t given prefix tgt[:, :t+1], padded to a bucket
    length.  Causality guarantees positions > t cannot leak in."""
    from ... import ndarray as F
    logits = model.decode(tgt_padded, memory)  # [B, Tb, V]
    return logits[:, t]


def greedy_search(model, src, bos, eos, max_length=64, use_cache=True):
    """Greedy decode → (tokens [B, max_length], lengths [B]).

    ``use_cache=True`` (default) decodes O(T) per step via the bucketed
    KV cache; ``False`` re-runs the causal prefix (the round-2 path, kept
    as the oracle and for post-norm decoders)."""
    import numpy as np

    from ... import ndarray as nd

    memory = model.encode(src)
    B = src.shape[0]
    cache = None
    if use_cache:
        try:
            cache = _KVCacheDecoder(model, memory, B, max_length)
        except NotImplementedError:
            cache = None
    tokens = np.full((B, max_length), eos, np.int32)
    tokens[:, 0] = bos
    lengths = np.full(B, max_length, np.int32)
    done = np.zeros(B, bool)
    for t in range(max_length - 1):
        if cache is not None:
            logits_np = cache.step(tokens[:, t], t)
        else:
            tb = _bucket(t + 1, max_length)
            logits_np = _step_logits(model, nd.array(tokens[:, :tb], dtype="int32"),
                                     memory, t).asnumpy()
        nxt = logits_np.argmax(axis=-1).astype(np.int32)
        nxt = np.where(done, eos, nxt)
        tokens[:, t + 1] = nxt
        newly = (~done) & (nxt == eos)
        lengths[newly] = t + 2
        done |= nxt == eos
        if done.all():
            break
    return tokens, lengths


def beam_search(model, src, bos, eos, beam_size=4, max_length=64, alpha=0.6,
                use_cache=True):
    """Length-penalized beam search (GNMT penalty ((5+len)/6)^alpha).

    Returns (tokens [B, K, max_length], scores [B, K]) sorted best-first.
    The per-step network call is one jitted decode over the [B·K] beam
    batch (O(T) per step through the KV cache; beam reorders permute the
    cache batch axis); beam bookkeeping is host-side numpy (cheap: K·V
    topk per step).
    """
    import numpy as np

    from ... import ndarray as nd

    memory = model.encode(src)          # [B, S, D]
    B, K = src.shape[0], beam_size
    mem = nd.array(np.repeat(memory.asnumpy(), K, axis=0))  # [B·K, S, D]
    cache = None
    if use_cache:
        try:
            cache = _KVCacheDecoder(model, mem, B * K, max_length)
        except NotImplementedError:
            cache = None

    tokens = np.full((B, K, max_length), eos, np.int32)
    tokens[:, :, 0] = bos
    scores = np.full((B, K), -np.inf, np.float64)
    scores[:, 0] = 0.0                  # only beam 0 live at t=0
    done = np.zeros((B, K), bool)

    for t in range(max_length - 1):
        if cache is not None:
            logits_np = cache.step(tokens[:, :, t].reshape(B * K), t)
        else:
            tb = _bucket(t + 1, max_length)
            flat = tokens[:, :, :tb].reshape(B * K, tb)
            logits_np = _step_logits(model, nd.array(flat, dtype="int32"),
                                     mem, t).asnumpy()
        logp = _log_softmax_np(logits_np.astype(np.float64))  # [B·K, V]
        V = logp.shape[-1]
        logp = logp.reshape(B, K, V)
        # finished beams only extend with eos at zero cost
        logp = np.where(done[:, :, None],
                        np.where(np.arange(V)[None, None] == eos, 0.0, -np.inf),
                        logp)
        cand = scores[:, :, None] + logp            # [B, K, V]
        flat_cand = cand.reshape(B, K * V)
        top = np.argsort(-flat_cand, axis=1)[:, :K]  # [B, K]
        new_scores = np.take_along_axis(flat_cand, top, axis=1)
        src_beam = top // V
        nxt_tok = (top % V).astype(np.int32)

        tokens = np.take_along_axis(
            tokens, src_beam[:, :, None], axis=1)
        tokens[:, :, t + 1] = nxt_tok
        done = np.take_along_axis(done, src_beam, axis=1) | (nxt_tok == eos)
        scores = new_scores
        if cache is not None:
            # permute the cache batch to follow the surviving beams
            flat_src = (np.arange(B)[:, None] * K + src_beam).reshape(-1)
            cache.reorder(flat_src)
        if done.all():
            break

    lengths = np.argmax(tokens == eos, axis=-1) + 1
    lengths[~done] = max_length
    lp = ((5.0 + lengths) / 6.0) ** alpha
    final = scores / lp
    order = np.argsort(-final, axis=1)
    return (np.take_along_axis(tokens, order[:, :, None], axis=1),
            np.take_along_axis(final, order, axis=1))


def _log_softmax_np(x):
    m = x.max(axis=-1, keepdims=True)
    e = _np.exp(x - m)
    return (x - m) - _np.log(e.sum(axis=-1, keepdims=True))
