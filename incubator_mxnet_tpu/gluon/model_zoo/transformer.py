"""Transformer encoder-decoder for seq2seq (WMT en-de, BASELINE.md
config 4; parity role: the reference's bucketing seq2seq example family,
[U:example/rnn/bucketing/], with the transformer itself living out-of-repo
in GluonNLP).

TPU-first inference design: there is no dynamic-shape KV cache — decode
steps re-run the causal decoder on the prefix padded to a **bucket**
length (powers of two), so the jit cache holds one program per bucket
(the BucketingModule discipline applied to inference), every shape is
static, and causal masking makes the padding invisible to the logits at
the read position.  Beam bookkeeping runs on the host in numpy; the
per-step network call is a single jitted program.
"""
from __future__ import annotations

import math

import numpy as _np

from ..block import HybridBlock
from ..nn.basic_layers import Dense, Dropout, Embedding
from ..nn.transformer import (TransformerEncoder, TransformerDecoder,
                              SinusoidalPositionalEncoding)

__all__ = ["Transformer", "transformer_base", "transformer_big",
           "transformer_sharding_rules", "beam_search", "greedy_search"]


class Transformer(HybridBlock):
    """Encoder-decoder transformer with tied source/target/output
    embeddings (the WMT convention)."""

    def __init__(self, vocab_size, units=512, hidden_size=2048, num_heads=8,
                 num_encoder_layers=6, num_decoder_layers=6, dropout=0.1,
                 max_length=1024, tie_weights=True, dtype="float32",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._vocab = vocab_size
        self._tie = tie_weights
        with self.name_scope():
            self.embed = Embedding(vocab_size, units, dtype=dtype, prefix="embed_")
            self.pos_enc = SinusoidalPositionalEncoding(units, max_length)
            self.encoder = TransformerEncoder(
                num_encoder_layers, units, hidden_size, num_heads, dropout,
                pre_norm=True, activation="relu", dtype=dtype, prefix="enc_")
            self.decoder = TransformerDecoder(
                num_decoder_layers, units, hidden_size, num_heads, dropout,
                pre_norm=True, activation="relu", dtype=dtype, prefix="dec_")
            if not tie_weights:
                self.proj = Dense(vocab_size, use_bias=False, flatten=False,
                                  dtype=dtype, prefix="proj_")
        self._drop = Dropout(dropout) if dropout else None
        if self._drop is not None:
            self.register_child(self._drop, "dropout")

    # -- halves (used by the search loops) ------------------------------
    def encode(self, src):
        x = self.embed(src) * math.sqrt(self._units)
        x = self.pos_enc(x)
        if self._drop is not None:
            x = self._drop(x)
        return self.encoder(x)

    def decode(self, tgt, memory):
        """tgt [B, T] int tokens → logits [B, T, V] (causal)."""
        x = self.embed(tgt) * math.sqrt(self._units)
        x = self.pos_enc(x)
        if self._drop is not None:
            x = self._drop(x)
        h = self.decoder(x, memory)
        if self._tie:
            from ... import ndarray as F
            # Parameter.data() returns the traced stand-in inside a jit
            # trace, so weight tying composes into the compiled graph
            return F.dot(h, self.embed.weight.data(), transpose_b=True)
        return self.proj(h)

    def forward(self, src, tgt):
        return self.decode(tgt, self.encode(src))


def transformer_base(vocab_size, max_length=1024, dropout=0.1, **kwargs):
    return Transformer(vocab_size, units=512, hidden_size=2048, num_heads=8,
                       max_length=max_length, dropout=dropout, **kwargs)


def transformer_big(vocab_size, max_length=1024, dropout=0.3, **kwargs):
    """The WMT'14 "big" configuration (BASELINE.md config 4)."""
    return Transformer(vocab_size, units=1024, hidden_size=4096, num_heads=16,
                       max_length=max_length, dropout=dropout, **kwargs)


def transformer_sharding_rules(fsdp=False):
    """Megatron-style TP placement for SPMDTrainer (same conventions as
    ``bert_sharding_rules``): QKV/FFN-in column-parallel, out-proj/FFN-out
    row-parallel, embedding vocab-sharded."""
    from ...parallel.sharding import ShardingRules

    dp = "fsdp" if fsdp else None
    return ShardingRules(rules=[
        (r".*qkv_weight$", ("tp", dp)),
        (r".*kv_weight$", ("tp", dp)),
        (r".*q_weight$", ("tp", dp)),
        (r".*ffn1_weight$", ("tp", dp)),
        (r".*(out|ffn2)_weight$", (dp, "tp")),
        (r".*embed_weight$", ("tp", dp)),
        (r".*_bias$", (None,)),
    ])


# ---------------------------------------------------------------------------
# Search (greedy + beam) — bucketed-prefix jit discipline
# ---------------------------------------------------------------------------


def _bucket(n, max_len):
    b = 8
    while b < n:
        b *= 2
    return min(b, max_len)


def _step_logits(model, tgt_padded, memory, t):
    """Logits for position t given prefix tgt[:, :t+1], padded to a bucket
    length.  Causality guarantees positions > t cannot leak in."""
    from ... import ndarray as F
    logits = model.decode(tgt_padded, memory)  # [B, Tb, V]
    return logits[:, t]


def greedy_search(model, src, bos, eos, max_length=64):
    """Greedy decode → (tokens [B, max_length], lengths [B])."""
    import numpy as np

    from ... import ndarray as nd

    memory = model.encode(src)
    B = src.shape[0]
    tokens = np.full((B, max_length), eos, np.int32)
    tokens[:, 0] = bos
    lengths = np.full(B, max_length, np.int32)
    done = np.zeros(B, bool)
    for t in range(max_length - 1):
        tb = _bucket(t + 1, max_length)
        logits = _step_logits(model, nd.array(tokens[:, :tb], dtype="int32"),
                              memory, t)
        nxt = logits.asnumpy().argmax(axis=-1).astype(np.int32)
        nxt = np.where(done, eos, nxt)
        tokens[:, t + 1] = nxt
        newly = (~done) & (nxt == eos)
        lengths[newly] = t + 2
        done |= nxt == eos
        if done.all():
            break
    return tokens, lengths


def beam_search(model, src, bos, eos, beam_size=4, max_length=64, alpha=0.6):
    """Length-penalized beam search (GNMT penalty ((5+len)/6)^alpha).

    Returns (tokens [B, K, max_length], scores [B, K]) sorted best-first.
    The per-step network call is one jitted decode over [B·K, Tb]; beam
    bookkeeping is host-side numpy (cheap: K·V topk per step).
    """
    import numpy as np

    from ... import ndarray as nd

    memory = model.encode(src)          # [B, S, D]
    B, K = src.shape[0], beam_size
    mem = nd.array(np.repeat(memory.asnumpy(), K, axis=0))  # [B·K, S, D]

    tokens = np.full((B, K, max_length), eos, np.int32)
    tokens[:, :, 0] = bos
    scores = np.full((B, K), -np.inf, np.float64)
    scores[:, 0] = 0.0                  # only beam 0 live at t=0
    done = np.zeros((B, K), bool)

    for t in range(max_length - 1):
        tb = _bucket(t + 1, max_length)
        flat = tokens[:, :, :tb].reshape(B * K, tb)
        logits = _step_logits(model, nd.array(flat, dtype="int32"), mem, t)
        logp = _log_softmax_np(logits.asnumpy().astype(np.float64))  # [B·K, V]
        V = logp.shape[-1]
        logp = logp.reshape(B, K, V)
        # finished beams only extend with eos at zero cost
        logp = np.where(done[:, :, None],
                        np.where(np.arange(V)[None, None] == eos, 0.0, -np.inf),
                        logp)
        cand = scores[:, :, None] + logp            # [B, K, V]
        flat_cand = cand.reshape(B, K * V)
        top = np.argsort(-flat_cand, axis=1)[:, :K]  # [B, K]
        new_scores = np.take_along_axis(flat_cand, top, axis=1)
        src_beam = top // V
        nxt_tok = (top % V).astype(np.int32)

        tokens = np.take_along_axis(
            tokens, src_beam[:, :, None], axis=1)
        tokens[:, :, t + 1] = nxt_tok
        done = np.take_along_axis(done, src_beam, axis=1) | (nxt_tok == eos)
        scores = new_scores
        if done.all():
            break

    lengths = np.argmax(tokens == eos, axis=-1) + 1
    lengths[~done] = max_length
    lp = ((5.0 + lengths) / 6.0) ** alpha
    final = scores / lp
    order = np.argsort(-final, axis=1)
    return (np.take_along_axis(tokens, order[:, :, None], axis=1),
            np.take_along_axis(final, order, axis=1))


def _log_softmax_np(x):
    m = x.max(axis=-1, keepdims=True)
    e = _np.exp(x - m)
    return (x - m) - _np.log(e.sum(axis=-1, keepdims=True))
