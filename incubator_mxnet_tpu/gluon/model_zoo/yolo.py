"""YOLOv3 (parity: the reference ecosystem's YOLOv3-darknet53 — the
detection family SURVEY.md's goal statement pairs with SSD-512; the
reference's own detection path is [U:example/ssd/] plus the YOLO
augmenters in [U:python/mxnet/image/detection.py]).

TPU-first shape discipline (same contract as ssd.py): every stage is
fixed-shape.  Per-scale grids and anchor tables are computed from the
statically-known feature shapes under trace, predictions concatenate to
one ``[B, N, 5+C]`` tensor, decoding is pure elementwise math, and NMS is
the mask-based ``box_nms`` from :mod:`...ops.detection`.  Both the
forward and a full training step jit.

Training targets use the dense best-anchor assignment
(:func:`yolo3_targets`): IoU of every (padded) ground-truth box against
every anchor prior, argmax over anchors — a static-shape formulation of
the reference's dynamic target matcher, mask-based like MultiBoxTarget.
"""
from __future__ import annotations

import numpy as _np

from ..block import HybridBlock
from .. import nn

__all__ = ["DarknetV3", "YOLOV3", "yolo3_darknet53", "yolo3_decode",
           "yolo3_targets", "yolo3_loss", "Yolo3DefaultAnchors"]

# The canonical COCO anchor schedule (pixels, for a 416 input), small→large
# stride scales: [8 is not used by v3; strides are 8/16/32 bottom-up].
Yolo3DefaultAnchors = [
    [(10, 13), (16, 30), (33, 23)],       # stride 8
    [(30, 61), (62, 45), (59, 119)],      # stride 16
    [(116, 90), (156, 198), (373, 326)],  # stride 32
]
Yolo3Strides = [8, 16, 32]


def _conv2d(channel, kernel, padding, stride):
    """conv → BN → LeakyReLU(0.1), the darknet unit."""
    cell = nn.HybridSequential(prefix="")
    cell.add(nn.Conv2D(channel, kernel_size=kernel, strides=stride,
                       padding=padding, use_bias=False))
    cell.add(nn.BatchNorm(epsilon=1e-5, momentum=0.9))
    cell.add(nn.LeakyReLU(0.1))
    return cell


class DarknetBasicBlockV3(HybridBlock):
    """1×1 bottleneck + 3×3, residual add."""

    def __init__(self, channel, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="")
            self.body.add(_conv2d(channel // 2, 1, 0, 1))
            self.body.add(_conv2d(channel, 3, 1, 1))

    def hybrid_forward(self, F, x):
        return x + self.body(x)


class DarknetV3(HybridBlock):
    """Darknet-53 backbone: stem + 5 stages of [1, 2, 8, 8, 4] residual
    blocks; ``stage_outputs`` taps the last 3 stages (strides 8/16/32)."""

    def __init__(self, layers=(1, 2, 8, 8, 4),
                 channels=(64, 128, 256, 512, 1024), **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.stages = nn.HybridSequential(prefix="")
            stem = nn.HybridSequential(prefix="")
            stem.add(_conv2d(32, 3, 1, 1))
            self.stages.add(stem)
            for nlayer, channel in zip(layers, channels):
                stage = nn.HybridSequential(prefix="")
                stage.add(_conv2d(channel, 3, 1, 2))  # stride-2 entry
                for _ in range(nlayer):
                    stage.add(DarknetBasicBlockV3(channel))
                self.stages.add(stage)

    def hybrid_forward(self, F, x):
        outs = []
        for i, stage in enumerate(self.stages._children.values()):
            x = stage(x)
            if i >= 3:  # stages at stride 8, 16, 32
                outs.append(x)
        return tuple(outs)


class YOLODetectionBlockV3(HybridBlock):
    """5-conv body → ``route`` (lateral, c) and ``tip`` (3×3, 2c)."""

    def __init__(self, channel, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="")
            for _ in range(2):
                self.body.add(_conv2d(channel, 1, 0, 1))
                self.body.add(_conv2d(channel * 2, 3, 1, 1))
            self.body.add(_conv2d(channel, 1, 0, 1))
            self.tip = _conv2d(channel * 2, 3, 1, 1)

    def hybrid_forward(self, F, x):
        route = self.body(x)
        return route, self.tip(route)


class YOLOV3(HybridBlock):
    """YOLOv3 with a top-down FPN over 3 backbone scales.

    Forward returns the RAW per-anchor prediction tensor
    ``[B, N, 5 + num_classes]`` (tx, ty, tw, th, obj, cls...) plus the
    static decode tables ``offsets [1, N, 2]``, ``anchors [1, N, 2]``,
    ``strides [1, N, 1]`` — feed them to :func:`yolo3_decode` for boxes
    or :func:`yolo3_loss` for training.
    """

    def __init__(self, backbone=None, num_classes=80,
                 anchors=Yolo3DefaultAnchors, strides=Yolo3Strides,
                 channels=(128, 256, 512), **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self._anchors = anchors
        self._strides = list(strides)
        self._table_cache = {}  # (h, w, scale_idx) → static decode tables
        na = len(anchors[0])
        with self.name_scope():
            self.backbone = backbone or DarknetV3()
            # top-down order: build blocks for the LARGEST stride first
            self.blocks = nn.HybridSequential(prefix="blk_")
            self.outputs = nn.HybridSequential(prefix="out_")
            self.laterals = nn.HybridSequential(prefix="lat_")
            for i, ch in enumerate(reversed(channels)):
                self.blocks.add(YOLODetectionBlockV3(ch))
                self.outputs.add(nn.Conv2D(na * (5 + num_classes),
                                           kernel_size=1))
                if i < len(channels) - 1:
                    self.laterals.add(_conv2d(ch // 2, 1, 0, 1))

    def hybrid_forward(self, F, x):
        feats = list(self.backbone(x))          # strides [8, 16, 32]
        feats = feats[::-1]                     # top-down: 32 first
        strides = self._strides[::-1]
        anchors = self._anchors[::-1]
        na = len(anchors[0])

        all_preds, all_offsets, all_anchors, all_strides = [], [], [], []
        route = None
        blocks = list(self.blocks._children.values())
        outputs = list(self.outputs._children.values())
        laterals = list(self.laterals._children.values())
        for i, feat in enumerate(feats):
            if route is not None:
                up = F.UpSampling(laterals[i - 1](route), scale=2,
                                  sample_type="nearest")
                feat = F.concat(up, feat, dim=1)
            route, tip = blocks[i](feat)
            raw = outputs[i](tip)               # [B, A*(5+C), H, W]
            b, _, h, w = raw.shape
            raw = raw.transpose((0, 2, 3, 1)).reshape((b, h * w * na,
                                                       5 + self.num_classes))
            all_preds.append(raw)

            # static decode tables for this scale: input-independent, so
            # computed once per feature shape and reused every forward
            key = (h, w, i)
            if key not in self._table_cache:
                np = _np
                ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
                grid = np.stack([xs, ys], axis=-1).reshape(h * w, 1, 2)
                grid = np.broadcast_to(grid, (h * w, na, 2)).reshape(1, -1, 2)
                anc = np.asarray(anchors[i], dtype=np.float32).reshape(1, 1, na, 2)
                anc = np.broadcast_to(anc, (1, h * w, na, 2)).reshape(1, -1, 2)
                st = np.full((1, h * w * na, 1), strides[i], dtype=np.float32)
                self._table_cache[key] = (F.array(grid.astype(np.float32)),
                                          F.array(anc.copy()), F.array(st))
            off_c, anc_c, st_c = self._table_cache[key]
            all_offsets.append(off_c)
            all_anchors.append(anc_c)
            all_strides.append(st_c)

        return (F.concat(*all_preds, dim=1),
                F.concat(*all_offsets, dim=1),
                F.concat(*all_anchors, dim=1),
                F.concat(*all_strides, dim=1))


def yolo3_decode(preds, offsets, anchors, strides, num_classes):
    """Raw predictions → ``(ids [B,N,1], scores [B,N,1], boxes [B,N,4])``
    in pixel corner format: the standard v3 decode
    (σ(txy)+grid)·stride, exp(twh)·anchor, σ(obj)·σ(cls)."""
    from ... import ndarray as nd

    txy = nd.slice_axis(preds, axis=-1, begin=0, end=2)
    twh = nd.slice_axis(preds, axis=-1, begin=2, end=4)
    obj = nd.slice_axis(preds, axis=-1, begin=4, end=5)
    cls = nd.slice_axis(preds, axis=-1, begin=5, end=5 + num_classes)

    xy = (nd.sigmoid(txy) + offsets) * strides
    wh = nd.exp(nd.clip(twh, -10, 8)) * anchors
    half = wh * 0.5
    boxes = nd.concat(xy - half, xy + half, dim=-1)
    scores = nd.sigmoid(obj) * nd.sigmoid(cls)          # [B, N, C]
    conf = nd.max(scores, axis=-1, keepdims=True)
    ids = nd.argmax(scores, axis=-1).expand_dims(-1)
    return ids, conf, boxes


def yolo3_targets(gt_boxes, gt_ids, offsets, anchors, strides, num_classes,
                  ignore_thresh=0.7):
    """Dense static-shape target assignment.

    gt_boxes: [B, M, 4] pixel corners, padded rows = -1.
    Returns (obj_t [B,N,1], box_t [B,N,4] raw-space, cls_t [B,N,C],
    masks [B,N,2]): for each valid gt, the prior (grid cell × anchor)
    whose centered anchor box has max IoU gets objectness 1, the encoded
    (tx,ty,tw,th), and the one-hot class.  When several gts pick the same
    prior, the highest-IoU gt wins (never a sum of encodings).
    ``masks[..., 0]`` is the positive mask; ``masks[..., 1]`` weights the
    objectness BCE — 0 for non-positive priors whose IoU with any gt
    exceeds ``ignore_thresh`` (the reference's ignore band, which keeps
    near-hits out of the negative loss)."""
    from ... import ndarray as nd

    B, M, _ = gt_boxes.shape
    N = offsets.shape[1]
    centers = (offsets + 0.5) * strides                  # [1, N, 2]
    half = anchors * 0.5
    priors = nd.concat(centers - half, centers + half, dim=-1)  # [1, N, 4]

    valid = (nd.slice_axis(gt_ids, axis=-1, begin=0, end=1) >= 0)  # [B, M, 1]
    ious = nd.reshape(nd.box_iou(gt_boxes.reshape((-1, 4)),
                                 priors.reshape((-1, 4))), (B, M, N))
    ious = ious * valid                                  # kill padded rows
    best = nd.argmax(ious, axis=-1)                      # [B, M] prior index

    onehotN = nd.one_hot(best.reshape((-1,)), N).reshape((B, M, N))
    onehotN = onehotN * valid                            # [B, M, N]
    obj_t = nd.max(onehotN, axis=1).expand_dims(-1)      # [B, N, 1]

    # crowded-scene tie-break: among gts assigned to a prior, the one with
    # max IoU wins it outright
    winner = nd.argmax(onehotN * ious, axis=1)           # [B, N] gt index
    winner_oh = nd.one_hot(winner.reshape((-1,)), M).reshape((B, N, M))
    winner_oh = winner_oh.transpose((0, 2, 1))           # [B, M, N]
    assign = winner_oh * onehotN                         # ≤1 gt per prior

    # encode each gt in raw space against ITS assigned prior
    gxy = (nd.slice_axis(gt_boxes, axis=-1, begin=0, end=2)
           + nd.slice_axis(gt_boxes, axis=-1, begin=2, end=4)) * 0.5
    gwh = (nd.slice_axis(gt_boxes, axis=-1, begin=2, end=4)
           - nd.slice_axis(gt_boxes, axis=-1, begin=0, end=2))
    strid = strides.reshape((1, 1, N, 1))
    offs = offsets.reshape((1, 1, N, 2))
    ancs = anchors.reshape((1, 1, N, 2))
    txy = gxy.reshape((B, M, 1, 2)) / strid - offs       # pre-sigmoid target
    txy = nd.clip(txy, 1e-6, 1 - 1e-6)
    twh = nd.log(nd.clip(gwh.reshape((B, M, 1, 2)) / ancs, 1e-6, 1e6))
    enc = nd.concat(txy, twh, dim=-1)                    # [B, M, N, 4]
    box_t = nd.sum(enc * assign.expand_dims(-1), axis=1)  # [B, N, 4]

    oh_cls = nd.one_hot(nd.clip(gt_ids.reshape((B, M)), 0, num_classes - 1),
                        num_classes)                     # [B, M, C]
    cls_t = nd.sum(assign.expand_dims(-1)
                   * oh_cls.reshape((B, M, 1, num_classes)), axis=1)

    # objectness ignore band: non-positive priors overlapping any gt above
    # ignore_thresh contribute nothing to the negative BCE
    max_iou = nd.max(ious, axis=1).expand_dims(-1)       # [B, N, 1]
    obj_w = nd.where(obj_t + (max_iou < ignore_thresh) > 0,
                     nd.ones_like(obj_t), nd.zeros_like(obj_t))
    return obj_t, box_t, cls_t, nd.concat(obj_t, obj_w, dim=-1)


def yolo3_loss(preds, obj_t, box_t, cls_t, masks, num_classes,
               reduction="mean"):
    """The v3 loss: BCE(obj) over non-ignored priors (see
    :func:`yolo3_targets`' ignore band) + (BCE(cls) + L2 on
    (σ(txy), twh)) on positives.  ``reduction='mean'`` averages over the
    batch (a scalar); ``'none'`` returns the per-sample loss ``[B]`` (the
    form SPMDTrainer loss_fns return)."""
    from ... import ndarray as nd

    pos_mask = nd.slice_axis(masks, axis=-1, begin=0, end=1)
    obj_w = nd.slice_axis(masks, axis=-1, begin=1, end=2)

    txy = nd.sigmoid(nd.slice_axis(preds, axis=-1, begin=0, end=2))
    twh = nd.slice_axis(preds, axis=-1, begin=2, end=4)
    obj = nd.slice_axis(preds, axis=-1, begin=4, end=5)
    cls = nd.slice_axis(preds, axis=-1, begin=5, end=5 + num_classes)

    def bce(logit, target):
        return nd.relu(logit) - logit * target + nd.log1p(nd.exp(-nd.abs(logit)))

    box_pred = nd.concat(txy, twh, dim=-1)
    per_sample = (nd.sum(bce(obj, obj_t) * obj_w, axis=(1, 2))
                  + nd.sum(bce(cls, cls_t) * pos_mask, axis=(1, 2))
                  + nd.sum(nd.square(box_pred - box_t) * pos_mask,
                           axis=(1, 2)))
    if reduction == "none":
        return per_sample
    return nd.mean(per_sample)


def yolo3_darknet53(num_classes=80, **kwargs):
    """YOLOv3 with the Darknet-53 backbone (the canonical config)."""
    return YOLOV3(DarknetV3(), num_classes=num_classes, **kwargs)
