"""Mixture-of-Experts layers — the 'ep' mesh axis tier.

:class:`MoEBlock` is a drop-in replacement for a transformer FFN sublayer
(``nn.PositionwiseFFN``): a router picks ``top_k`` of ``num_experts``
expert FFNs per token, tokens are dispatched under a per-expert capacity
with deterministic overflow drops, and outputs combine gate-weighted.
The expert weights are SINGLE stacked parameters with a leading
``num_experts`` dim — :func:`moe_sharding_rules` shards exactly that dim
over 'ep', so expert parallelism is one ``ShardingRules`` entry and XLA
derives the token all-to-alls from the annotations (no bespoke comm
path, matching the repo's SPMD design).  The math lives in the
registered :func:`ops.moe.moe_ffn` kernel, so eager autograd, hybridize
and the SPMD trace all share one implementation.

Auxiliary losses (Switch-style load balancing + router z-loss) must
reach the training loss *inside* the compiled step.  The frame protocol
here does that: ``SPMDTrainer`` (and any custom step) opens a
:func:`moe_loss_frame` around the forward; every MoE layer registers its
weighted losses and routing metrics into the innermost frame, and the
trainer folds :func:`frame_loss` into the scalar it differentiates and
ships :func:`frame_metrics` out of the program for the
``moe_tokens_dropped`` counter / expert-load gauges.  With no frame open
(plain eager training) the layer stashes its last weighted loss on
``self`` — add ``block.aux_loss()`` to the loss before ``backward()``.
"""
from __future__ import annotations

import threading as _threading

from ..block import HybridBlock
from ...parallel.schedule import in_backward_trace

__all__ = [
    "MoEBlock",
    "moe_sharding_rules",
    "moe_loss_frame",
    "frame_loss",
    "frame_metrics",
]

_tls = _threading.local()


def _frames():
    st = getattr(_tls, "frames", None)
    if st is None:
        st = _tls.frames = []
    return st


class moe_loss_frame:
    """``with moe_loss_frame() as frame:`` — collect every MoE layer's
    weighted aux losses and routing metrics traced inside the scope."""

    def __init__(self):
        self.losses = []     # weighted scalar losses (traced values)
        self.metrics = []    # dicts of traced metric scalars

    def __enter__(self):
        _frames().append(self)
        return self

    def __exit__(self, *exc):
        _frames().pop()
        return False


def _register(loss, metrics):
    if in_backward_trace():
        # a remat stage's backward slot re-traces the forward; its values
        # belong to the checkpoint primitive's inner scope — collecting
        # them would both double-count and leak inner tracers
        return False
    st = _frames()
    if not st:
        return False
    st[-1].losses.append(loss)
    st[-1].metrics.append(metrics)
    return True


def frame_loss(frame):
    """Sum of the frame's weighted aux losses (None when no MoE ran)."""
    if not frame.losses:
        return None
    total = frame.losses[0]
    for l in frame.losses[1:]:
        total = total + l
    return total


def frame_metrics(frame):
    """Combined routing metrics across the frame's layers: summed drops
    and slots, min/max expert load over every layer.  Values are traced
    scalars — return them from the compiled step, then read on host."""
    if not frame.metrics:
        return None
    out = {
        "tokens_dropped": frame.metrics[0]["tokens_dropped"],
        "expert_load_min": frame.metrics[0]["expert_load_min"],
        "expert_load_max": frame.metrics[0]["expert_load_max"],
    }
    for m in frame.metrics[1:]:
        out["tokens_dropped"] = out["tokens_dropped"] + m["tokens_dropped"]
        mn, mx = m["expert_load_min"], m["expert_load_max"]
        out["expert_load_min"] = 0.5 * (
            out["expert_load_min"] + mn - abs(out["expert_load_min"] - mn))
        out["expert_load_max"] = 0.5 * (
            out["expert_load_max"] + mx + abs(out["expert_load_max"] - mx))
    return out


def moe_sharding_rules(base=None):
    """Prepend expert-parallel placement to a rule table: the stacked
    expert dim (axis 0 of ``experts_*``) shards over 'ep', the router
    stays replicated.  ``base`` rules (tp/fsdp) apply to everything
    else."""
    from ...parallel.sharding import ShardingRules
    from jax.sharding import PartitionSpec as P

    rules = ShardingRules([
        (r"experts_.*weight$", P("ep", None, None)),
        (r"experts_.*bias$", P("ep", None)),
        (r"router_weight$", P(None, None)),
    ], default=base.default if base is not None else P())
    if base is not None:
        for pat, spec in base:   # ShardingRules is iterable; add()
            rules.add(pat, spec)  # accepts compiled patterns
    return rules


class MoEBlock(HybridBlock):
    """Top-k routed mixture-of-experts FFN: [..., units] → [..., units].

    Parameters
    ----------
    units : int
        Token feature dim (input and output).
    hidden_size : int
        Per-expert FFN hidden dim.
    num_experts : int
    top_k : int, default 2
    capacity_factor : float, default 1.25
        Per-expert slots = ceil(T·k/E · capacity_factor); overflow tokens
        are dropped deterministically (choice-rank then token order) and
        counted.
    aux_loss_weight / z_loss_weight : float
        Weights on the load-balancing loss (Switch: E·Σ f·P̄) and router
        z-loss (mean logsumexp²); the WEIGHTED sum is what reaches the
        frame / ``aux_loss()``.
    """

    def __init__(self, units, hidden_size, num_experts, top_k=2,
                 capacity_factor=1.25, aux_loss_weight=1e-2,
                 z_loss_weight=1e-3, activation="relu", dtype="float32",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if top_k > num_experts:
            raise ValueError(f"top_k {top_k} > num_experts {num_experts}")
        self._units = int(units)
        self._hidden = int(hidden_size)
        self._num_experts = int(num_experts)
        self._top_k = int(top_k)
        self._capacity_factor = float(capacity_factor)
        self._aux_w = float(aux_loss_weight)
        self._z_w = float(z_loss_weight)
        self._activation = activation
        self._last_aux = None
        E, d, h = self._num_experts, self._units, self._hidden
        with self.name_scope():
            self.router_weight = self.params.get(
                "router_weight", shape=(d, E), dtype="float32")
            self.experts_mlp1_weight = self.params.get(
                "experts_mlp1_weight", shape=(E, d, h), dtype=dtype)
            self.experts_mlp1_bias = self.params.get(
                "experts_mlp1_bias", shape=(E, h), dtype=dtype, init="zeros")
            self.experts_mlp2_weight = self.params.get(
                "experts_mlp2_weight", shape=(E, h, d), dtype=dtype)
            self.experts_mlp2_bias = self.params.get(
                "experts_mlp2_bias", shape=(E, d), dtype=dtype, init="zeros")

    def hybrid_forward(self, F, x, router_weight, experts_mlp1_weight,
                       experts_mlp1_bias, experts_mlp2_weight,
                       experts_mlp2_bias):
        outs = F.contrib.moe_ffn(
            x, router_weight, experts_mlp1_weight, experts_mlp1_bias,
            experts_mlp2_weight, experts_mlp2_bias,
            num_experts=self._num_experts, top_k=self._top_k,
            capacity_factor=self._capacity_factor,
            activation=self._activation)
        y, aux, z, dropped, load_min, load_max = outs
        weighted = aux * self._aux_w + z * self._z_w

        def _raw(v):
            return v._data if hasattr(v, "_data") else v

        registered = _register(weighted, {
            "tokens_dropped": _raw(dropped),
            "expert_load_min": _raw(load_min),
            "expert_load_max": _raw(load_max),
        })
        if not registered and not in_backward_trace():
            import jax as _jax

            if not isinstance(_raw(weighted), _jax.core.Tracer):
                # eager path: stash for block.aux_loss().  A frameless
                # TRACED forward (hybridize's cached-graph build, a hand
                # jit) must not stash — the tracer would leak out of its
                # finished trace and poison a later aux_loss() use
                self._last_aux = weighted
        return y

    def aux_loss(self):
        """Last EAGER forward's weighted aux loss (add it to the task
        loss before ``backward()``).  Compiled paths don't stash: the
        SPMD step collects through :func:`moe_loss_frame`, and a
        hybridized block's cached graph never re-runs this Python — use
        the un-hybridized block (or the frame) when you need the loss."""
        if self._last_aux is None:
            raise RuntimeError(
                "MoEBlock.aux_loss(): no eager forward has run (compiled "
                "forwards — hybridize/SPMD — don't stash; collect via "
                "moe_loss_frame instead)")
        return self._last_aux

    def __repr__(self):
        return (f"MoEBlock({self._units} -> {self._num_experts}x"
                f"[{self._hidden}] top{self._top_k}, "
                f"cf={self._capacity_factor})")
