"""Model zoo (parity: [U:python/mxnet/gluon/model_zoo/])."""
from . import vision
from . import bert
from .vision import get_model
from .bert import BERTModel, BERTForPretrain, bert_base, bert_large, bert_sharding_rules

__all__ = [
    "vision",
    "bert",
    "get_model",
    "BERTModel",
    "BERTForPretrain",
    "bert_base",
    "bert_large",
    "bert_sharding_rules",
]
