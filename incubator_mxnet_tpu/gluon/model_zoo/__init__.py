"""Model zoo (parity: [U:python/mxnet/gluon/model_zoo/])."""
from . import vision
from . import bert
from . import yolo
from . import moe
from .moe import MoEBlock, moe_sharding_rules
from .vision import get_model
from .bert import BERTModel, BERTForPretrain, bert_base, bert_large, bert_sharding_rules
from .yolo import YOLOV3, DarknetV3, yolo3_darknet53

__all__ = [
    "vision",
    "bert",
    "yolo",
    "get_model",
    "BERTModel",
    "BERTForPretrain",
    "bert_base",
    "bert_large",
    "bert_sharding_rules",
    "YOLOV3",
    "DarknetV3",
    "yolo3_darknet53",
    "moe",
    "MoEBlock",
    "moe_sharding_rules",
]
