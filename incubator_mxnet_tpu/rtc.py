"""``mx.rtc`` — runtime-compiled custom kernels, the Pallas way.

Parity target: [U:python/mxnet/rtc.py] (``CudaModule``: compile raw CUDA
C at runtime, ``get_kernel(name, signature)``, ``kernel.launch(args, ctx,
grid_dims, block_dims)``).

TPU-native design: the runtime-kernel story on TPU is **Pallas/Mosaic**,
not NVRTC, so the "source" a :class:`PallasModule` compiles is Pallas
kernel code — either a Python *string* compiled at runtime (the closest
analog of the reference's CUDA-source string) or already-defined kernel
functions.  A kernel body follows the standard Pallas contract: it takes
input ``Ref``s then output ``Ref``s and writes results with ``o[...] =``.
``launch`` mirrors the reference's shape: positional NDArray args, an
optional grid, and it allocates + returns the outputs.

Off-TPU the kernel runs under ``interpret=True`` (the same dispatch
discipline as ops/attention.py), so rtc kernels are testable on the CPU
mesh.  Like the reference, rtc kernels are raw compute: no autograd
(wrap one in ``mx.operator.CustomOp`` to differentiate through it).
"""
from __future__ import annotations

import textwrap

import jax
import jax.numpy as jnp

from .util import resolve_platform

__all__ = ["PallasModule"]


class Kernel:
    """A launchable kernel (parity shape: ``mx.rtc.CudaKernel``)."""

    def __init__(self, fn, name, out_shapes, out_dtypes, grid, in_specs, out_specs):
        self._fn = fn
        self.name = name
        self._out_shapes = tuple(tuple(s) for s in out_shapes)
        self._out_dtypes = tuple(out_dtypes)
        self._grid = grid
        self._in_specs = in_specs
        self._out_specs = out_specs
        # compiled-once discipline (the reference compiles at get_kernel
        # time): pallas_call closures cached per (grid, platform)
        self._calls = {}

    def _call(self, grid, platform):
        key = (grid, platform)
        call = self._calls.get(key)
        if call is not None:
            return call
        from jax.experimental import pallas as pl

        out_shape = [jax.ShapeDtypeStruct(s, jnp.dtype(d))
                     for s, d in zip(self._out_shapes, self._out_dtypes)]
        single = len(out_shape) == 1
        kwargs = {}
        if grid is not None:
            kwargs["grid"] = grid
        if self._in_specs is not None:
            kwargs["in_specs"] = self._in_specs
        if self._out_specs is not None:
            kwargs["out_specs"] = self._out_specs if not single else self._out_specs[0]
        call = jax.jit(pl.pallas_call(
            self._fn,
            out_shape=out_shape[0] if single else out_shape,
            interpret=platform != "tpu",
            **kwargs,
        ))
        self._calls[key] = call
        return call

    def launch(self, args, ctx=None, grid_dims=None):
        """Run the kernel on ``args`` (NDArrays); returns the output
        NDArray, or a tuple when the kernel has several outputs.

        ``grid_dims`` overrides the grid given at ``get_kernel`` time
        (the reference's launch-time grid).  ``ctx`` is accepted for API
        parity; placement follows the inputs, like every other op here.
        """
        from .ndarray.ndarray import NDArray

        del ctx
        grid = grid_dims if grid_dims is not None else self._grid
        if isinstance(grid, list):
            grid = tuple(grid)
        xs = [a._data if isinstance(a, NDArray) else jnp.asarray(a) for a in args]
        platform = resolve_platform(xs[0] if xs else None)
        out = self._call(grid, platform)(*xs)
        if len(self._out_shapes) == 1:
            return NDArray(out)
        return tuple(NDArray(o) for o in out)


class PallasModule:
    """Compile Pallas kernel source at runtime (parity:
    ``mx.rtc.CudaModule``).

    ``source`` is either a string of Python code defining kernel
    functions (compiled with ``exec`` in a namespace that already has
    ``pl``, ``jnp``, ``jax`` — the runtime-compilation analog of NVRTC),
    or a callable / iterable of callables.  ``exports`` optionally limits
    which names are retrievable, like the reference's exports list.

    Example::

        src = '''
        def scale_add(x_ref, y_ref, o_ref):
            o_ref[...] = 2.0 * x_ref[...] + y_ref[...]
        '''
        mod = mx.rtc.PallasModule(src, exports=["scale_add"])
        k = mod.get_kernel("scale_add", out_shapes=[(64, 64)])
        z = k.launch([x, y])
    """

    def __init__(self, source, exports=()):
        from jax.experimental import pallas as pl

        self._kernels = {}
        if isinstance(source, str):
            ns = {"pl": pl, "jnp": jnp, "jax": jax}
            exec(compile(textwrap.dedent(source), "<mx.rtc source>", "exec"), ns)
            fns = {k: v for k, v in ns.items()
                   if callable(v) and k not in ("pl", "jnp", "jax")
                   and not k.startswith("__")}
        elif callable(source):
            fns = {source.__name__: source}
        else:
            fns = {f.__name__: f for f in source}
        allowed = set(exports) if exports else None
        for name, fn in fns.items():
            if allowed is None or name in allowed:
                self._kernels[name] = fn
        if allowed is not None and allowed - set(self._kernels):
            missing = sorted(allowed - set(self._kernels))
            raise ValueError(f"exports not found in source: {missing}")

    def get_kernel(self, name, out_shapes, out_dtypes=None, grid=None,
                   in_specs=None, out_specs=None, signature=None):
        """Retrieve a launchable kernel.

        ``out_shapes``/``out_dtypes`` declare the outputs the kernel
        writes (the role the reference's C ``signature`` string played —
        accepted as ``signature`` for drop-in callers and ignored).
        ``grid``/``in_specs``/``out_specs`` are the Pallas launch
        geometry; with no grid the kernel sees whole-array Refs.
        """
        del signature
        if name not in self._kernels:
            raise ValueError(
                f"kernel {name!r} not in module (have {sorted(self._kernels)})")
        if isinstance(out_shapes[0], int):
            out_shapes = [out_shapes]
        if out_dtypes is None:
            out_dtypes = ["float32"] * len(out_shapes)
        elif isinstance(out_dtypes, str):
            out_dtypes = [out_dtypes] * len(out_shapes)
        if len(out_dtypes) != len(out_shapes):
            raise ValueError(
                f"out_dtypes has {len(out_dtypes)} entries for "
                f"{len(out_shapes)} out_shapes")
        if out_specs is not None and not isinstance(out_specs, (list, tuple)):
            out_specs = [out_specs]
        if out_specs is not None and len(out_specs) != len(out_shapes):
            raise ValueError(
                f"out_specs has {len(out_specs)} entries for "
                f"{len(out_shapes)} out_shapes")
        return Kernel(self._kernels[name], name, out_shapes, out_dtypes,
                      grid, in_specs, out_specs)
