"""``mx.amp`` — automatic mixed precision.

Parity target: [U:python/mxnet/contrib/amp/amp.py].  The reference
monkey-patches op invocation to insert ``amp_cast``/``amp_multicast``
nodes per allow/deny lists and adds a dynamic loss scaler for fp16.
TPU-native version: one dispatch hook on ``ndarray.invoke`` casts float
inputs per the same list structure (lists.py) — because Gluon layers,
``hybridize`` traces, Symbol executors and SPMDTrainer all funnel through
the same registry dispatch, a single hook covers eager, jitted and SPMD
execution.  Target dtype is bfloat16 (MXU-native; no loss scaling
required); float16 is supported with the reference's dynamic LossScaler
semantics for API/workload parity.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ..base import _as_np_dtype
from . import lists

__all__ = ["init", "init_trainer", "scale_loss", "unscale", "convert_hybrid_block", "LossScaler", "disabled"]

_FLOAT_KINDS = ("f",)


class _AmpPolicy:
    def __init__(self, target_dtype):
        self.target = _as_np_dtype(target_dtype)
        self.fp32 = _np.dtype("float32")

    def _is_float(self, a):
        # jnp.issubdtype, not numpy kind: ml_dtypes' bfloat16 has kind 'V'
        return hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)

    def cast_inputs(self, opname, raw):
        if opname in lists.TARGET_OPS:
            return [a.astype(self.target) if self._is_float(a) and a.dtype != self.target else a
                    for a in raw]
        if opname in lists.FP32_OPS:
            return [a.astype(self.fp32) if self._is_float(a) and a.dtype != self.fp32 else a
                    for a in raw]
        if opname in lists.WIDEST_OPS:
            floats = {_np.dtype(a.dtype) for a in raw if self._is_float(a)}
            if len(floats) > 1:
                widest = max(floats, key=lambda d: d.itemsize)
                return [a.astype(widest) if self._is_float(a) and a.dtype != widest else a
                        for a in raw]
        return raw


def init(target_dtype="bfloat16"):
    """Enable AMP globally (parity: ``amp.init()``).  Idempotent."""
    from ..ndarray import ndarray as nd_core

    assert str(target_dtype) in ("bfloat16", "float16"), target_dtype
    nd_core._amp = _AmpPolicy(target_dtype)
    # new dtype decisions invalidate existing jit caches built without AMP
    _clear_block_caches()


def is_enabled():
    from ..ndarray import ndarray as nd_core

    return getattr(nd_core, "_amp", None) is not None


def disable():
    from ..ndarray import ndarray as nd_core

    nd_core._amp = None
    _clear_block_caches()


class disabled:
    """``with amp.disabled():`` — scoped suspension of the dispatch hook
    (round-2 review asked for a scoped control over the process-global
    state).  Restores the previous policy (and invalidates jit caches both
    ways, since dtype decisions differ) on exit."""

    def __enter__(self):
        from ..ndarray import ndarray as nd_core

        self._prev = getattr(nd_core, "_amp", None)
        if self._prev is not None:
            nd_core._amp = None
            _clear_block_caches()
        return self

    def __exit__(self, *a):
        from ..ndarray import ndarray as nd_core

        if self._prev is not None:
            nd_core._amp = self._prev
            _clear_block_caches()
        return False


def _clear_block_caches():
    """Invalidate every jit cache traced under the previous AMP state:
    HybridBlock CachedOps, SPMDTrainer fused steps, Symbol executors —
    all registered in base._jit_cache_owners at construction."""
    from ..base import invalidate_jit_caches

    invalidate_jit_caches()


class LossScaler:
    """Dynamic loss scaling (parity: [U:python/mxnet/contrib/amp/
    loss_scaler.py]): double every ``scale_window`` good steps, halve and
    skip the update on overflow.  bf16 never overflows in practice; this
    exists for fp16 parity."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0, scale_window=2000):
        self.loss_scale = float(init_scale)
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite.  Accepts Gluon Parameters
        (grads live on the param's NDArray) or NDArrays with attached
        grads.  One fused device reduction + a single host sync, not one
        round-trip per parameter."""
        checks = []
        for p in params:
            data = getattr(p, "_data", None)
            g = getattr(data, "_grad", None) if data is not None else getattr(p, "_grad", None)
            if g is not None:
                checks.append(jnp.isfinite(g._data).all())
        if not checks:
            return False
        return not bool(jnp.all(jnp.stack(checks)))

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self.loss_scale *= self._factor
                self._unskipped = 0


class scale_loss:
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``
    (parity: ``amp.scale_loss``).  Scales the loss up and arranges for the
    trainer to unscale gradients in the optimizer rescale."""

    def __init__(self, loss, trainer):
        self._loss = loss
        self._trainer = trainer

    def __enter__(self):
        scaler = getattr(self._trainer, "_amp_loss_scaler", None)
        if scaler is None:
            return self._loss
        # grads come out multiplied by loss_scale; the wrapped step
        # (init_trainer) divides rescale_grad by the same factor
        if isinstance(self._loss, (list, tuple)):
            return [l * scaler.loss_scale for l in self._loss]
        return self._loss * scaler.loss_scale

    def __exit__(self, *a):
        return False


def init_trainer(trainer):
    """Attach a dynamic LossScaler to a Gluon Trainer and wrap ``step`` to
    skip updates on overflow (parity: ``amp.init_trainer``).  Idempotent —
    wrapping twice would divide gradients by the scale twice."""
    if getattr(trainer, "_amp_loss_scaler", None) is not None:
        return trainer
    scaler = LossScaler()
    trainer._amp_loss_scaler = scaler
    orig_step = trainer.step

    def step(batch_size, ignore_stale_grad=False):
        params = [p for p in trainer._params if p.grad_req != "null"]
        overflow = scaler.has_overflow(params)
        if not overflow:
            if getattr(trainer, "_amp_unscaled", False):
                # grads were already divided by amp.unscale() (clipping
                # flow); don't divide a second time
                trainer._amp_unscaled = False
                orig_step(batch_size, ignore_stale_grad)
            else:
                # fold the loss scale into trainer._scale — Trainer.step
                # recomputes rescale_grad from it every call
                saved = trainer._scale
                trainer._scale = saved / scaler.loss_scale
                try:
                    orig_step(batch_size, ignore_stale_grad)
                finally:
                    trainer._scale = saved
        else:
            trainer._amp_unscaled = False
        scaler.update_scale(overflow)

    trainer.step = step
    return trainer


def unscale(trainer):
    """Explicitly divide current grads by the loss scale (for gradient
    clipping before step)."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    for p in trainer._params:
        if p.grad_req == "null":
            continue
        g = getattr(p._data, "_grad", None) if p._data is not None else None
        if g is not None:
            g._data = g._data / scaler.loss_scale
            g._version += 1
    trainer._amp_unscaled = True  # wrapped step must not divide again


def convert_hybrid_block(block, target_dtype="bfloat16"):
    """Offline O2-style conversion (parity: ``amp.convert_hybrid_block`` /
    the C++ ReducePrecision pass): cast the block's parameters to the
    target dtype in place and return the block.  Combine with ``init()``
    for list-based op casting."""
    target = _as_np_dtype(target_dtype)
    for p in block.collect_params().values():
        if p._data is not None and not jnp.issubdtype(p._data.dtype, jnp.floating):
            continue  # integer params (embedding indices etc.) stay put
        p.cast(target)  # Parameter.cast also rebuilds the grad buffer
    return block


def list_lp16_ops(target_dtype="bfloat16"):
    """Parity: ``amp.list_lp16_ops`` — ops run in the low-precision
    target dtype."""
    from .lists import TARGET_OPS

    return sorted(TARGET_OPS)


def list_fp32_ops(target_dtype="bfloat16"):
    """Parity: ``amp.list_fp32_ops``."""
    from .lists import FP32_OPS

    return sorted(FP32_OPS)


def convert_symbol(sym, target_dtype="bfloat16", target_dtype_ops=None,
                   fp32_ops=None, excluded_sym_names=(), **kwargs):
    """Offline AMP graph conversion (parity: ``amp.convert_symbol`` — the
    reference's nnvm ``low_precision_pass``): rewrite the Symbol DAG,
    inserting ``amp_cast`` nodes so TARGET_OPS consume the low-precision
    dtype and FP32_OPS consume float32.  ``amp_cast`` passes integer
    tensors through unchanged, so index inputs (Embedding/labels) are
    safe.  XLA folds back-to-back casts, so the inserted nodes cost
    nothing where dtypes already agree."""
    from ..symbol.symbol import Symbol, _Node
    from .lists import FP32_OPS, TARGET_OPS

    lp16 = set(target_dtype_ops) if target_dtype_ops is not None else set(TARGET_OPS)
    fp32 = set(fp32_ops) if fp32_ops is not None else set(FP32_OPS)
    excluded = set(excluded_sym_names)

    mapping = {}
    for node in sym._topo():
        if node.op is None:
            mapping[id(node)] = node
            continue
        new_inputs = [(mapping[id(n)], i) for n, i in node.inputs]
        cast_to = None
        if node.op in lp16 and node.name not in excluded:
            cast_to = target_dtype
        elif node.op in fp32 and node.name not in excluded:
            cast_to = "float32"
        if cast_to is not None:
            wrapped = []
            for j, (src, idx) in enumerate(new_inputs):
                cn = _Node("amp_cast", f"{node.name}_in{j}_amp_cast",
                           [(src, idx)], {"dtype": cast_to})
                wrapped.append((cn, 0))
            new_inputs = wrapped
        mapping[id(node)] = _Node(node.op, node.name, new_inputs,
                                  dict(node.attrs))
    return Symbol([(mapping[id(n)], i) for n, i in sym._outputs])


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  excluded_sym_names=(), **kwargs):
    """Parity: ``amp.convert_model`` — convert the graph with
    :func:`convert_symbol` and cast float parameters to the target dtype,
    EXCEPT parameters feeding FP32-listed ops directly (they stay fp32,
    as the reference's cast_optional_params=False default does)."""
    import numpy as _np

    from ..ndarray.ndarray import array as _arr
    from .lists import FP32_OPS

    from .lists import TARGET_OPS

    out_sym = convert_symbol(sym, target_dtype=target_dtype,
                             excluded_sym_names=excluded_sym_names, **kwargs)
    lp16 = (set(kwargs["target_dtype_ops"])
            if kwargs.get("target_dtype_ops") is not None else set(TARGET_OPS))
    fp32 = (set(kwargs["fp32_ops"])
            if kwargs.get("fp32_ops") is not None else set(FP32_OPS))
    excluded = set(excluded_sym_names)
    # cast ONLY parameters consumed by effective-lp16, non-excluded nodes
    # — and never one that ALSO feeds an fp32/excluded consumer
    castable, pinned = set(), set()
    for node in sym._topo():
        if node.op is None:
            continue
        eff_lp16 = node.op in lp16 and node.name not in excluded
        for src, _ in node.inputs:
            if src.op is None:
                (castable if eff_lp16 else pinned).add(src.name)
    castable -= pinned

    def cast_dict(d):
        out = {}
        for k, v in (d or {}).items():
            a = v.asnumpy() if hasattr(v, "asnumpy") else _np.asarray(v)
            if _np.issubdtype(a.dtype, _np.floating) and k in castable:
                a = a.astype(target_dtype)
            out[k] = _arr(a, dtype=str(a.dtype))
        return out

    return out_sym, cast_dict(arg_params), cast_dict(aux_params)
