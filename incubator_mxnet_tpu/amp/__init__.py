"""``mx.amp`` namespace (parity: [U:python/mxnet/contrib/amp/])."""
from .amp import (
    init,
    init_trainer,
    is_enabled,
    disable,
    disabled,
    scale_loss,
    unscale,
    convert_hybrid_block,
    convert_symbol,
    convert_model,
    list_lp16_ops,
    list_fp32_ops,
    LossScaler,
)
from . import lists

__all__ = [
    "init",
    "init_trainer",
    "is_enabled",
    "disable",
    "disabled",
    "scale_loss",
    "unscale",
    "convert_hybrid_block",
    "convert_symbol",
    "convert_model",
    "list_lp16_ops",
    "list_fp32_ops",
    "LossScaler",
    "lists",
]
