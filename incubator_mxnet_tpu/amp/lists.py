"""AMP op lists (parity: [U:python/mxnet/contrib/amp/lists/symbol_fp16.py]).

Three tiers, consulted by the dispatch hook in ndarray.invoke:
* TARGET_OPS  — run in the low-precision target dtype (the MXU ops where
  all the FLOPs are: matmul/conv/attention); float inputs are cast down.
* FP32_OPS    — numerically sensitive; float inputs are cast UP to fp32
  (softmax/exp/norm/loss heads).
* WIDEST_OPS  — multi-input ops that must agree on a dtype; inputs are
  cast to the widest float dtype present.
Everything else passes through untouched.

bf16 is the TPU-native target (fp16's loss-scaling machinery is kept for
API parity but bf16 needs no scaler — same exponent range as fp32).
"""

TARGET_OPS = {
    "FullyConnected", "fully_connected",
    "Convolution", "Deconvolution",
    "dot", "batch_dot", "linalg_gemm2",
    "fused_attention", "fused_qkv_attention", "fused_kv_attention",
    "RNN",
    # Embedding output feeds the transformer residual stream; emitting it
    # in the target dtype keeps that stream bf16 end-to-end (the norms
    # below preserve input dtype), killing the per-sublayer cast copies
    # the round-2 profile charged ~2-3% MFU to (docs/PERF_NOTES.md).
    "Embedding",
}

# softmax/log_softmax/softmin and the norms are NOT fp32-listed: the ops
# themselves compute exp/statistics in fp32 and return the input dtype
# (ops/nn.py), which is numerically equivalent to hook-casting but lets
# the converts fuse into the reduction instead of materializing copies.
FP32_OPS = {
    "SoftmaxOutput", "Softmax", "softmax_cross_entropy",
    "LinearRegressionOutput", "MAERegressionOutput", "LogisticRegressionOutput",
    "L2Normalization", "norm",
    "exp", "log", "log2", "log10", "log1p", "expm1",
    "sum", "mean", "prod", "nansum", "nanprod",
    "erf", "erfinv", "gamma", "gammaln",
    "smooth_l1", "MakeLoss",
    "power", "broadcast_power", "_power_scalar", "sqrt", "rsqrt", "square",
}

WIDEST_OPS = {
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "concat", "Concat", "stack", "add_n", "where",
}
