"""Weight initializers (parity: [U:python/mxnet/initializer.py]).

Same registry + ``InitDesc``-pattern-matching design as the reference: an
Initializer is called with a descriptor (carrying the parameter name and
attrs) and fills the NDArray; name suffixes (``_weight``, ``_bias``,
``_gamma``, ``_beta``, ``_mean``, ``_var``) route to the right rule.
"""
from __future__ import annotations

import json
import math

import numpy as _np

from . import random as _random
from .ndarray.ndarray import NDArray

__all__ = [
    "InitDesc",
    "Initializer",
    "Zero",
    "One",
    "Constant",
    "Uniform",
    "Normal",
    "Orthogonal",
    "Xavier",
    "MSRAPrelu",
    "Bilinear",
    "LSTMBias",
    "Load",
    "Mixed",
    "register",
    "create",
]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


_ALIASES = {"zeros": "zero", "ones": "one", "gaussian": "normal"}


def create(initializer, **kwargs):
    if initializer is None:
        return None
    if isinstance(initializer, Initializer):
        return initializer
    if isinstance(initializer, str):
        name = initializer.lower()
        name = _ALIASES.get(name, name)
        return _REGISTRY[name](**kwargs)
    raise TypeError(initializer)


class InitDesc(str):
    """Parameter descriptor: a str (the name) with optional attrs
    (parity: ``mx.init.InitDesc``)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init = desc.attrs.get("__init__", "")
        if init:
            create(json.loads(init)[0], **json.loads(init)[1])._init_weight(desc, arr)
            return
        name = str(desc)
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean") or name.endswith("mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var") or name.endswith("var"):
            self._init_one(desc, arr)
        else:
            self._init_default(desc, arr)

    # element rules -----------------------------------------------------
    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = _random.uniform(-self.scale, self.scale, arr.shape, dtype="float32").astype(arr.dtype)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = _random.normal(0, self.sigma, arr.shape, dtype="float32").astype(arr.dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        rows = arr.shape[0]
        cols = int(_np.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (rows, cols))
        else:
            tmp = _np.random.normal(0.0, 1.0, (rows, cols))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = _np.asarray(self.scale * q.reshape(arr.shape), dtype="float32")


@register
class Xavier(Initializer):
    """Parity: ``mx.init.Xavier`` (gaussian/uniform, avg/in/out factor)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError(f"Xavier requires ndim>=2, got {shape} for {desc}")
        if len(shape) > 2:
            hw_scale = float(_np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError(self.factor_type)
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = _random.uniform(-scale, scale, shape, dtype="float32").astype(arr.dtype)
        else:
            arr[:] = _random.normal(0, scale, shape, dtype="float32").astype(arr.dtype)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        weight = _np.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    """Forget-gate bias = forget_bias, others 0 (parity: mx.init.LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = _np.zeros(arr.shape, dtype="float32")
        num_hidden = arr.shape[0] // 4
        b[num_hidden : 2 * num_hidden] = self.forget_bias
        arr[:] = b

    _init_default = _init_weight
    _init_bias = _init_weight


@register
class Load:
    """Initialize from a dict of arrays (parity: ``mx.init.Load``)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = param
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        name = str(name)
        for key in (name, "arg:" + name, "aux:" + name):
            if key in self.param:
                src = self.param[key]
                arr[:] = src if not isinstance(src, NDArray) else src
                return
        if self.default_init is None:
            raise ValueError(f"no init for {name}")
        self.default_init(InitDesc(name), arr)


@register
class Mixed:
    """Pattern -> initializer routing (parity: ``mx.init.Mixed``)."""

    def __init__(self, patterns, initializers):
        import re

        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(str(name)):
                init(InitDesc(str(name)), arr)
                return
        raise ValueError(f"parameter {name} did not match any pattern")
