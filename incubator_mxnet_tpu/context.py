"""Device contexts: ``mx.cpu()``, ``mx.gpu()``, ``mx.tpu()``.

Parity target: [U:python/mxnet/context.py] (Context objects, ``with ctx:``
scoping, ``num_gpus()``) — extended with ``mx.tpu()`` as a first-class context
per the north-star.  A Context resolves lazily to a concrete ``jax.Device``;
``gpu``/``tpu`` fall back to whatever accelerator JAX exposes (on this image the
TPU chip may surface under an experimental platform name), and finally to CPU so
CPU-only test runs still work by swapping nothing.
"""
from __future__ import annotations

import threading

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus", "current_device"]

_DEVTYPE_TO_ID = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
_ID_TO_DEVTYPE = {v: k for k, v in _DEVTYPE_TO_ID.items()}

_tls = threading.local()


class Context:
    """A device context.  Parity: ``mxnet.context.Context``.

    Unlike the reference (where a Context selects a CUDA device and an engine
    worker pool), here a Context names a JAX device; XLA/PJRT owns streams,
    memory and scheduling.
    """

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in _DEVTYPE_TO_ID:
            raise ValueError(f"unknown device type {device_type!r}")
        self.device_type = device_type
        self.device_id = int(device_id)

    @property
    def device_typeid(self):
        return _DEVTYPE_TO_ID[self.device_type]

    # -- jax resolution ----------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device (lazily; cached per process)."""
        return _resolve_jax_device(self.device_type, self.device_id)

    # -- scoping -----------------------------------------------------------
    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self)
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return repr(self)

    def empty_cache(self):
        """Parity: Context.empty_cache (GPU pool release).  XLA owns pooling;
        this is a best-effort hint."""
        import gc

        gc.collect()


_device_cache = {}
_device_lock = threading.Lock()


def _accelerator_devices():
    import jax

    # process-LOCAL: under multi-process (dist kvstore / launch_local.py)
    # eager arrays must land on a device this process can address
    devs = jax.local_devices()
    return [d for d in devs if d.platform not in ("cpu",)] or []


def _resolve_jax_device(device_type, device_id):
    key = (device_type, device_id)
    with _device_lock:
        if key in _device_cache:
            return _device_cache[key]
    import jax

    dev = None
    if device_type == "cpu" or device_type.startswith("cpu_"):
        try:
            cpus = jax.local_devices(backend="cpu")
        except RuntimeError:
            cpus = [d for d in jax.local_devices() if d.platform == "cpu"]
        if cpus:
            dev = cpus[min(device_id, len(cpus) - 1)]
        else:
            # CPU platform absent (accelerator-only build): fall back to default
            dev = jax.local_devices()[0]
    else:
        accel = _accelerator_devices()
        if accel:
            dev = accel[device_id % len(accel)]
        else:
            dev = jax.local_devices()[min(device_id, len(jax.local_devices()) - 1)]
    with _device_lock:
        _device_cache[key] = dev
    return dev


def cpu(device_id=0):
    """Return a CPU context (parity: ``mx.cpu``)."""
    return Context("cpu", device_id)


def gpu(device_id=0):
    """Return an accelerator context.  On a TPU image this aliases the TPU so
    that unmodified ``ctx=mx.gpu()`` scripts run (north-star drop-in goal)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """Return a TPU context — the north-star first-class context."""
    return Context("tpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def current_context():
    """The innermost ``with ctx:`` context, else cpu/tpu default.

    Parity: ``mx.context.current_context`` — default is cpu() like the
    reference; accelerator placement is explicit (or via ``with mx.tpu():``).
    """
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1]
    return Context("cpu", 0)


def current_device():
    return current_context()


def num_gpus():
    """Number of accelerator devices visible (parity: ``mx.context.num_gpus``)."""
    return len(_accelerator_devices())


def num_tpus():
    return len(_accelerator_devices())
