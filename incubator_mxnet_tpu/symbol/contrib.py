"""``mx.sym.contrib`` namespace (parity: [U:python/mxnet/contrib/symbol.py]).

Same name resolution as ``nd.contrib``: ops registered with a
``contrib_``/``_contrib_`` prefix are reachable without it, and every
top-level op is also visible.  Control-flow ops (foreach/while_loop/cond)
take subgraph callables and live on the nd side only — under Symbol, use
the op graph directly.
"""
from __future__ import annotations

from ..ops import registry as _registry
from .symbol import _make_sym_op

_CACHE = {}


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    if name in _CACHE:
        return _CACHE[name]
    for candidate in (f"contrib_{name}", f"_contrib_{name}", name):
        try:
            _registry.get_op(candidate)
        except KeyError:
            continue
        w = _make_sym_op(candidate)
        _CACHE[name] = w
        return w
    raise AttributeError(f"sym.contrib has no op {name!r}")


def __dir__():
    names = set()
    for n in _registry.list_ops():
        names.add(n)
        for pre in ("contrib_", "_contrib_"):
            if n.startswith(pre):
                names.add(n[len(pre):])
    return sorted(names)
