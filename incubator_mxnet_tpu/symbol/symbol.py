"""``mx.sym`` — the symbolic front end.

Parity target: [U:python/mxnet/symbol/symbol.py] over the nnvm graph IR
([U:3rdparty/tvm/nnvm/include/nnvm/graph.h]).  TPU-native design: a Symbol
is a tiny pure-Python DAG over the SAME pure-function op registry that
``mx.nd`` dispatches to — there is no second operator implementation.
``bind``/``simple_bind`` lower the DAG to one ``jax.jit``-compiled XLA
program (the GraphExecutor analog, [U:src/executor/graph_executor.cc]);
memory planning, fusion and scheduling are XLA's.

Reference behaviors kept:
* auto-created parameter variables (``sym.FullyConnected(data, num_hidden=10,
  name='fc1')`` creates ``fc1_weight``/``fc1_bias``),
* ``list_arguments`` / ``list_auxiliary_states`` split by the
  moving-stat naming convention,
* ``infer_shape`` with partial inputs (param shapes derived from data
  shapes — the deferred-init path Module.bind depends on),
* JSON (de)serialization, ``__getitem__`` output selection, ``Group``.
"""
from __future__ import annotations

import functools
import inspect
import json
import threading

from ..ops.registry import get_op, list_ops
from .. import attribute as _attr_mod
from .. import name as _name_mod

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json", "zeros", "ones"]

_tls = threading.local()


def _name_counters():
    if not hasattr(_tls, "sym_counters"):
        _tls.sym_counters = {}
    return _tls.sym_counters


def _auto_name(hint):
    c = _name_counters()
    idx = c.get(hint, 0)
    c[hint] = idx + 1
    return f"{hint}{idx}"


def _reset_naming():  # test helper
    _tls.sym_counters = {}


def _dunder(k):
    """Normalize a user-attr key to single-dunder storage form.  Accepts
    both bare keys ('ctx_group') and reference-style pre-wrapped keys
    ('__ctx_group__') without double-wrapping."""
    if k.startswith("__") and k.endswith("__") and len(k) > 4:
        return k
    return f"__{k}__"


# Aux-state naming convention (parity: BatchNorm's auxiliary moving stats
# are not trainable arguments — [U:src/operator/nn/batch_norm.cc]).
AUX_SUFFIXES = ("moving_mean", "moving_var", "running_mean", "running_var")


def is_aux_name(name: str) -> bool:
    return name.endswith(AUX_SUFFIXES)


# Ops whose trailing tensor params are optional-but-autocreated unless a
# flag disables them.
_OPTIONAL_TENSOR = {
    "FullyConnected": {"bias": "no_bias"},
    "fully_connected": {"bias": "no_bias"},
    "Convolution": {"bias": "no_bias"},
    "Deconvolution": {"bias": "no_bias"},
    # flag None: optional tensor with no gate attr — simply omitted from the
    # node when the caller doesn't pass it (the op fn's own default applies,
    # e.g. RNN synthesizes zero initial states)
    "RNN": {"state": None, "state_cell": None},
}

# Ops whose SYMBOL carries multiple outputs (attrs -> count): the node's
# fn returns a tuple and each element is addressable as sym[i] / by the
# executor (MXNet's sym.split contract).  Ops not listed keep the default
# single primary output even when the fn returns a tuple (e.g. BatchNorm's
# (out, mean, var) — the extra entries are layer-internal).
def _truthy(v):
    """One acceptance set for stringly-typed boolean attrs (symbol JSON
    round-trips stringify them)."""
    return v in (True, 1, "1", "True", "true")


def _split_v2_outputs(a):
    spec = a.get("indices_or_sections", 1)
    return int(spec) if isinstance(spec, int) else len(tuple(spec)) + 1


_MULTI_OUTPUT = {
    "split": lambda a: int(a.get("num_outputs", 1)),
    "SliceChannel": lambda a: int(a.get("num_outputs", 1)),
    "split_v2": _split_v2_outputs,
    "RNN": lambda a: ((3 if a.get("mode", "lstm") == "lstm" else 2)
                      if _truthy(a.get("state_outputs")) else 1),
}

# Explicit tensor-input lists for ops where signature inspection is not
# enough.  Everything else: parameters without a default are tensor inputs
# — unless the caller passed them as non-Symbol kwargs (static attrs), see
# ``_apply_op``.
_TENSOR_PARAMS = {
    "BatchNorm": ("data", "gamma", "beta", "moving_mean", "moving_var"),
    "Dropout": ("data",),
    # shape/axis/reps/... are required static attrs, never tensor inputs
    "Reshape": ("data",),
    "reshape": ("data",),
    "expand_dims": ("data",),
    "tile": ("data",),
    "broadcast_to": ("data",),
    "slice_axis": ("data",),
    "slice": ("data",),
    "transpose": ("data",),
    "repeat": ("data",),
    "flip": ("data",),
    # num_outputs/depth are required static attrs, never tensor inputs
    "split": ("x",),
    "SliceChannel": ("x",),
    "one_hot": ("indices",),
}


@functools.lru_cache(maxsize=None)
def _flag_default(fn, flag):
    """Default value of an optional-tensor gate flag (e.g. no_bias) from
    the op's own signature (cached: graph-construction hot path)."""
    p = inspect.signature(fn).parameters.get(flag)
    return bool(p.default) if p is not None and p.default is not inspect.Parameter.empty else False


def _tensor_params(opname, fn):
    """Tensor-input parameter names, or None for variadic ops (``*args``
    like concat/add_n/stack, which take any number of tensor inputs)."""
    if opname in _TENSOR_PARAMS:
        return list(_TENSOR_PARAMS[opname])
    sig = inspect.signature(fn)
    names = []
    for p in sig.parameters.values():
        if p.kind == p.VAR_POSITIONAL:
            return None  # variadic
        if p.kind == p.VAR_KEYWORD:
            break
        if p.default is inspect.Parameter.empty:
            names.append(p.name)
        else:
            break
    extra = _OPTIONAL_TENSOR.get(opname)
    if extra:
        names.extend(extra)
    return names


class _Node:
    """One graph node: a Variable (op is None) or an op application."""

    __slots__ = ("op", "name", "inputs", "attrs")

    def __init__(self, op, name, inputs=(), attrs=None):
        self.op = op                  # registry op name, or None for Variable
        self.name = name
        self.inputs = list(inputs)    # list of (_Node, out_index)
        self.attrs = dict(attrs or {})  # static (non-tensor) op kwargs


class Symbol:
    """A handle to one or more graph outputs."""

    __slots__ = ("_outputs",)

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list of (_Node, int)

    # -- introspection --------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def _topo(self):
        seen, order = set(), []

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for n, _ in node.inputs:
                visit(n)
            order.append(node)

        for n, _ in self._outputs:
            visit(n)
        return order

    def list_arguments(self):
        return [n.name for n in self._topo()
                if n.op is None and not is_aux_name(n.name)]

    def list_auxiliary_states(self):
        return [n.name for n in self._topo()
                if n.op is None and is_aux_name(n.name)]

    def list_outputs(self):
        out = []
        for node, idx in self._outputs:
            if node.op is None:
                out.append(node.name)
            else:
                out.append(f"{node.name}_output" if idx == 0 else f"{node.name}_output{idx}")
        return out

    def list_inputs(self):
        return [n.name for n in self._topo() if n.op is None]

    def attr(self, key):
        """This symbol's attribute ``key`` (set via ``AttrScope``, the
        ``attr=`` kwarg of Variable, or lr_mult/wd_mult), or None.
        Parity: ``Symbol.attr`` ([U:python/mxnet/symbol/symbol.py])."""
        k = _dunder(key)
        if k in _TYPED_DUNDER:
            return None
        return self._outputs[0][0].attrs.get(k)

    def attr_dict(self):
        """``{node_name: {key: value}}`` over every node that carries
        user-level attributes (dunder-stored, string-valued; static op
        kwargs and internal typed attrs excluded).
        Parity: ``Symbol.attr_dict``."""
        out = {}
        for node in self._topo():
            d = {k[2:-2]: v for k, v in node.attrs.items()
                 if k.startswith("__") and k.endswith("__")
                 and k not in _TYPED_DUNDER}
            if d:
                out[node.name] = d
        return out

    def get_internals(self):
        """Symbol over every node's primary output (parity:
        ``Symbol.get_internals`` — used to tap intermediate features)."""
        return Symbol([(n, 0) for n in self._topo()])

    def __getitem__(self, idx):
        if isinstance(idx, str):
            names = self.list_outputs()
            idx = names.index(idx)
        return Symbol([self._outputs[idx]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __repr__(self):
        names = ", ".join(self.list_outputs())
        return f"<Symbol {names}>"

    # -- arithmetic sugar ------------------------------------------------
    def __add__(self, other):
        return _binary("broadcast_add", "_plus_scalar", self, other)

    def __radd__(self, other):
        return _binary("broadcast_add", "_plus_scalar", self, other)

    def __sub__(self, other):
        return _binary("broadcast_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _binary("broadcast_sub", "_rminus_scalar", self, other, swap=True)

    def __mul__(self, other):
        return _binary("broadcast_mul", "_mul_scalar", self, other)

    def __rmul__(self, other):
        return _binary("broadcast_mul", "_mul_scalar", self, other)

    def __truediv__(self, other):
        return _binary("broadcast_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _binary("broadcast_div", "_rdiv_scalar", self, other, swap=True)

    def __pow__(self, other):
        return _binary("broadcast_power", "_power_scalar", self, other)

    def __mod__(self, other):
        return _binary("broadcast_mod", "_mod_scalar", self, other)

    def __rmod__(self, other):
        return _binary("broadcast_mod", "_rmod_scalar", self, other, swap=True)

    # comparisons build graph nodes returning the reference's 1.0/0.0
    # float masks (NOT Python bools); non-numeric operands defer to
    # Python's protocol (`sym == None` stays False, not a graph node)
    @staticmethod
    def _comparable(other):
        import numbers

        return isinstance(other, (Symbol, numbers.Number))

    def __eq__(self, other):
        if not self._comparable(other):
            return NotImplemented
        return _binary("broadcast_equal", "_equal_scalar", self, other)

    def __ne__(self, other):
        if not self._comparable(other):
            return NotImplemented
        return _binary("broadcast_not_equal", "_not_equal_scalar", self, other)

    def __gt__(self, other):
        if not self._comparable(other):
            return NotImplemented
        return _binary("broadcast_greater", "_greater_scalar", self, other)

    def __ge__(self, other):
        if not self._comparable(other):
            return NotImplemented
        return _binary("broadcast_greater_equal", "_greater_equal_scalar",
                       self, other)

    def __lt__(self, other):
        if not self._comparable(other):
            return NotImplemented
        return _binary("broadcast_lesser", "_lesser_scalar", self, other)

    def __le__(self, other):
        if not self._comparable(other):
            return NotImplemented
        return _binary("broadcast_lesser_equal", "_lesser_equal_scalar",
                       self, other)

    __hash__ = object.__hash__  # __eq__ is symbolic; keep identity hashing

    def __bool__(self):
        # numpy-style: a graph node has no truth value — this also stops
        # `a in [b]` from silently matching via a truthy __eq__ Symbol
        # (parity: the reference raises NotImplementedForSymbol here)
        raise TypeError(
            "Symbol has no boolean value; comparisons build graph nodes. "
            "Use `is`/`is not` for identity, or evaluate the comparison.")

    def __neg__(self):
        return self * -1.0

    # -- graph ops -------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        from .infer import infer_shape
        return infer_shape(self, *args, **kwargs)

    def infer_shape_partial(self, *args, **kwargs):
        from .infer import infer_shape
        return infer_shape(self, *args, allow_unknown=True, **kwargs)

    def infer_type(self, **kwargs):
        from .infer import infer_type
        return infer_type(self, **kwargs)

    def eval(self, ctx=None, **kwargs):
        """Eager evaluation with NDArray bindings (parity: ``Symbol.eval``)."""
        from ..executor import Executor
        ex = Executor(self, ctx, args=kwargs, grad_req="null")
        return ex.forward(is_train=False)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from ..executor import Executor
        return Executor(self, ctx, args=args or {}, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None, **shapes):
        from ..executor import Executor
        return Executor.simple_bind(self, ctx, grad_req=grad_req,
                                    type_dict=type_dict, **shapes)

    # -- serialization ---------------------------------------------------
    def tojson(self, remove_amp_cast=True):
        sym = self
        if remove_amp_cast:
            # the reference contract: checkpoint symbols are cast-free —
            # bypass amp_cast nodes (rewire consumers to their input)
            def deref(entry):
                src, idx = entry
                while src.op == "amp_cast" and src.inputs:
                    src, idx = src.inputs[0]
                return (src, idx)

            mapping = {}
            for n in self._topo():
                if n.op == "amp_cast":
                    continue
                if n.op is None:
                    mapping[id(n)] = n
                    continue
                new_in = []
                for e in n.inputs:
                    src, idx = deref(e)
                    new_in.append((mapping.get(id(src), src), idx))
                mapping[id(n)] = _Node(n.op, n.name, new_in, dict(n.attrs))
            outs = []
            for e in self._outputs:
                src, idx = deref(e)
                outs.append((mapping.get(id(src), src), idx))
            sym = Symbol(outs)
        nodes = sym._topo()
        nid = {id(n): i for i, n in enumerate(nodes)}
        payload = {
            "nodes": [
                {
                    "op": n.op or "null",
                    "name": n.name,
                    "attrs": {k: _attr_str(v) for k, v in n.attrs.items()},
                    "inputs": [[nid[id(src)], idx] for src, idx in n.inputs],
                }
                for n in nodes
            ],
            "arg_nodes": [i for i, n in enumerate(nodes) if n.op is None],
            "heads": [[nid[id(n)], idx] for n, idx in sym._outputs],
            "attrs": {"mxnet_version": ["int", 10700], "format": "incubator_mxnet_tpu"},
        }
        return json.dumps(payload, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- composition ------------------------------------------------------
    def __call__(self, **kwargs):
        """Compose: replace free variables by other symbols (parity:
        ``Symbol.__call__``)."""
        mapping = {}
        for node in self._topo():
            if node.op is None and node.name in kwargs:
                repl = kwargs[node.name]
                mapping[id(node)] = repl._outputs[0]
        if not mapping:
            return self
        memo = {}

        def clone_entry(entry):
            src, idx = entry
            if id(src) in mapping:
                return mapping[id(src)]
            if id(src) in memo:
                return (memo[id(src)], idx)
            new_inputs = [clone_entry(e) for e in src.inputs]
            new = _Node(src.op, src.name, new_inputs, src.attrs)
            memo[id(src)] = new
            return (new, idx)

        return Symbol([clone_entry(e) for e in self._outputs])


def _attr_str(v):
    if isinstance(v, (list, tuple)):
        return json.dumps(list(v))
    return json.dumps(v) if not isinstance(v, str) else v


def _parse_attr(s):
    if not isinstance(s, str):
        return s
    try:
        return json.loads(s)
    except (ValueError, TypeError):
        return s


# Internal dunder attrs (graph metadata, hidden from attr()/attr_dict()).
# Only _PARSED_DUNDER carry typed values re-parsed on load; __dtype__/
# __init__ stay strings (an __init__ attr may itself be JSON — the
# Initializer.dumps() format — and must round-trip verbatim).  Every OTHER
# dunder key is a user-level attribute (AttrScope / Variable ``attr=``/
# ``lr_mult=``), string-typed by contract — left verbatim so e.g.
# lr_mult="0.1" round-trips as the string it was set to.
_TYPED_DUNDER = ("__input_names__", "__shape__", "__dtype__", "__init__")
_PARSED_DUNDER = ("__input_names__", "__shape__")


def _parse_loaded_attr(k, v):
    if k.startswith("__") and k.endswith("__") and k not in _PARSED_DUNDER:
        return v
    return _parse_attr(v)


def _binary(broadcast_op, scalar_op, lhs, rhs, swap=False):
    if isinstance(rhs, Symbol):
        return _apply_op(broadcast_op, (lhs, rhs), {})
    attrs = {"scalar": float(rhs)}
    return _apply_op(scalar_op, (lhs,), attrs)


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """Create a symbolic variable (parity: ``mx.sym.Variable``)."""
    attrs = {}
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    if init is not None:
        # the init consumer (initializer.Initializer.__call__) parses the
        # attr as Initializer.dumps() JSON — store that form
        attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    if lr_mult is not None:
        attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        attrs["__wd_mult__"] = str(wd_mult)
    if stype is not None:
        attrs["__stype__"] = str(stype)
    # reference contract: extra kwargs must be dunder-named attributes
    # (``sym.Variable('w', __ctx_group__='dev1')``); anything else raises
    # rather than being silently dropped
    for k, v in kwargs.items():
        if not (k.startswith("__") and k.endswith("__")):
            raise ValueError(
                f"Variable: unknown kwarg {k!r} — attribute kwargs must be "
                "dunder-named (e.g. __ctx_group__), or use attr={...}")
        if attr and k in attr:
            continue
        attr = dict(attr or {})
        attr[k] = v
    if attr:
        for k, v in attr.items():
            _attr_mod._check_key(k, "Variable attr")
            if not isinstance(v, str):
                raise ValueError(
                    "Variable attr values must be strings (same contract "
                    f"as AttrScope); got {type(v).__name__} for {k!r}")
            attrs[_dunder(k)] = v
    for k, v in _attr_mod.current().get().items():
        attrs.setdefault(_dunder(k), v)
    return Symbol([(_Node(None, name, attrs=attrs), 0)])


var = Variable


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def zeros(shape, dtype="float32", name=None, **kwargs):
    return _apply_op("_sym_zeros", (), {"shape": tuple(shape) if not isinstance(shape, int) else (shape,), "dtype": dtype}, name=name, hint="_zeros")


def ones(shape, dtype="float32", name=None, **kwargs):
    return _apply_op("_sym_ones", (), {"shape": tuple(shape) if not isinstance(shape, int) else (shape,), "dtype": dtype}, name=name, hint="_ones")


def _apply_op(opname, args, kwargs, name=None, hint=None):
    """Build an op node: positional/keyword Symbols are tensor inputs,
    everything else static attrs; missing tensor params are auto-created as
    Variables named ``<node>_<param>``."""
    op = get_op(opname)
    tnames = _tensor_params(opname, op.fn)
    name = _name_mod.current().get(name, hint or opname.lower().lstrip("_"))
    scope_attrs = _attr_mod.current().get()

    if tnames is None:  # variadic op: all positional Symbols are inputs
        inputs, input_names = [], []
        for i, a in enumerate(args):
            if not isinstance(a, Symbol):
                raise TypeError(f"{opname}: positional arg {i} must be a Symbol, got {type(a)}")
            entry = a._outputs
            if len(entry) != 1:
                raise ValueError(f"{opname}: input {i} must be a single-output symbol")
            inputs.append(entry[0])
            input_names.append(f"arg{i}")
        attrs = {k: v for k, v in kwargs.items() if not isinstance(v, Symbol)}
        node = _Node(opname, name, inputs, attrs)
        node.attrs["__input_names__"] = input_names
        for k, v in scope_attrs.items():
            node.attrs.setdefault(_dunder(k), v)
        return Symbol([(node, 0)])

    provided = {}
    for i, a in enumerate(args):
        if isinstance(a, Symbol):
            if i >= len(tnames):
                raise ValueError(f"{opname}: too many tensor inputs")
            provided[tnames[i]] = a
        else:
            raise TypeError(f"{opname}: positional arg {i} must be a Symbol, got {type(a)}")
    attrs = {}
    for k, v in kwargs.items():
        if isinstance(v, Symbol):
            provided[k] = v
        else:
            attrs[k] = v

    inputs, input_names = [], []
    optional = _OPTIONAL_TENSOR.get(opname, {})
    skipped_optional = None
    for t in tnames:
        if t in attrs:
            # supplied as a non-Symbol kwarg → it is a static attr
            # (e.g. reshape(data, shape=(4, 2))), not a tensor input;
            # do NOT auto-create a phantom variable for it.
            continue
        if t in provided:
            if skipped_optional is not None:
                # the executor passes inputs positionally: providing a
                # tensor AFTER an omitted flagless-optional one would bind
                # it to the wrong parameter (e.g. RNN state_cell→state)
                raise ValueError(
                    f"{opname}: {t} provided but earlier optional input "
                    f"{skipped_optional!r} omitted; pass both or neither")
            entry = provided[t]._outputs
            if len(entry) != 1:
                raise ValueError(f"{opname}: input {t} must be a single-output symbol")
            inputs.append(entry[0])
            input_names.append(t)
        else:
            if t in optional:
                flag = optional[t]
                if flag is None:
                    # flagless optional tensor: omitted when not provided
                    skipped_optional = t
                    continue
                if attrs.get(flag, _flag_default(op.fn, flag)):
                    # e.g. no_bias=True — including by the OP'S OWN default
                    # (Deconvolution defaults no_bias=true in the reference,
                    # Convolution false; the signature is the source of truth)
                    continue
            # missing inputs auto-create variables, incl. the MXNet idiom
            # sym.SoftmaxOutput(data, name='softmax') → 'softmax_label';
            # they inherit the active AttrScope (the reference's main use
            # case: per-parameter lr_mult/ctx_group on auto-created weights)
            auto_attrs = {_dunder(k): v for k, v in scope_attrs.items()}
            inputs.append((_Node(None, f"{name}_{t}", attrs=auto_attrs), 0))
            input_names.append(t)

    # pass skipped-optional info through attrs so the executor calls the op
    # with the right arity
    node = _Node(opname, name, inputs, attrs)
    node.attrs["__input_names__"] = input_names
    for k, v in scope_attrs.items():
        node.attrs.setdefault(_dunder(k), v)
    n_out = _MULTI_OUTPUT.get(opname, lambda a: 1)(attrs)
    return Symbol([(node, i) for i in range(n_out)])


def _make_sym_op(opname):
    def sym_op(*args, name=None, **kwargs):
        return _apply_op(opname, args, kwargs, name=name)

    sym_op.__name__ = opname
    sym_op.__qualname__ = f"sym.{opname}"
    op = get_op(opname)
    sym_op.__doc__ = op.doc
    return sym_op


# Fluent methods (parity: the reference Symbol's op-backed methods —
# `sym.reshape(...)`, `sym.sum(axis=1)`, ... mirror NDArray's so ported
# scripts keep their chained style).  Bound lazily AFTER the op registry
# is populated; existing class attributes are never overridden.
_FLUENT_METHODS = (
    "reshape", "reshape_like", "flatten", "squeeze", "expand_dims", "tile",
    "pad", "repeat", "flip", "transpose", "swapaxes", "broadcast_to",
    "broadcast_like", "split", "slice", "slice_axis", "slice_like", "take",
    "pick", "one_hot", "sum", "mean", "max", "min", "prod", "nansum",
    "nanprod", "argmax", "argmin", "norm", "clip", "abs", "exp", "log",
    "sqrt", "square", "sign", "round", "floor", "ceil", "sigmoid", "tanh",
    "relu", "softmax", "log_softmax", "sort", "argsort", "topk", "diag",
    "zeros_like", "ones_like",
)


def _make_fluent(opname):
    fn = get_op(opname).fn
    tps = _tensor_params(opname, fn) or ()
    # non-tensor params: everything after the tensor-input slots in
    # signature order (BY POSITION — the _TENSOR_PARAMS table's names are
    # descriptive, not guaranteed to match the fn's parameter spelling).
    # These are the targets for positional attrs, so reference chained
    # forms like sym.reshape((0, -1)) / sym.split(3) work exactly like
    # their NDArray twins.
    _named = [p.name for p in inspect.signature(fn).parameters.values()
              if p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)]
    attr_names = _named[len(tps):]

    # ops whose first attr is a tuple the reference lets callers splat:
    # x.reshape(0, -1), x.transpose(1, 0), x.tile(2, 3) (NDArray.reshape
    # accepts the same splat)
    splat = opname in ("reshape", "transpose", "tile", "broadcast_to")

    def method(self, *args, name=None, **kwargs):
        sym_args = [self]
        rest = list(args)
        while rest and isinstance(rest[0], Symbol):
            sym_args.append(rest.pop(0))
        if (splat and rest and attr_names
                and all(isinstance(v, int) for v in rest)
                and attr_names[0] not in kwargs):
            rest = [tuple(rest)]
        for i, v in enumerate(rest):
            if i >= len(attr_names):
                raise TypeError(f"{opname}: too many positional arguments")
            if attr_names[i] in kwargs:
                raise TypeError(
                    f"{opname}: got multiple values for {attr_names[i]!r}")
            kwargs[attr_names[i]] = v
        return _apply_op(opname, tuple(sym_args), kwargs, name=name)

    method.__name__ = opname
    method.__qualname__ = f"Symbol.{opname}"
    method.__doc__ = f"Fluent form of ``sym.{opname}`` applied to this symbol."
    return method


def _bind_fluent_methods():
    from ..ops.registry import list_ops

    ops = set(list_ops())
    for n in _FLUENT_METHODS:
        if n in ops and not hasattr(Symbol, n):
            setattr(Symbol, n, _make_fluent(n))
    if not hasattr(Symbol, "astype"):
        def astype(self, dtype, name=None):
            return _apply_op("cast", (self,), {"dtype": dtype}, name=name)
        Symbol.astype = astype


def load_json(json_str):
    payload = json.loads(json_str)
    nodes = []
    for spec in payload["nodes"]:
        attrs = {k: _parse_loaded_attr(k, v) for k, v in spec.get("attrs", {}).items()}
        op = spec["op"]
        node = _Node(None if op == "null" else op, spec["name"], attrs=attrs)
        nodes.append((node, spec.get("inputs", [])))
    for node, inputs in nodes:
        node.inputs = [(nodes[nid][0], idx) for nid, idx in inputs]
    heads = payload["heads"]
    return Symbol([(nodes[nid][0], idx) for nid, idx in heads])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())
