"""``mx.sym`` / ``mx.symbol`` namespace.

Op wrappers are synthesized on attribute access from the same pure-function
registry as ``mx.nd`` (parity: the reference generates both namespaces from
the one C op registry — [U:python/mxnet/symbol/register.py])."""
from __future__ import annotations

from ..ops import registry as _registry
from .symbol import (
    Symbol,
    Variable,
    var,
    Group,
    load,
    load_json,
    zeros,
    ones,
    _make_sym_op,
    _bind_fluent_methods,
)

_bind_fluent_methods()  # registry is fully populated by the ..ops import

from . import contrib  # noqa: E402  (mx.sym.contrib namespace)

__all__ = [
    "Symbol",
    "Variable",
    "var",
    "Group",
    "load",
    "load_json",
    "zeros",
    "ones",
]


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    try:
        _registry.get_op(name)
    except KeyError:
        raise AttributeError(f"symbol op {name!r} is not registered") from None
    w = _make_sym_op(name)
    globals()[name] = w
    return w


def __dir__():
    return sorted(set(list(globals()) + _registry.list_ops()))
