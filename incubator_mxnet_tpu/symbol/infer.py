"""Shape/type inference over a Symbol graph.

Parity target: the nnvm InferShape/InferType passes
([U:3rdparty/tvm/nnvm/src/pass/infer_shape.cc],
[U:src/executor/infer_graph_attr_pass.cc]).  TPU-native twist: per-op
output shapes come from ``jax.eval_shape`` of the SAME pure function that
computes — there is no hand-maintained FInferShape table.  What does need
hand rules is the *backward* direction the reference gets from its
bidirectional pass: inferring parameter shapes (weight/bias/gamma/...)
from the data shape plus op attrs.  Those rules live in
``PARAM_SHAPE_RULES`` below and cover the parameterized ops.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp

from ..base import _as_np_dtype
from ..ops.registry import get_op
from ..ops.rnn_ops import rnn_param_size as _rnn_param_size
from .symbol import is_aux_name

__all__ = ["infer_shape", "infer_type", "PARAM_SHAPE_RULES"]


def _conv_weight(data_shape, attrs):
    num_filter = attrs.get("num_filter", 0)
    kernel = tuple(attrs.get("kernel", ()))
    groups = attrs.get("num_group", 1)
    cin = data_shape[1] // groups
    return (num_filter, cin) + kernel


def _deconv_weight(data_shape, attrs):
    num_filter = attrs.get("num_filter", 0)
    kernel = tuple(attrs.get("kernel", ()))
    groups = attrs.get("num_group", 1)
    return (data_shape[1], num_filter // groups) + kernel


def _fc_weight(data_shape, attrs):
    num_hidden = attrs.get("num_hidden", 0)
    if attrs.get("flatten", True):
        in_units = int(_np.prod(data_shape[1:]))
    else:
        in_units = data_shape[-1]
    return (num_hidden, in_units)


def _channel(data_shape, attrs):
    axis = attrs.get("axis", 1) % len(data_shape)
    return (data_shape[axis],)


def _last_dim(data_shape, attrs):
    axis = attrs.get("axis", -1) % len(data_shape)
    return (data_shape[axis],)


# op → {param_name: rule(data_shape, attrs) -> shape}
PARAM_SHAPE_RULES = {
    "FullyConnected": {
        "weight": _fc_weight,
        "bias": lambda d, a: (a.get("num_hidden", 0),),
    },
    "Convolution": {
        "weight": _conv_weight,
        "bias": lambda d, a: (a.get("num_filter", 0),),
    },
    "Deconvolution": {
        "weight": _deconv_weight,
        "bias": lambda d, a: (a.get("num_filter", 0),),
    },
    "BatchNorm": {
        "gamma": _channel, "beta": _channel,
        "moving_mean": _channel, "moving_var": _channel,
    },
    "LayerNorm": {"gamma": _last_dim, "beta": _last_dim},
    "RMSNorm": {"gamma": _last_dim},
    "InstanceNorm": {"gamma": _channel, "beta": _channel},
    "GroupNorm": {"gamma": _channel, "beta": _channel},
    "Embedding": {
        "weight": lambda d, a: (a.get("input_dim", 0), a.get("output_dim", 0)),
    },
    "RNN": {
        "parameters": lambda d, a: (_rnn_param_size(
            a.get("mode", "lstm"), d[2], a.get("state_size", 0),
            a.get("num_layers", 1), a.get("bidirectional", False)),),
        "state": lambda d, a: (
            int(a.get("num_layers", 1)) * (2 if a.get("bidirectional") else 1),
            d[1], int(a.get("state_size", 0))),
        "state_cell": lambda d, a: (
            int(a.get("num_layers", 1)) * (2 if a.get("bidirectional") else 1),
            d[1], int(a.get("state_size", 0))),
    },
    # loss heads: label shape from data shape (the bidirectional-inference
    # direction the reference's InferShape pass provides — lets predict-
    # style binds omit label shapes)
    "SoftmaxOutput": {
        "label": lambda d, a: ((d[0],) + d[2:]) if a.get("multi_output") else d[:-1],
    },
    "LinearRegressionOutput": {"label": lambda d, a: d},
    "MAERegressionOutput": {"label": lambda d, a: d},
    "LogisticRegressionOutput": {"label": lambda d, a: d},
}
PARAM_SHAPE_RULES["fully_connected"] = PARAM_SHAPE_RULES["FullyConnected"]
PARAM_SHAPE_RULES["Softmax"] = PARAM_SHAPE_RULES["SoftmaxOutput"]


def _clean_attrs(attrs):
    return {k: v for k, v in attrs.items() if not k.startswith("__")}


_INT_DTYPES = ("int32", "int64", "uint8", "int8", "bool")


_CAST_WRAPPERS = ("amp_cast", "cast", "Cast")


def _behind_casts(src):
    """Deref a chain of shape-preserving cast wrappers to the underlying
    node (AMP-converted graphs interpose amp_cast between parameter
    variables and their consumers)."""
    while src.op in _CAST_WRAPPERS and src.inputs:
        src = src.inputs[0][0]
    return src


def _graph_infer(symbol, shape_hints, dtype_hints, allow_unknown=False):
    """Topo sweeps to fixpoint (cast wrappers over deferred-init variables
    need a second pass: the consumer's rule resolves the var, then the
    wrapper).  Returns (node → tuple-of-ShapeDtypeStruct,
    var_name → ShapeDtypeStruct)."""
    values = {}   # id(node) -> tuple of ShapeDtypeStruct
    varspec = {}  # var name -> ShapeDtypeStruct
    topo = symbol._topo()

    for node in topo:
        if node.op is None:
            shape = shape_hints.get(node.name, node.attrs.get("__shape__"))
            dtype = dtype_hints.get(node.name, node.attrs.get("__dtype__", "float32"))
            if shape is None:
                values[id(node)] = None  # unknown until a consumer rule fires
            else:
                spec = jax.ShapeDtypeStruct(tuple(shape), _as_np_dtype(dtype))
                values[id(node)] = (spec,)
                varspec[node.name] = spec

    progress = True
    while progress:
        progress = False
        for node in topo:
            if node.op is None or values.get(id(node)) is not None:
                continue

            rules = PARAM_SHAPE_RULES.get(node.op, {})
            input_names = node.attrs.get("__input_names__") or []
            data_spec = None
            if node.inputs:
                first = values.get(id(node.inputs[0][0]))
                if first is not None:
                    data_spec = first[node.inputs[0][1]]
            # derive unknown parameter-variable shapes from the data shape
            # (through any cast wrappers an AMP-converted graph inserted)
            for (src, idx), pname in zip(node.inputs, input_names):
                tgt = _behind_casts(src)
                if values.get(id(tgt)) is None and tgt.op is None:
                    rule = rules.get(pname)
                    if rule is not None and data_spec is not None:
                        shape = tuple(rule(data_spec.shape, node.attrs))
                        dtype = dtype_hints.get(
                            tgt.name,
                            tgt.attrs.get("__dtype__", str(data_spec.dtype)))
                        spec = jax.ShapeDtypeStruct(shape, _as_np_dtype(dtype))
                        values[id(tgt)] = (spec,)
                        varspec[tgt.name] = spec
                        progress = True

            in_specs = []
            missing = False
            for src, idx in node.inputs:
                v = values.get(id(src))
                if v is None:
                    missing = True
                    break
                in_specs.append(v[idx])
            if missing:
                continue  # maybe resolvable next sweep

            op = get_op(node.op)
            attrs = _clean_attrs(node.attrs)
            out = jax.eval_shape(lambda *a: op.fn(*a, **attrs), *in_specs)
            values[id(node)] = (tuple(out) if isinstance(out, (list, tuple))
                                else (out,))
            progress = True

    if not allow_unknown:
        stuck = [n for n in topo
                 if n.op is not None and values.get(id(n)) is None]
        if stuck:
            # report a non-wrapper node (an amp_cast is not actionable —
            # its consumer and the underlying variable are), and name the
            # underlying VARIABLES behind any cast chain
            node = next((n for n in stuck if n.op not in _CAST_WRAPPERS),
                        stuck[0])
            unknown = sorted({_behind_casts(s).name
                              for s, _ in node.inputs
                              if values.get(id(s)) is None})
            raise ValueError(
                f"infer_shape: cannot infer inputs {unknown} of node "
                f"{node.name!r} (op {node.op}); provide their shapes")
    return values, varspec


def infer_shape(symbol, *args, allow_unknown=False, **kwargs):
    """Returns (arg_shapes, out_shapes, aux_shapes) in the order of
    ``list_arguments()`` / ``list_outputs()`` / ``list_auxiliary_states()``
    (parity: ``Symbol.infer_shape``)."""
    if args:
        names = symbol.list_arguments()
        for name, shape in zip(names, args):
            if shape is not None:
                kwargs.setdefault(name, shape)
    shape_hints = {k: tuple(v) for k, v in kwargs.items() if v is not None}
    dtype_hints = {k: "int32" for k in shape_hints
                   if k.endswith(("label", "idx", "indices", "token_ids"))}
    values, varspec = _graph_infer(symbol, shape_hints, dtype_hints,
                                   allow_unknown=allow_unknown)

    def var_shape(name):
        spec = varspec.get(name)
        return tuple(spec.shape) if spec is not None else None

    arg_shapes = [var_shape(n) for n in symbol.list_arguments()]
    aux_shapes = [var_shape(n) for n in symbol.list_auxiliary_states()]
    out_shapes = []
    for node, idx in symbol._outputs:
        v = values.get(id(node))
        out_shapes.append(tuple(v[idx].shape) if v is not None else None)
    return arg_shapes, out_shapes, aux_shapes


def infer_type(symbol, **kwargs):
    """(arg_dtypes, out_dtypes, aux_dtypes); needs shapes only when the
    graph has no variable shape annotations."""
    shape_hints, dtype_hints = {}, {}
    for k, v in kwargs.items():
        if v is None:
            continue
        if isinstance(v, (tuple, list)):
            shape_hints[k] = tuple(v)
        else:
            dtype_hints[k] = str(_np.dtype(v)) if not isinstance(v, str) else v
    for node in symbol._topo():
        if node.op is None and "__shape__" in node.attrs:
            shape_hints.setdefault(node.name, tuple(node.attrs["__shape__"]))
    values, varspec = _graph_infer(symbol, shape_hints, dtype_hints,
                                   allow_unknown=True)

    def var_dtype(name):
        spec = varspec.get(name)
        return _np.dtype(spec.dtype) if spec is not None else None

    arg_dtypes = [var_dtype(n) for n in symbol.list_arguments()]
    aux_dtypes = [var_dtype(n) for n in symbol.list_auxiliary_states()]
    out_dtypes = []
    for node, idx in symbol._outputs:
        v = values.get(id(node))
        out_dtypes.append(_np.dtype(v[idx].dtype) if v is not None else None)
    return arg_dtypes, out_dtypes, aux_dtypes
