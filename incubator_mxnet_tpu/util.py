"""Misc util shims (parity: [U:python/mxnet/util.py]).

The reference toggles legacy-vs-numpy shape/array semantics process-wide
(``np_shape``/``np_array``); this framework is numpy-semantics natively (jax
is), so the toggles are tracked flags that always behave as enabled for
computation — kept so reference scripts calling ``mx.npx.set_np()`` etc.
run unchanged.
"""
from __future__ import annotations

import contextlib
import functools

_np_shape = True
_np_array = True


def is_np_shape():
    return _np_shape


def is_np_array():
    return _np_array


@contextlib.contextmanager
def np_shape(active=True):
    global _np_shape
    prev, _np_shape = _np_shape, active
    try:
        yield
    finally:
        _np_shape = prev


@contextlib.contextmanager
def np_array(active=True):
    global _np_array
    prev, _np_array = _np_array, active
    try:
        yield
    finally:
        _np_array = prev


def set_np(shape=True, array=True):
    global _np_shape, _np_array
    _np_shape, _np_array = shape, array


def reset_np():
    set_np(True, True)


def use_np(func):
    @functools.wraps(func)
    def wrapper(*a, **k):
        return func(*a, **k)

    return wrapper
