"""Misc util shims (parity: [U:python/mxnet/util.py]).

The reference toggles legacy-vs-numpy shape/array semantics process-wide
(``np_shape``/``np_array``); this framework is numpy-semantics natively (jax
is), so the toggles are tracked flags that always behave as enabled for
computation — kept so reference scripts calling ``mx.npx.set_np()`` etc.
run unchanged.
"""
from __future__ import annotations

import contextlib
import functools

_np_shape = True
_np_array = True


def is_np_shape():
    return _np_shape


def is_np_array():
    return _np_array


@contextlib.contextmanager
def np_shape(active=True):
    global _np_shape
    prev, _np_shape = _np_shape, active
    try:
        yield
    finally:
        _np_shape = prev


@contextlib.contextmanager
def np_array(active=True):
    global _np_array
    prev, _np_array = _np_array, active
    try:
        yield
    finally:
        _np_array = prev


def set_np(shape=True, array=True):
    global _np_shape, _np_array
    _np_shape, _np_array = shape, array


def reset_np():
    set_np(True, True)


def use_np(func):
    @functools.wraps(func)
    def wrapper(*a, **k):
        return func(*a, **k)

    return wrapper


def resolve_platform(x=None):
    """The platform a dispatch will actually execute on: a concrete
    input's device wins (eager op on a CPU-placed array while the default
    backend is tpu, e.g. model init under ``jax.default_device(cpu)``);
    then an active ``jax_default_device`` override; then the default
    backend.  Shared by ops/attention.py and rtc.py so the two dispatch
    disciplines cannot drift."""
    import jax

    platform = None
    if x is not None and not isinstance(x, jax.core.Tracer):
        try:
            platform = next(iter(x.devices())).platform
        except Exception:
            platform = None
    if platform is None:
        dd = getattr(jax.config, "jax_default_device", None)
        platform = getattr(dd, "platform", None) or jax.default_backend()
    return platform


def makedirs(d):
    """Recursive mkdir that tolerates existing dirs (parity:
    ``mx.util.makedirs`` — pre-exist_ok-era helper)."""
    import os

    os.makedirs(os.path.expanduser(d), exist_ok=True)


def getenv(name):
    """Read an MXNET_* env var through the C runtime in the reference;
    plain os.environ here (parity: ``mx.util.getenv``)."""
    import os

    return os.environ.get(name)


def setenv(name, value):
    """Parity: ``mx.util.setenv`` (process-wide)."""
    import os

    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = str(value)


def get_gpu_count():
    """Parity: ``mx.util.get_gpu_count`` — accelerator count on this
    host (TPU chips play the gpu role)."""
    from . import context

    return context.num_tpus() or 0


def get_gpu_memory(dev_id=0):
    """Parity: ``mx.util.get_gpu_memory`` -> (free, total) bytes for the
    accelerator, via the shared ``profiler.device_memory_stats`` probe
    (one memory_stats() parse rule for the whole repo)."""
    import jax

    from . import profiler

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if not devs:
        raise RuntimeError("no accelerator device visible")
    d = devs[min(dev_id, len(devs) - 1)]
    stats = profiler.device_memory_stats([d]).get(str(d))
    if not stats:
        return (0, 0)
    total = stats["bytes_limit"]
    used = stats["bytes_in_use"]
    return (total - used, total)
