"""Misc util shims (parity: [U:python/mxnet/util.py]).

The reference toggles legacy-vs-numpy shape/array semantics process-wide
(``np_shape``/``np_array``); this framework is numpy-semantics natively (jax
is), so the toggles are tracked flags that always behave as enabled for
computation — kept so reference scripts calling ``mx.npx.set_np()`` etc.
run unchanged.
"""
from __future__ import annotations

import contextlib
import functools

_np_shape = True
_np_array = True


def is_np_shape():
    return _np_shape


def is_np_array():
    return _np_array


@contextlib.contextmanager
def np_shape(active=True):
    global _np_shape
    prev, _np_shape = _np_shape, active
    try:
        yield
    finally:
        _np_shape = prev


@contextlib.contextmanager
def np_array(active=True):
    global _np_array
    prev, _np_array = _np_array, active
    try:
        yield
    finally:
        _np_array = prev


def set_np(shape=True, array=True):
    global _np_shape, _np_array
    _np_shape, _np_array = shape, array


def reset_np():
    set_np(True, True)


def use_np(func):
    @functools.wraps(func)
    def wrapper(*a, **k):
        return func(*a, **k)

    return wrapper


def resolve_platform(x=None):
    """The platform a dispatch will actually execute on: a concrete
    input's device wins (eager op on a CPU-placed array while the default
    backend is tpu, e.g. model init under ``jax.default_device(cpu)``);
    then an active ``jax_default_device`` override; then the default
    backend.  Shared by ops/attention.py and rtc.py so the two dispatch
    disciplines cannot drift."""
    import jax

    platform = None
    if x is not None and not isinstance(x, jax.core.Tracer):
        try:
            platform = next(iter(x.devices())).platform
        except Exception:
            platform = None
    if platform is None:
        dd = getattr(jax.config, "jax_default_device", None)
        platform = getattr(dd, "platform", None) or jax.default_backend()
    return platform
