"""``mx.predictor`` — standalone inference API.

Parity: [U:src/c_api/c_predict_api.cc] (``MXPredCreate`` / SetInput /
Forward / GetOutput) — the embedding-oriented predict surface that loads a
``-symbol.json`` + ``.params`` checkpoint and runs forward-only.  Here the
bound program is one ``jax.jit``-compiled XLA executable (donated inputs,
no autograd machinery), the deployment analog of ``Block.export``.

Since ISSUE 8 the predictor is the binding substrate of the serving tier
(``incubator_mxnet_tpu/serving``): parameters are placed on device ONCE
and **shared by object across every shape bind** — ``reshape(new_shapes)``
swaps the active input-shape signature, reusing both the parameter arrays
and any executor (+ its jit cache) previously bound for that signature.
A shape-bucketed server therefore holds one copy of the weights no matter
how many (batch, length) buckets it serves, and switching buckets costs a
dict lookup, not a device copy or a recompile.

A ``Predictor`` is NOT thread-safe (``reshape``/``set_input``/``forward``
mutate the active executor): concurrent callers must serialize, which is
exactly what ``serving.InferenceServer``'s single scheduler thread does.
"""
from __future__ import annotations

import time as _time

import numpy as _np

__all__ = ["Predictor", "StatefulExecutor", "load_checkpoint"]


def _nd_store_nbytes(nd):
    """Footprint of one stored NDArray — the shared shape-x-dtype rule
    (``profiler.array_nbytes``; never touches the raw buffer)."""
    from . import profiler

    return profiler.array_nbytes(nd)


def _release_predictor_memory(cell):
    """weakref.finalize hook for a predictor's ledger share (mutable cell:
    late-bound zero-filled parameters grow it after construction)."""
    from . import profiler

    profiler.track_memory("predictor.params", "params").free(cell[0])
    cell[0] = 0


def _split_param_key(name):
    """Split a checkpoint key into (kind, bare_name).

    Only the literal ``arg:`` / ``aux:`` prefixes of the reference
    checkpoint format are stripped; any other colon is part of the
    parameter's own name (the old ``split(":", 1)`` mangled e.g. a scoped
    ``encoder:weight`` into ``weight``).  ``kind`` is ``"arg"``, ``"aux"``
    or ``None`` (unprefixed — classified against the symbol's
    argument/aux lists by the caller), so prefixed and unprefixed
    checkpoints load identically."""
    if name.startswith("arg:"):
        return "arg", name[4:]
    if name.startswith("aux:"):
        return "aux", name[4:]
    return None, name


def load_checkpoint(symbol_file, param_file):
    """Load a (symbol, params) checkpoint into ``(symbol, arg_params,
    aux_params)`` NDArray dicts with the ``arg:``/``aux:`` prefixes
    resolved (unprefixed keys are classified against the symbol's
    argument/aux lists — prefixed and bare checkpoints load identically).
    The :class:`Predictor` constructor and the serving tier's AMP path
    (``amp.convert_model`` wants the split dicts) share this loader."""
    from . import symbol as sym_mod
    from .ndarray import utils as nd_utils
    from .ndarray.ndarray import NDArray, array

    sym = sym_mod.load(symbol_file) if isinstance(symbol_file, str) \
        else symbol_file
    loaded = nd_utils.load(param_file) if isinstance(param_file, str) \
        else param_file
    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    args, auxs = {}, {}
    for k, v in loaded.items():
        kind, name = _split_param_key(k)
        if kind is None:
            kind = "aux" if name in aux_names and name not in arg_names \
                else "arg"
        nd = v if isinstance(v, NDArray) else array(_np.asarray(v))
        (auxs if kind == "aux" else args)[name] = nd
    return sym, args, auxs


class StatefulExecutor:
    """Bind pure step programs over a shared, donated device state.

    The decode loop of the generation tier (``serving/generation.py``) is
    a *stateful* workload: every iteration consumes the KV cache buffers
    and produces their successors.  A plain ``Predictor`` models the
    opposite contract (stateless forward over immutable parameters), so
    this is the second binding substrate: named jitted programs that all
    read and return one ``{name: array}`` state dict, with the state
    donated on every dispatch — steady-state HBM holds exactly one copy
    of the cache, and mutation is buffer aliasing, not allocation.

    Programs are plain functions ``fn(state, inputs) -> (outputs,
    new_state)`` where ``new_state`` must carry every state key (pass
    unchanged entries straight through — XLA aliases them back onto the
    donated input buffers).  ``run()`` rebinds the state BEFORE reporting
    a detected compile, so a raise-mode compile guard can never leave the
    executor pointing at deleted buffers (the PR 9 ``group_apply``
    discipline).

    Not thread-safe — same contract as :class:`Predictor`: the single
    scheduler thread owns all dispatches.
    """

    def __init__(self, state, name="stateful", compile_site=None):
        self._state = dict(state or {})
        self._name = str(name)
        self._site = compile_site or f"executor.{self._name}"
        self._programs = {}
        self._calls = {}
        self._compiles = 0

    @property
    def state(self):
        """The live state dict (read-only by convention; entries are the
        donated/rebound jax arrays)."""
        return self._state

    def add_program(self, name, fn, donate_state=True):
        """Register ``fn(state, inputs) -> (outputs, new_state)`` under
        ``name``.  ``donate_state`` (default) donates the whole state
        pytree on every call."""
        import jax

        if name in self._programs:
            raise ValueError(f"program {name!r} already bound")
        self._programs[name] = jax.jit(
            fn, donate_argnums=(0,) if donate_state else ())
        self._calls[name] = 0
        return self

    def _signature(self, program, inputs):
        from . import profiler

        sig = {"__program__": program}
        for k, v in self._state.items():
            sig[f"state:{k}"] = profiler.sig_array(v)
        for k, v in (inputs or {}).items():
            sig[k] = (profiler.sig_array(v) if hasattr(v, "shape")
                      else profiler.sig_static(v))
        return sig

    def run(self, program, **inputs):
        """Dispatch ``program`` on the current state; rebind the returned
        state; return the outputs.  A call that grew the program's jit
        cache is reported to the compile registry under this executor's
        site (guard raise mode raises AFTER the state is rebound)."""
        from . import profiler

        jfn = self._programs[program]
        before = profiler.jit_cache_size(jfn)
        t0 = _time.perf_counter()
        try:
            outputs, new_state = jfn(self._state, inputs)
        except Exception as e:
            # the stateful dispatch (decode step / KV-cache insert) is an
            # OOM choke point: emit one postmortem naming the top ledger
            # owners before the error surfaces (no-op otherwise)
            profiler.maybe_oom_postmortem(e, f"{self._site}:{program}")
            raise
        wall_ms = (_time.perf_counter() - t0) * 1e3
        missing = set(self._state) - set(new_state)
        if missing:
            raise RuntimeError(
                f"program {program!r} dropped state keys {sorted(missing)} "
                f"— donated buffers are gone; every program must return "
                f"the full state")
        sig = None
        if profiler.jit_cache_size(jfn) > before >= 0:
            sig = self._signature(program, inputs)  # before rebinding
        self._state = dict(new_state)
        self._calls[program] += 1
        if sig is not None:
            self._compiles += 1
            profiler.record_compile(self._site, sig, wall_ms, fn=jfn)
        return outputs

    def is_warm(self, program):
        """True when ``program`` has at least one compiled entry."""
        from . import profiler

        return profiler.jit_cache_size(self._programs[program]) > 0

    def compile_stats(self):
        """{"programs", "entries", "compiles", "calls"} — the generation
        harness diffs this around a traffic run to prove the decode loop
        never compiled after warmup."""
        from . import profiler

        entries = 0
        for fn in self._programs.values():
            n = profiler.jit_cache_size(fn)
            if n > 0:
                entries += n
        return {"programs": len(self._programs), "entries": entries,
                "compiles": self._compiles, "calls": dict(self._calls)}


class Predictor:
    """forward-only executor over (symbol json, params file).

    Parameters
    ----------
    symbol_file : path to ``*-symbol.json`` (or a Symbol instance)
    param_file : path to ``.params``/``.npz`` (or a dict of NDArrays,
        keys optionally ``arg:``/``aux:``-prefixed)
    input_shapes : dict name -> shape of the initially bound signature
    """

    def __init__(self, symbol_file, param_file, input_shapes, dev_type="cpu",
                 dev_id=0):
        import weakref as _weakref

        from . import context as ctx_mod
        from . import profiler

        self._sym, self._arg_store, self._aux_store = load_checkpoint(
            symbol_file, param_file)
        self._ctx = ctx_mod.Context(dev_type, dev_id)
        self._input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        self._exe_cache = {}   # shape signature -> Executor (jit caches ride)
        self._outputs = None
        # device-memory ledger: the shared parameter store is accounted
        # ONCE here (executors share it by object, so their own bound-
        # array accounting is released in _executor_for); freed on
        # close() or GC, whichever first
        self._mem_cell = [sum(
            _nd_store_nbytes(nd)
            for store in (self._arg_store, self._aux_store)
            for nd in store.values())]
        profiler.track_memory("predictor.params", "params").alloc(
            self._mem_cell[0])
        self._mem_finalizer = _weakref.finalize(
            self, _release_predictor_memory, self._mem_cell)
        self._exe = self._executor_for(self._input_shapes)

    def close(self):
        """Release this predictor's share of the device-memory ledger
        (the arrays themselves are freed by GC as usual).  Idempotent;
        also runs at GC via ``weakref.finalize``."""
        self._mem_finalizer()

    @staticmethod
    def _sig(shapes):
        return tuple(sorted((k, tuple(v)) for k, v in shapes.items()))

    def _executor_for(self, shapes):
        """Executor bound for ``shapes``, from the cache when this
        signature was seen before.  A fresh bind allocates ONLY the input
        placeholders — parameters/aux states are the shared store arrays,
        so the device copy made at construction is the only one ever."""
        import jax.numpy as jnp

        from .base import _as_np_dtype
        from .executor import Executor
        from .ndarray.ndarray import NDArray

        sig = self._sig(shapes)
        exe = self._exe_cache.get(sig)
        if exe is not None:
            return exe
        arg_shapes, _, aux_shapes = self._sym.infer_shape(**shapes)
        arg_dtypes, _, aux_dtypes = self._sym.infer_type(
            **{k: tuple(v) for k, v in shapes.items()})

        args = {}
        for name, shp, dt in zip(self._sym.list_arguments(), arg_shapes,
                                 arg_dtypes):
            if name in shapes:
                dtype = _as_np_dtype(dt or "float32")
                args[name] = NDArray(jnp.zeros(tuple(shapes[name]), dtype))
                continue
            nd = self._arg_store.get(name)
            if nd is None:
                # parameter absent from the checkpoint: bind zeros, but
                # STORE them so later binds share the same array
                if shp is None:
                    raise ValueError(
                        f"predictor: cannot infer shape of unbound "
                        f"parameter {name!r}")
                dtype = _as_np_dtype(dt or "float32")
                nd = self._arg_store[name] = NDArray(jnp.zeros(shp, dtype))
                self._mem_account(nd)
            elif shp is not None and tuple(nd.shape) != tuple(shp):
                raise ValueError(
                    f"predictor: parameter {name!r} has shape "
                    f"{tuple(nd.shape)} but the graph needs {tuple(shp)} "
                    f"for inputs {dict(shapes)} — shape-dependent "
                    f"parameters cannot be shared across binds")
            args[name] = nd
        auxs = {}
        for name, shp, dt in zip(self._sym.list_auxiliary_states(),
                                 aux_shapes, aux_dtypes):
            nd = self._aux_store.get(name)
            if nd is None:
                dtype = _as_np_dtype(dt or "float32")
                nd = self._aux_store[name] = NDArray(
                    jnp.zeros(shp if shp is not None else (1,), dtype))
                self._mem_account(nd)
            auxs[name] = nd
        exe = Executor(self._sym, self._ctx, args=args, grad_req="null",
                       aux_states=auxs)
        # compile-registry attribution: a compile triggered by a predictor
        # bind reports as the predictor's, not a bare executor's (the
        # serving tier further overrides via profiler.compile_site)
        exe._compile_site = "predictor.forward"
        # memory attribution: the executor's bound arrays ARE the shared
        # store this predictor already accounted — drop the executor's own
        # ledger row so the bytes are never counted twice
        exe._release_memory()
        self._exe_cache[sig] = exe
        return exe

    def _mem_account(self, nd):
        n = _nd_store_nbytes(nd)
        if n:
            from . import profiler

            self._mem_cell[0] += n
            profiler.track_memory("predictor.params", "params").alloc(n)

    def reshape(self, new_shapes):
        """Rebind for a new input-shape signature, sharing the parameter
        arrays (no device copy).  A signature seen before reuses its
        executor — and therefore its warm jit cache — outright.  Returns
        ``self`` (the c_predict ``MXPredReshape`` contract: the handle
        stays valid, only the bound shapes change)."""
        new_shapes = {k: tuple(v) for k, v in new_shapes.items()}
        unknown = set(new_shapes) - set(self._input_shapes)
        if unknown:
            raise KeyError(f"unknown input(s) {sorted(unknown)!r}; "
                           f"inputs are {sorted(self._input_shapes)}")
        shapes = dict(self._input_shapes)
        shapes.update(new_shapes)
        self._exe = self._executor_for(shapes)
        self._input_shapes = shapes
        self._outputs = None
        return self

    def is_warm(self, shapes=None):
        """True when the given (default: current) signature has a bound
        executor whose forward program is already compiled — i.e. a
        ``forward`` at this signature will not trigger a jit trace.  The
        serving tier's bucket hit/miss accounting reads this."""
        shapes = dict(self._input_shapes) if shapes is None else \
            {k: tuple(v) for k, v in shapes.items()}
        exe = self._exe_cache.get(self._sig(shapes))
        return exe is not None and len(exe._fwd_cache) > 0

    def compile_stats(self):
        """{"executors": bound signatures, "fwd_entries": compiled forward
        programs across them} — the serving harness diffs this around a
        traffic run to prove zero post-warmup recompiles."""
        return {
            "executors": len(self._exe_cache),
            "fwd_entries": sum(len(e._fwd_cache)
                               for e in self._exe_cache.values()),
        }

    # -- c_predict-style surface ----------------------------------------
    def set_input(self, name, value):
        """``MXPredSetInput``."""
        if name not in self._input_shapes:
            raise KeyError(f"unknown input {name!r}")
        self._exe.arg_dict[name][:] = _np.asarray(
            value.asnumpy() if hasattr(value, "asnumpy") else value)

    def forward(self):
        """``MXPredForward`` — runs the compiled program (is_train=False)."""
        self._outputs = self._exe.forward(is_train=False)
        return self

    def get_output(self, index=0):
        """``MXPredGetOutput`` — numpy copy of output ``index``."""
        if self._outputs is None:
            raise RuntimeError("call forward() first")
        return self._outputs[index].asnumpy()

    def num_outputs(self):
        """``MXPredGetOutputShape``-adjacent: how many outputs the bound
        graph produces (the serving tier sizes its per-output unpadding
        spec from this)."""
        return len(self._sym._outputs)

    def get_outputs(self):
        """Numpy copies of ALL outputs of the last ``forward()`` (the
        multi-output serving path; ``get_output`` stays the single-output
        c_predict surface)."""
        if self._outputs is None:
            raise RuntimeError("call forward() first")
        return [o.asnumpy() for o in self._outputs]

    def predict(self, **inputs):
        """Convenience: set all inputs, forward, return output 0."""
        for k, v in inputs.items():
            self.set_input(k, v)
        return self.forward().get_output(0)
