"""``mx.predictor`` — standalone inference API.

Parity: [U:src/c_api/c_predict_api.cc] (``MXPredCreate`` / SetInput /
Forward / GetOutput) — the embedding-oriented predict surface that loads a
``-symbol.json`` + ``.params`` checkpoint and runs forward-only.  Here the
bound program is one ``jax.jit``-compiled XLA executable (donated inputs,
no autograd machinery), the deployment analog of ``Block.export``.
"""
from __future__ import annotations

import json as _json

import numpy as _np

__all__ = ["Predictor"]


class Predictor:
    """forward-only executor over (symbol json, params file).

    Parameters
    ----------
    symbol_file : path to ``*-symbol.json`` (or a Symbol instance)
    param_file : path to ``.params``/``.npz`` (or a dict of NDArrays)
    input_shapes : dict name -> shape
    """

    def __init__(self, symbol_file, param_file, input_shapes, dev_type="cpu",
                 dev_id=0):
        from . import context as ctx_mod
        from . import symbol as sym_mod
        from .ndarray import utils as nd_utils

        if isinstance(symbol_file, str):
            self._sym = sym_mod.load(symbol_file)
        else:
            self._sym = symbol_file
        if isinstance(param_file, str):
            loaded = nd_utils.load(param_file)
        else:
            loaded = param_file
        self._params = {}
        for k, v in loaded.items():
            name = k.split(":", 1)[1] if ":" in k else k
            self._params[name] = v
        self._input_shapes = dict(input_shapes)
        self._ctx = ctx_mod.Context(dev_type, dev_id)
        self._inputs = {k: None for k in input_shapes}
        self._outputs = None
        self._exe = self._bind()

    def _bind(self):
        exe = self._sym.simple_bind(**self._input_shapes)
        for name, arr in self._params.items():
            if name in exe.arg_dict:
                exe.arg_dict[name][:] = arr
            elif name in exe.aux_dict:
                exe.aux_dict[name][:] = arr
        return exe

    # -- c_predict-style surface ----------------------------------------
    def set_input(self, name, value):
        """``MXPredSetInput``."""
        from .ndarray.ndarray import array

        if name not in self._input_shapes:
            raise KeyError(f"unknown input {name!r}")
        self._exe.arg_dict[name][:] = _np.asarray(
            value.asnumpy() if hasattr(value, "asnumpy") else value)

    def forward(self):
        """``MXPredForward`` — runs the compiled program (is_train=False)."""
        self._outputs = self._exe.forward(is_train=False)
        return self

    def get_output(self, index=0):
        """``MXPredGetOutput`` — numpy copy of output ``index``."""
        if self._outputs is None:
            raise RuntimeError("call forward() first")
        return self._outputs[index].asnumpy()

    def predict(self, **inputs):
        """Convenience: set all inputs, forward, return output 0."""
        for k, v in inputs.items():
            self.set_input(k, v)
        return self.forward().get_output(0)
