"""Engine facade.

Parity target: [U:src/engine/] + [U:python/mxnet/engine.py].  The reference's
ThreadedEnginePerDevice (async dataflow scheduler over per-device worker
threads and CUDA streams) is played here by XLA/PJRT's async dispatch: every
op returns a future-backed ``jax.Array`` and XLA orders execution by data
dependence, which is exactly the engine's var-version dependency rule.  What
remains of the engine API:

* ``waitall`` — fence (``Engine::WaitForAll``)
* ``bulk(size)`` — op-bulking hint; XLA fusion subsumes it, kept as a no-op
  scope for script compat
* naive/sync mode — ``set_engine_type('NaiveEngine')`` maps to
  ``jax.disable_jit`` + eager blocking, the reference's ``MXNET_ENGINE_TYPE``
  debug bisection knob
"""
from __future__ import annotations

import contextlib
import os

__all__ = ["waitall", "bulk", "set_bulk_size", "engine_type", "set_engine_type"]

_engine_type = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
_bulk_size = int(os.environ.get("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 15))


def waitall():
    from .ndarray.ndarray import waitall as _w

    _w()


@contextlib.contextmanager
def bulk(size):
    """Bulk-execution scope (parity: ``mx.engine.bulk``).  XLA fuses traced
    regions automatically; this scope is retained for API compatibility."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, size
    try:
        yield
    finally:
        _bulk_size = prev


def set_bulk_size(size):
    global _bulk_size
    prev, _bulk_size = _bulk_size, size
    return prev


def engine_type():
    return _engine_type


def set_engine_type(name):
    """'NaiveEngine' => synchronous, jit-free debug mode."""
    global _engine_type
    import jax

    prev = _engine_type
    _engine_type = name
    if name == "NaiveEngine":
        jax.config.update("jax_disable_jit", True)
    else:
        jax.config.update("jax_disable_jit", False)
    return prev
