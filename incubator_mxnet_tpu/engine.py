"""Execution engine: op-bulking, sync fences, and engine-type selection.

Parity target: [U:src/engine/] + [U:python/mxnet/engine.py].  The reference's
ThreadedEnginePerDevice is an async dataflow scheduler: ops are pushed with
read/write var lists, execute out-of-line on per-device worker threads, and
``MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN`` lets it segment the dependency graph
into *bulks* dispatched as one unit to amortize per-op overhead.  Here the
async half is played by XLA/PJRT (every op returns a future-backed
``jax.Array``; data dependence orders execution), and this module supplies
the other half for real:

* ``bulk(size)`` — **op-bulking scope**.  Eligible eager op calls inside the
  scope are NOT dispatched one by one; they are appended to a per-thread
  micro-graph whose outputs are lightweight :class:`DeferredArray`
  placeholders (shape/dtype known via a cached ``jax.eval_shape``, no
  compute issued).  The whole micro-graph is compiled ONCE per graph shape
  (LRU-cached ``jax.jit``) and executed as a single XLA program when a
  flush trigger fires:

    - the bulk scope exits,
    - the accumulated op count reaches the bulk size cap,
    - a value is demanded (``wait_to_read``/``asnumpy``/``__repr__``/
      ``float()``… — anything that touches a DeferredArray's data),
    - an ineligible call consumes a deferred input (autograd recording,
      tracers, unregistered closures, PRNG-consuming ops, AMP),
    - ``waitall()``.

  Steady-state training loops therefore pay one cached-executable launch
  per ``bulk_size`` eager ops — the engine-parity semantics the old stub
  only documented.  ``MXNET_EAGER_BULK=1`` turns ambient bulking on outside
  explicit scopes (cap = ``MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN``).

* ``waitall`` — fence (``Engine::WaitForAll``): flushes pending bulks, then
  blocks on every local device queue.

* naive/sync mode — ``set_engine_type('NaiveEngine')`` maps to
  ``jax.disable_jit`` + eager blocking dispatch and BYPASSES both the
  level-1 dispatch cache (ops/registry.py) and bulking, the reference's
  ``MXNET_ENGINE_TYPE`` debug-bisection knob.

See docs/eager_dispatch.md for the full dispatch-path decision tree.
"""
from __future__ import annotations

import contextlib
import os
import threading
import weakref
from collections import OrderedDict
from time import perf_counter as _perf

import jax as _jax
import numpy as _np

from . import profiler as _profiler

__all__ = ["waitall", "bulk", "set_bulk_size", "engine_type", "set_engine_type",
           "DeferredArray", "active_queue", "flush_pending", "flush_all",
           "resolve"]

_engine_type = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
_bulk_size = int(os.environ.get("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 15))
_ambient = os.environ.get("MXNET_EAGER_BULK", "0") == "1"
_MAX_FLUSH_JITS = int(os.environ.get("MXNET_EAGER_BULK_CACHE_SIZE", "128"))

_tls = threading.local()

# number of live bulk() scopes across all threads — a one-attr-read
# pre-filter for ndarray.invoke so the no-bulking hot path pays nothing
_bulk_scopes = 0
_scope_lock = threading.Lock()

_JArray = _jax.Array
_JTracer = _jax.core.Tracer
# exact-type scalar set mirroring registry._SCALAR_TYPES: scalars are the
# second-most-common enqueue argument after pending deferreds, and an exact
# type test dodges the jax.Array ABC __instancecheck__ in _wire_value
_SCALAR_TYPES = frozenset((bool, int, float, complex, str, type(None)))


def is_naive():
    return _engine_type == "NaiveEngine"


# ---------------------------------------------------------------------------
# Deferred arrays
# ---------------------------------------------------------------------------


class DeferredArray:
    """Placeholder for one output of a pending bulked op.

    Knows its aval (shape/dtype) without any compute; any access to the
    actual data (``__array__``, ``block_until_ready``, or attribute
    delegation) flushes the owning micro-graph first.  ``ndarray.invoke``
    swaps the concrete array into the owning NDArray on first touch, so the
    indirection disappears after resolution.
    """

    __slots__ = ("_queue", "_aval", "_concrete", "_src", "_tok",
                 "__weakref__")

    def __init__(self, queue, aval, src=None, tok=None):
        self._queue = queue
        self._aval = aval
        self._concrete = None
        self._src = src  # (op index, output index) within the pending graph
        self._tok = tok  # precomputed (shape, dtype, weak_type) key token

    # -- lazy metadata (no flush) ------------------------------------
    @property
    def shape(self):
        return tuple(self._aval.shape)

    @property
    def dtype(self):
        return self._aval.dtype

    @property
    def ndim(self):
        return len(self._aval.shape)

    @property
    def size(self):
        s = 1
        for d in self._aval.shape:
            s *= d
        return s

    @property
    def aval(self):
        return self._aval

    # -- forcing ------------------------------------------------------
    def _resolve(self):
        if self._concrete is None:
            self._queue.flush()
            if self._concrete is None:
                # the flush that should have produced this value failed (its
                # exception surfaced to whoever triggered it) and the queue
                # is already drained — fail loudly instead of returning None
                raise RuntimeError(
                    "bulked op failed: this DeferredArray belongs to a "
                    "micro-graph whose flush raised; its value was never "
                    "computed")
        return self._concrete

    def block_until_ready(self):
        return self._resolve().block_until_ready()

    def __array__(self, dtype=None, copy=None):
        import numpy as np

        a = np.asarray(self._resolve())
        return a.astype(dtype) if dtype is not None else a

    def __getattr__(self, name):
        # anything beyond the lazy surface delegates to the concrete array
        # (forcing a flush): .at, .astype, .devices, arithmetic helpers …
        if name.startswith("__"):
            raise AttributeError(name)
        return getattr(self._resolve(), name)

    def __repr__(self):
        if self._concrete is not None:
            return repr(self._concrete)
        return f"<DeferredArray {self.shape} {self.dtype} pending>"


def _forward_dunder(name):
    # implicit special-method lookup skips __getattr__ (the interpreter
    # resolves dunders on the type), so each one needs a real class attr;
    # deferred operands are resolved directly instead of round-tripping
    # through __array__ (which would detour via host numpy)
    def fwd(self, *args):
        args = tuple(a._resolve() if type(a) is DeferredArray else a
                     for a in args)
        return getattr(self._resolve(), name)(*args)
    fwd.__name__ = name
    fwd.__qualname__ = f"DeferredArray.{name}"
    return fwd


# container/conversion/operator protocol for direct consumers of
# NDArray._data (sparse kernels, autograd grad accumulation, executor copy
# paths) that index or combine the raw array without going through invoke().
# __eq__/__ne__ are installed by setattr AFTER class creation deliberately:
# an in-class __eq__ would null out __hash__, and the engine keys pending
# deferreds by identity (weakrefs in _PendingOp.outs).
for _nm in (
    "__getitem__", "__len__", "__iter__", "__contains__",
    "__bool__", "__float__", "__int__", "__index__", "__complex__",
    "__neg__", "__pos__", "__abs__", "__invert__",
    "__add__", "__radd__", "__sub__", "__rsub__", "__mul__", "__rmul__",
    "__truediv__", "__rtruediv__", "__floordiv__", "__rfloordiv__",
    "__mod__", "__rmod__", "__pow__", "__rpow__",
    "__matmul__", "__rmatmul__", "__divmod__", "__rdivmod__",
    "__and__", "__rand__", "__or__", "__ror__", "__xor__", "__rxor__",
    "__lshift__", "__rlshift__", "__rshift__", "__rrshift__",
    "__lt__", "__le__", "__gt__", "__ge__", "__eq__", "__ne__",
):
    setattr(DeferredArray, _nm, _forward_dunder(_nm))
del _nm


def resolve(x):
    """Concrete jax.Array for ``x`` (flushing if it is a pending deferred)."""
    if isinstance(x, DeferredArray):
        return x._resolve()
    return x


# ---------------------------------------------------------------------------
# Bulk queue
# ---------------------------------------------------------------------------

_flush_jits: OrderedDict = OrderedDict()  # graph key -> jitted program
_aval_cache: dict = {}                    # per-op key -> output avals
_flush_lock = threading.Lock()
# every live _BulkQueue (one per thread that ever bulked), so waitall() can
# fence other threads' pending micro-graphs; guarded by _scope_lock
_all_queues: weakref.WeakSet = weakref.WeakSet()

_registry_mod = None


def _registry():
    """ops.registry, imported on first bulked enqueue (module-level import
    would drag the whole ops package in before engine config is read)."""
    global _registry_mod
    if _registry_mod is None:
        from .ops import registry as _r

        _registry_mod = _r
    return _registry_mod


class _PendingOp:
    __slots__ = ("fn", "wiring", "static_kw", "dyn_kw", "n_out", "key",
                 "outs", "avals")

    def __init__(self, fn, wiring, static_kw, dyn_kw, n_out, key):
        self.fn = fn
        self.wiring = wiring        # per positional arg: ('d',op,out)|('c',slot)|('s',value)
        self.static_kw = static_kw  # dict of baked kwargs
        self.dyn_kw = dyn_kw        # list of (name, ('c',slot)|('d',op,out))
        self.n_out = n_out
        self.key = key              # hashable token incl. fn + wiring + avals
        self.outs = None            # weakref per output DeferredArray
        self.avals = None           # output avals (outlive the deferreds)


def _spec_of(ops):
    """Graph spec reused by the jitted program — holds no DeferredArray
    references, so cached programs don't pin flushed buffers."""
    return tuple((op.fn, op.wiring, tuple(sorted(op.static_kw.items())),
                  tuple(op.dyn_kw), op.n_out) for op in ops)


def _run_spec(spec, consts, live):
    """Execute the graph, returning only the ``live``-masked outputs.

    Every op still runs in trace order (they are pure registered fns), but
    only outputs whose DeferredArray is still referenced are returned — so
    under jit XLA dead-code-eliminates the intermediates and the 64-op
    chain compiles to one fused kernel with one output buffer instead of
    materializing all 64."""
    env = []
    for fn, wiring, static_kw, dyn_kw, n_out in spec:
        args = []
        for w in wiring:
            if w[0] == "d":
                args.append(env[w[1]][w[2]])
            elif w[0] == "c":
                args.append(consts[w[1]])
            else:
                args.append(w[1])
        kw = dict(static_kw)
        for name, src in dyn_kw:
            kw[name] = env[src[1]][src[2]] if src[0] == "d" else consts[src[1]]
        out = fn(*args, **kw)
        env.append(out if isinstance(out, tuple) else (out,))
    return [o for outs, lv in zip(env, live) for o, alive in zip(outs, lv)
            if alive]


def _program(spec, live):
    def run(consts):
        return _run_spec(spec, consts, live)
    return run


class _BulkQueue:
    """Per-thread micro-graph of deferred eager ops."""

    def __init__(self):
        self.ops = []
        self.consts = []
        self._lock = threading.RLock()
        self._t0_accum = None  # first-enqueue time (bulk.accumulate span)

    # -- classification helpers --------------------------------------
    def _wire_value(self, v, jax, key_parts):
        """Wiring + key token for one dynamic input value, or None if the
        value can't participate."""
        if isinstance(v, DeferredArray):
            if v._concrete is not None:
                v = v._concrete  # fall through to the concrete case
            elif v._queue is self and v._src is not None:
                # _src is the ("d", i, j) wiring tuple and _tok the aval
                # token, both precomputed at creation: the hot chain case
                # (op output feeding the next op) appends two existing
                # refs instead of rebuilding tuples from property reads.
                # The aval token matters: the per-op _aval_cache key must
                # stand alone, and (i, j) alone says nothing about the
                # upstream output's shape in a different graph prefix.
                src = v._src
                key_parts.append((src, v._tok))
                return src
            else:
                v = v._resolve()  # cross-thread deferred: force it
        tv = type(v)
        if tv in _SCALAR_TYPES:
            # STATIC, keyed by type+value — matching level 1's
            # _classify_args: shipping scalars as jit operands costs one
            # host->device buffer commit per scalar per flush (~64 puts for
            # a 64-op chain, dwarfing the whole dispatch win); distinct
            # literals recompile, bounded by the _flush_jits LRU
            if (tv is float or tv is complex) and v == 0:
                key_parts.append(("s", tv, v, str(v)))  # -0.0 vs 0.0
            else:
                key_parts.append(("s", tv, v))
            return ("s", v)
        if isinstance(v, _JTracer):
            return None
        if isinstance(v, _JArray):
            self.consts.append(v)
            key_parts.append(("a", v.shape, v.dtype,
                              v.aval.weak_type, v.sharding))
            return ("c", len(self.consts) - 1)
        if isinstance(v, _np.ndarray):
            self.consts.append(v)
            key_parts.append(("n", v.shape, v.dtype.str))
            return ("c", len(self.consts) - 1)
        if isinstance(v, (bool, int, float, complex, str, _np.generic)):
            # scalar SUBCLASS (IntEnum, np.float64 — a float subclass …) or
            # numpy scalar: the shared level-1/level-2 keying rule
            key_parts.append(("s", _registry()._scalar_token(type(v), v)))
            return ("s", v)
        try:
            key_parts.append(("s", _registry()._static_token(v)))
        except TypeError:
            return None
        return ("s", v)

    def enqueue(self, fn, raw_args, kwargs):
        """Try to defer ``fn(*raw_args, **kwargs)``.  Returns a tuple of
        DeferredArray outputs, or None when the call must run eagerly."""
        reg = _registry_mod
        if reg is None:
            reg = _registry()
        prng = reg._PRNG_FNS.get(fn)
        if prng is None:
            if fn not in reg._CACHEABLE_FNS:
                return None
            prng = reg._PRNG_FNS[fn] = reg._reads_ambient_prng(fn)
        if prng and kwargs.get("key") is None:
            return None
        jax = _jax

        # Resolve foreign (other-queue) and poisoned deferreds BEFORE taking
        # our lock: v._resolve() flushes the OWNING queue under ITS lock, and
        # doing that while holding ours is an ABBA deadlock when two threads
        # consume each other's pending outputs.  After this scan, everything
        # _wire_value sees under the lock is own-queue-pending or concrete.
        for a in raw_args:
            if type(a) is DeferredArray and a._concrete is None \
                    and (a._queue is not self or a._src is None):
                a._resolve()
        if kwargs:
            for v in kwargs.values():
                if type(v) is DeferredArray and v._concrete is None \
                        and (v._queue is not self or v._src is None):
                    v._resolve()

        with self._lock:
            n_consts0 = len(self.consts)
            key_parts = [fn]  # head: fn identity (never a tuple, no collision)
            wiring = []
            for a in raw_args:
                # inlined _wire_value fast cases — a pending deferred from
                # this queue (op output feeding the next op, the shape of
                # every chain) costs two ref appends, and a python scalar
                # (the other operand of nearly every chain op) one exact
                # type test — no function call, no ABC isinstance cascade
                ta = type(a)
                if ta is DeferredArray:
                    if a._concrete is None and a._queue is self \
                            and a._src is not None:
                        key_parts.append((a._src, a._tok))
                        wiring.append(a._src)
                        continue
                elif ta in _SCALAR_TYPES:
                    if (ta is float or ta is complex) and a == 0:
                        # -0.0 == 0.0 and they hash alike, but baking the
                        # wrong zero flips signs (x / -0.0); str() splits them
                        key_parts.append(("s", ta, a, str(a)))
                    else:
                        key_parts.append(("s", ta, a))
                    wiring.append(("s", a))
                    continue
                w = self._wire_value(a, jax, key_parts)
                if w is None:
                    del self.consts[n_consts0:]
                    return None
                wiring.append(w)
            static_kw, dyn_kw = {}, []
            if kwargs:
                for k in sorted(kwargs):
                    v = kwargs[k]
                    if isinstance(v, (_JArray, DeferredArray)) \
                            and not isinstance(v, _JTracer):
                        key_parts.append(("kw", k))
                        w = self._wire_value(v, jax, key_parts)
                        if w is None or w[0] == "s":
                            del self.consts[n_consts0:]
                            return None
                        dyn_kw.append((k, w))
                    else:
                        try:
                            key_parts.append(("ks", k, reg._static_token(v)))
                        except TypeError:
                            del self.consts[n_consts0:]
                            return None
                        static_kw[k] = v

            op_key = tuple(key_parts)
            inferred = self._infer_avals(fn, wiring, static_kw, dyn_kw, op_key, jax)
            if inferred is None:
                del self.consts[n_consts0:]
                return None
            avals, is_tuple, toks = inferred

            op = _PendingOp(fn, tuple(wiring), static_kw, dyn_kw,
                            len(avals), op_key)
            i = len(self.ops)
            if i == 0 and _profiler._active:
                self._t0_accum = _perf()  # accumulate-phase span start
            # the queue holds only WEAK refs to its outputs: a deferred the
            # caller has dropped by flush time is provably unreadable, so
            # the flush program need not return it (XLA DCEs the buffer)
            if len(avals) == 1:  # single output: skip the genexpr machinery
                d = DeferredArray(self, avals[0], ("d", i, 0), toks[0])
                outs = (d,)
                op.outs = (weakref.ref(d),)
            else:
                outs = tuple(DeferredArray(self, av, ("d", i, j), tok)
                             for j, (av, tok) in enumerate(zip(avals, toks)))
                op.outs = tuple(weakref.ref(o) for o in outs)
            op.avals = avals
            self.ops.append(op)
            # effective cap: the per-thread scope cap when inside bulk(),
            # else the global ambient cap (enqueue only runs on the owner
            # thread, so _tls here is the right thread's state)
            cap = (_tls.bulk_cap if getattr(_tls, "bulk_depth", 0) > 0
                   else _bulk_size)
            full = i + 1 >= cap
        if full:
            self.flush()
        return outs, is_tuple

    def _infer_avals(self, fn, wiring, static_kw, dyn_kw, op_key, jax):
        """Output avals via a cached eval_shape keyed like the flush jit —
        steady-state enqueues are a dict hit, no tracing."""
        cached = _aval_cache.get(op_key)
        if cached is not None:
            return cached

        def arg_aval(w):
            if w[0] == "d":
                return self.ops[w[1]].avals[w[2]]
            if w[0] == "c":
                v = self.consts[w[1]]
                if isinstance(v, jax.Array):
                    return jax.ShapeDtypeStruct(v.shape, v.dtype,
                                                weak_type=bool(v.aval.weak_type))
                return v  # numpy / python scalar: eval_shape takes it as-is
            return None

        dyn_avals = []
        for w in wiring:
            if w[0] != "s":
                dyn_avals.append(arg_aval(w))
        kw_avals = [arg_aval(w) for _, w in dyn_kw]

        def probe(dyn, kw_vals):
            it = iter(dyn)
            args = [next(it) if w[0] != "s" else w[1] for w in wiring]
            kw = dict(static_kw)
            kw.update((name, v) for (name, _), v in zip(dyn_kw, kw_vals))
            return fn(*args, **kw)

        try:
            res = jax.eval_shape(probe, tuple(dyn_avals), tuple(kw_avals))
        except Exception:
            return None
        is_tuple = isinstance(res, tuple)
        avals = res if is_tuple else (res,)
        if not all(hasattr(a, "shape") and hasattr(a, "dtype") for a in avals):
            return None  # exotic output structure: stay on the raw path
        if len(_aval_cache) > 8192:
            _aval_cache.clear()
        # key tokens cached alongside so enqueue hands them to the output
        # DeferredArrays for free (downstream wiring appends them as refs)
        toks = tuple((tuple(a.shape), a.dtype,
                      bool(getattr(a, "weak_type", False))) for a in avals)
        inferred = (tuple(avals), is_tuple, toks)
        _aval_cache[op_key] = inferred
        return inferred

    def flush(self):
        jax = _jax
        profiler = _profiler

        # The queue lock is held through execution AND result assignment:
        # the owner thread's enqueue can never observe a half-flushed queue
        # (it would wire ('d', i, j) indices into a cleared ops list), and a
        # cross-thread _resolve blocks here until the concrete it needs is
        # assigned.  Lock order is queue lock -> _flush_lock, and no code
        # path touches a FOREIGN queue's lock while holding its own
        # (enqueue resolves foreign deferreds before locking), so no cycle
        # is possible.
        with self._lock:
            if not self.ops:
                return
            t_flush = _perf() if profiler._active else None
            t_accum, self._t0_accum = self._t0_accum, None
            ops, consts = self.ops, self.consts
            self.ops, self.consts = [], []
            # liveness snapshot: dereffed again at assignment, so a deferred
            # dying between here and there just wastes one program output
            live = tuple(tuple(wr() is not None for wr in op.outs)
                         for op in ops)
            graph_key = (tuple(op.key for op in ops), live)
            with _flush_lock:
                jitted = _flush_jits.get(graph_key)
                compiled_now = jitted is None
                if compiled_now:
                    # spec built only on compile (and fallback below): the
                    # steady-state flush is just this dict hit + one pjit call
                    jitted = jax.jit(_program(_spec_of(ops), live))
                    _flush_jits[graph_key] = jitted
                    while len(_flush_jits) > _MAX_FLUSH_JITS:
                        _flush_jits.popitem(last=False)
                else:
                    _flush_jits.move_to_end(graph_key)
            compile_ms = None
            t_c = _perf() if compiled_now else None
            try:
                t_ex = t_flush and _perf()
                # jax.jit is lazy: a fresh graph traces+compiles inside its
                # first call, so that call is the "trace" phase, not execute
                results = jitted(consts)
                if compiled_now:
                    compile_ms = (_perf() - t_c) * 1e3
                if t_ex:
                    profiler.record_span(
                        "bulk.trace" if compiled_now else "bulk.execute",
                        "bulk", t_ex)
            except Exception:
                # jit artifact or genuine user error: re-run the graph
                # eagerly; genuine errors surface with eager semantics
                profiler.incr("bulk_fallback")
                with _flush_lock:
                    _flush_jits.pop(graph_key, None)
                try:
                    results = _run_spec(_spec_of(ops), consts, live)
                except Exception as flush_err:
                    # the flush is lost (ops already drained): poison the
                    # surviving outputs so a later enqueue can't wire their
                    # stale ('d', i, j) indices into a fresh graph — reads
                    # hit _resolve()'s RuntimeError guard instead
                    for op in ops:
                        for wr in op.outs:
                            d = wr()
                            if d is not None:
                                d._src = None
                    # bulk flush is an OOM choke point: a device allocation
                    # failure gets one postmortem naming the ledger's top
                    # owners before it surfaces (no-op otherwise)
                    profiler.maybe_oom_postmortem(flush_err, "engine.flush")
                    raise
            profiler.incr("bulk_flush")
            profiler.incr("bulk_ops_flushed", len(ops))
            if t_flush is not None:
                # the accumulate phase (first enqueue -> flush trigger)
                # travels as an arg, NOT its own span: it can straddle
                # unrelated spans on this thread (ambient bulking flushes
                # from inside Trainer.step), and a partially-overlapping
                # B/E interval would break chrome-trace duration nesting
                args = {"ops": len(ops)}
                if t_accum is not None:
                    args["accum_ms"] = round((t_flush - t_accum) * 1e3, 3)
                profiler.record_span("bulk.flush", "bulk", t_flush,
                                     args=args)
            k = 0
            for op, lv in zip(ops, live):
                for wr, alive in zip(op.outs, lv):
                    if alive:
                        d = wr()
                        if d is not None:
                            d._concrete = results[k]
                        k += 1
            if compile_ms is not None:
                # AFTER result wiring: a guard in raise mode must not
                # leave the flushed deferreds unresolved.  Micro-graphs
                # have no named arguments, so the signature is coarse —
                # op mix + a graph digest (drift shows as a new graph).
                # The digest covers only process-stable parts (op names,
                # wiring, statics, liveness — NOT fn reprs or Python
                # hash(), both of which differ across ranks/runs), so
                # merged multi-rank compile reports see ONE graph id.
                import zlib

                mix = {}
                stable = []
                for op, lv in zip(ops, live):
                    n = getattr(op.fn, "__name__", "?")
                    mix[n] = mix.get(n, 0) + 1
                    stable.append((n, op.wiring,
                                   tuple(sorted(op.static_kw.items())),
                                   tuple(op.dyn_kw), lv,
                                   tuple((tuple(a.shape), str(a.dtype))
                                         for a in (op.avals or ()))))
                digest = zlib.crc32(repr(stable).encode())
                profiler.record_compile("engine.bulk", {
                    "__program__": "bulk",
                    "ops": {"k": "static", "value": str(len(ops))},
                    "graph": {"k": "static", "value": f"{digest:08x}"},
                    "op_mix": {"k": "static", "value": ",".join(
                        f"{n}x{c}" for n, c in sorted(mix.items()))[:120]},
                }, compile_ms)


def active_queue():
    """This thread's bulk queue when eager ops should accumulate, else None.
    One merged check for ndarray.invoke (which pre-filters on
    ``_bulk_scopes``/``_ambient`` so the no-bulking hot path never gets
    here): engine type, scope depth, cap, and the TLS queue in one call.
    The cap is per-thread inside explicit ``bulk()`` scopes (concurrent
    scopes on different threads must not clobber each other) and the global
    ``set_bulk_size`` value in ambient mode."""
    if _engine_type == "NaiveEngine":
        return None
    if getattr(_tls, "bulk_depth", 0) > 0:
        cap = _tls.bulk_cap
    elif _ambient:
        cap = _bulk_size
    else:
        return None
    if cap <= 1:
        return None
    q = getattr(_tls, "queue", None)
    if q is None:
        q = _tls.queue = _BulkQueue()
        with _scope_lock:
            _all_queues.add(q)
    return q


def flush_pending():
    """Flush this thread's pending bulk (sync points, recording starts)."""
    q = getattr(_tls, "queue", None)
    if q is not None:
        q.flush()


def flush_all():
    """Flush EVERY thread's pending bulk — the ``waitall`` fence and global
    semantic flips (``set_engine_type``) must not leave another thread's
    deferred micro-graph undispatched."""
    with _scope_lock:
        queues = list(_all_queues)
    for q in queues:
        q.flush()


# ---------------------------------------------------------------------------
# Public engine API
# ---------------------------------------------------------------------------


def waitall():
    from .ndarray.ndarray import waitall as _w

    _w()  # its first act is flush_all(): every thread's bulk dispatches


@contextlib.contextmanager
def bulk(size):
    """Bulk-execution scope (parity: ``mx.engine.bulk``): inside the scope
    eligible eager ops accumulate into a micro-graph flushed as ONE compiled
    program at scope exit, at the ``size`` cap, or at any read of a pending
    value.  ``size <= 1`` makes the scope a no-op.  The cap is THREAD-LOCAL:
    concurrent scopes on other threads keep their own caps."""
    global _bulk_scopes
    prev_cap = getattr(_tls, "bulk_cap", 0)
    _tls.bulk_cap = int(size)
    _tls.bulk_depth = getattr(_tls, "bulk_depth", 0) + 1
    with _scope_lock:
        _bulk_scopes += 1
    try:
        yield
    finally:
        with _scope_lock:
            _bulk_scopes -= 1
        _tls.bulk_depth -= 1
        flush_pending()
        _tls.bulk_cap = prev_cap


def set_bulk_size(size):
    """Set the AMBIENT bulk cap (the flush threshold under
    ``MXNET_EAGER_BULK=1``; explicit ``bulk(size)`` scopes carry their own
    per-thread cap); returns the previous value."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


def engine_type():
    return _engine_type


def set_engine_type(name):
    """'NaiveEngine' => synchronous, jit-free debug mode: disables jax jit,
    the dispatch cache, and op-bulking in one switch."""
    global _engine_type
    import jax

    flush_all()
    prev = _engine_type
    _engine_type = name
    if name == "NaiveEngine":
        jax.config.update("jax_disable_jit", True)
    else:
        jax.config.update("jax_disable_jit", False)
    return prev
