"""Evaluation metrics (parity: [U:python/mxnet/metric.py]).

Same registry/EvalMetric design; update() accepts NDArrays or numpy and does
host-side accumulation (metrics are cheap relative to the jitted step; a
fully-jitted metric path lives in gluon.contrib.estimator for fused loops).
"""
from __future__ import annotations

import numpy as _np

from .ndarray.ndarray import NDArray

__all__ = [
    "EvalMetric",
    "Accuracy",
    "TopKAccuracy",
    "F1",
    "MCC",
    "PCC",
    "MAE",
    "MSE",
    "RMSE",
    "CrossEntropy",
    "NegativeLogLikelihood",
    "Perplexity",
    "PearsonCorrelation",
    "Loss",
    "CompositeEvalMetric",
    "CustomMetric",
    "create",
    "np",
]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    name = metric.lower()
    aliases = {"acc": "accuracy", "ce": "crossentropy", "nll_loss": "negativeloglikelihood",
               "top_k_accuracy": "topkaccuracy", "top_k_acc": "topkaccuracy"}
    name = aliases.get(name, name)
    return _REGISTRY[name](*args, **kwargs)


def _to_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        lab = list(label.values()) if self.label_names is None else [label[n] for n in self.label_names]
        prd = list(pred.values()) if self.output_names is None else [pred[n] for n in self.output_names]
        self.update(lab, prd)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_np(pred)
            label = _to_np(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype("int32").reshape(-1)
            label = label.astype("int32").reshape(-1)
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None, label_names=None):
        super().__init__(f"{name}_{top_k}", output_names, label_names)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_np(pred)
            label = _to_np(label).astype("int32").reshape(-1)
            topk = _np.argsort(-pred, axis=-1)[:, : self.top_k]
            self.sum_metric += float((topk == label[:, None]).any(axis=1).sum())
            self.num_inst += len(label)


class _BinaryStats(EvalMetric):
    """Shared binary confusion-matrix accumulation (F1/MCC base).  Labels
    must be binary — multi-class input raises, matching the reference."""

    def __init__(self, name, output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.reset_stats()

    def reset_stats(self):
        self.tp = self.fp = self.fn = self.tn = 0.0

    def reset(self):
        super().reset()
        if hasattr(self, "tp"):
            self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_np(pred)
            label = _to_np(label).reshape(-1).astype("int32")
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.reshape(-1).astype("int32")
            if ((label < 0) | (label > 1)).any() or ((pred < 0) | (pred > 1)).any():
                raise ValueError(
                    f"{type(self).__name__} requires binary labels/predictions")
            self.tp += float(((pred == 1) & (label == 1)).sum())
            self.fp += float(((pred == 1) & (label == 0)).sum())
            self.fn += float(((pred == 0) & (label == 1)).sum())
            self.tn += float(((pred == 0) & (label == 0)).sum())
            self.num_inst += 1


@register
class F1(_BinaryStats):
    def __init__(self, name="f1", output_names=None, label_names=None, average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average

    def get(self):
        prec = self.tp / max(self.tp + self.fp, 1e-12)
        rec = self.tp / max(self.tp + self.fn, 1e-12)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return (self.name, f1 if self.num_inst else float("nan"))


@register
class MCC(_BinaryStats):
    """Matthews correlation coefficient for binary classification (parity:
    ``mx.metric.MCC``): (tp·tn − fp·fn) / √((tp+fp)(tp+fn)(tn+fp)(tn+fn));
    0 when any denominator factor is 0, the reference convention."""

    def __init__(self, name="mcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        if not self.num_inst:
            return (self.name, float("nan"))
        denom = ((self.tp + self.fp) * (self.tp + self.fn)
                 * (self.tn + self.fp) * (self.tn + self.fn))
        if denom == 0:
            return (self.name, 0.0)
        return (self.name,
                (self.tp * self.tn - self.fp * self.fn) / _np.sqrt(denom))


@register
class PCC(EvalMetric):
    """Multiclass Pearson correlation coefficient over a running k x k
    confusion matrix (parity: ``mx.metric.PCC`` — the R_k statistic;
    reduces to MCC for binary problems)."""

    def __init__(self, name="pcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def reset(self):
        super().reset()
        self._cm = _np.zeros((0, 0), dtype=_np.float64)

    def _grow(self, k):
        if k > self._cm.shape[0]:
            cm = _np.zeros((k, k), dtype=_np.float64)
            old = self._cm.shape[0]
            cm[:old, :old] = self._cm
            self._cm = cm

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_np(label), _to_np(pred)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(-1)
            pred = pred.reshape(-1)
            if _np.issubdtype(pred.dtype, _np.floating):
                if pred.size and 0.0 <= pred.min() and pred.max() <= 1.0:
                    pred = (pred >= 0.5)  # binary probabilities
                else:
                    pred = _np.rint(pred)
            label = label.reshape(-1).astype(_np.int64)
            pred = pred.astype(_np.int64)
            if label.size and (label.min() < 0 or pred.min() < 0):
                raise ValueError(
                    "PCC: negative class index (mask out ignore labels "
                    "before updating)")
            k = int(max(label.max(initial=0), pred.max(initial=0))) + 1
            self._grow(k)
            _np.add.at(self._cm, (label, pred), 1.0)
            self.num_inst += label.size

    def get(self):
        if not self.num_inst:
            return (self.name, float("nan"))
        cm = self._cm
        s = cm.sum()
        c = _np.trace(cm)
        t = cm.sum(axis=1)  # true-class counts
        p = cm.sum(axis=0)  # predicted-class counts
        cov_yy = s * s - (p * p).sum()
        cov_xx = s * s - (t * t).sum()
        if cov_yy == 0 or cov_xx == 0:
            return (self.name, 0.0)
        return (self.name, float((c * s - (t * p).sum())
                                 / _np.sqrt(cov_xx * cov_yy)))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += float(_np.abs(label.reshape(pred.shape) - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_np(label), _to_np(pred)
            self.sum_metric += float(((label.reshape(pred.shape) - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        EvalMetric.__init__(self, name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(_np.sqrt(self.sum_metric / self.num_inst)))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).astype("int32").reshape(-1)
            pred = _to_np(pred)
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None, label_names=None):
        EvalMetric.__init__(self, name, output_names, label_names)
        self.eps = eps


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", output_names=None, label_names=None):
        EvalMetric.__init__(self, name, output_names, label_names)
        self.eps = 1e-12
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_np(label).astype("int32").reshape(-1)
            pred = _to_np(pred).reshape(label.shape[0], -1)
            prob = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                prob = _np.where(ignore, 1.0, prob)
                self.num_inst += int((~ignore).sum())
            else:
                self.num_inst += label.shape[0]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, float(_np.exp(self.sum_metric / self.num_inst)))


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_np(label).reshape(-1), _to_np(pred).reshape(-1)
            self.sum_metric += float(_np.corrcoef(label, pred)[0, 1])
            self.num_inst += 1


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = _to_np(pred)
            self.sum_metric += float(loss.sum())
            self.num_inst += loss.size


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(n if isinstance(n, list) else [n])
            values.extend(v if isinstance(v, list) else [v])
        return (names, values)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, output_names=None, label_names=None):
        super().__init__(f"custom({name})", output_names, label_names)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            val = self._feval(_to_np(label), _to_np(pred))
            if isinstance(val, tuple):
                s, n = val
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += val
                self.num_inst += 1


np = CustomMetric  # legacy alias: mx.metric.np wraps a numpy feval
